"""Node agent: the per-node daemon (raylet equivalent).

Equivalent role to the reference's raylet
(reference: src/ray/raylet/node_manager.h:125, worker_pool.h:104,
local_object_manager.cc) plus the plasma store process (the StoreCore
runs inside this agent's event loop — one fewer process hop than the
reference, same shared-memory data path).

Responsibilities:
  - hosts the shared-memory object store (store_* RPCs serve the
    PlasmaClient protocol in object_store.py)
  - worker pool: forks `worker_main` processes, tracks registration,
    reaps deaths and reports them to the head
    (reference: worker_pool.h PopWorker / StartWorkerProcess)
  - lease protocol: request_lease grants a worker + resources, queues
    FIFO-with-resources when full, spills back to other nodes per the
    hybrid policy (reference: node_manager.h:520 HandleRequestWorkerLease,
    scheduling/policy/hybrid_scheduling_policy.h)
  - object transfer: pull-based chunked fetch from peer agents
    (reference: object_manager.h pull/push managers)
  - heartbeats resource availability to the head; the reply carries the
    cluster view used for spillback decisions (reference: ray_syncer)
"""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private import fault_injection, memory_monitor
from ray_tpu._private.config import config
from ray_tpu._private.errors import RuntimeEnvSetupError
from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.log_monitor import LogMonitor
from ray_tpu._private.object_store import StoreCore
from ray_tpu._private.profiling import IntrospectionRpcMixin, loop_lag_probe
from ray_tpu._private.object_transfer import (ObjectTransferClient,
                                              ObjectTransferServer,
                                              TransferError, dest_view)
from ray_tpu._private.resources import NodeResources, ResourceSet
from ray_tpu._private.rpc import RpcClient, RpcHost, RpcServer
from ray_tpu._private.scheduler import LocalScheduler, pick_node
from ray_tpu._private.task_spec import NORMAL_TASK, TaskSpec


class _Worker:
    __slots__ = ("worker_id", "pid", "proc", "port", "ready", "lease_id",
                 "started_at", "env_key", "idle_since", "iclient",
                 "pinned", "saving")

    def __init__(self, worker_id: str, proc: subprocess.Popen,
                 env_key: str = ""):
        self.worker_id = worker_id
        self.proc = proc
        self.pid = proc.pid
        self.port: int = 0
        self.ready = asyncio.Event()
        self.lease_id: Optional[str] = None
        self.started_at = time.monotonic()
        # OOM victim-policy flags, pushed by the worker itself
        # (worker_flags oneway): running a pinned __rt_dag_* loop /
        # mid-__rt_save__ snapshot — both are last-resort victims
        self.pinned = False
        self.saving = False
        # pooled introspection client (stacks/profile/memory fan-outs):
        # the periodic memory scan would otherwise dial a fresh TCP
        # connection per worker per scan, forever
        self.iclient: Optional["RpcClient"] = None
        # workers are pooled per runtime-env identity: an env-X lease
        # never reuses an env-Y worker (reference: worker_pool.h keys
        # idle workers by runtime env hash)
        self.env_key = env_key
        self.idle_since = time.monotonic()


def _is_hard_strategy(strategy: Dict[str, Any]) -> bool:
    """Strategies pinned to specific existing nodes — unsatisfiable by
    scale-up, so infeasibility is terminal (never parked)."""
    stype = (strategy or {}).get("type", "")
    return (stype == "node_label"
            or (stype == "node_affinity" and not strategy.get("soft")))


class _Lease:
    __slots__ = ("lease_id", "worker", "resources", "bundle_key", "seq",
                 "tpu_chips", "blocked", "donated", "owner_conn",
                 "owner_id", "owner_addr", "retriable", "fid", "task_name")

    def __init__(self, lease_id: str, worker: _Worker, resources: ResourceSet,
                 bundle_key: str = "", seq: int = 0, owner_conn=None,
                 owner_id: str = "", owner_addr=None, retriable: bool = True,
                 fid: str = "", task_name: str = ""):
        self.lease_id = lease_id
        self.worker = worker
        self.resources = resources
        self.bundle_key = bundle_key
        self.seq = seq  # grant order; the OOM policy kills newest first
        self.tpu_chips: List[int] = []  # chip indices assigned to this lease
        # the connection the grant went out on — lets the agent push a
        # reclaim request to the owner when new demand queues behind
        # idle-lingering leases (reference: the raylet's lease revocation
        # via ReleaseUnusedWorkers)
        self.owner_conn = owner_conn
        # the granting spec's caller_id: a reconnected owner's next
        # lease request re-binds its surviving leases to the new
        # connection before the orphan-reap grace expires
        self.owner_id = owner_id
        # the owner's own RPC server address (spec.owner_addr): the
        # orphan reap pings it before killing anything, so a transient
        # control-connection drop from a LIVE owner never costs workers
        self.owner_addr = tuple(owner_addr) if owner_addr else None
        # True while the leased worker is blocked in a get(): its
        # fungible resources are returned to the pool so nested tasks
        # can run (reference: node_manager HandleWorkerBlocked/Unblocked
        # — CPU only; accelerators stay bound to their chip assignment)
        self.blocked = False
        self.donated: Optional[ResourceSet] = None  # what blocking released
        # OOM victim policy inputs, from the granting spec: whether the
        # class's tasks are retriable (an adopted same-shape class can
        # differ per task — the granting spec is the agent's best view),
        # and the function/class id + name for the kill receipt and the
        # head's poison-task accounting
        self.retriable = retriable
        self.fid = fid
        self.task_name = task_name


class NodeAgent(IntrospectionRpcMixin, RpcHost):
    def __init__(self, head_addr: Tuple[str, int], session_dir: str,
                 resources: Dict[str, float], arena_path: str = "",
                 capacity: int = 0, is_head_node: bool = False,
                 node_id: str = "", labels: Optional[Dict[str, str]] = None):
        self.node_id = node_id or NodeID.from_random().hex()
        self.head_addr = head_addr
        self.session_dir = session_dir
        self.is_head_node = is_head_node
        self.labels: Dict[str, str] = labels or {}
        self.arena_path = arena_path or os.path.join(
            "/dev/shm", f"rt-arena-{self.node_id[:12]}")
        self.capacity = capacity or config.object_store_memory_bytes
        spill_dir = os.path.join(session_dir, f"spill-{self.node_id[:12]}")
        self.store = StoreCore(self.arena_path, self.capacity, spill_dir)
        # implicit per-node resource for node-affine placement (per-node
        # serve proxies, node-pinned actors; reference: the "node:<ip>"
        # implicit resource in common/scheduling)
        resources = dict(resources)
        resources.setdefault(f"node:{self.node_id[:12]}", 1.0)
        # real memory bin-packing: tasks declaring `memory=` in
        # .options() reserve bytes against this node total — the virtual
        # watchdog envelope when set, else the host's MemTotal
        # (reference: the "memory" resource in ray_constants/_raylet)
        mem_total = int(config.memory_monitor_node_total_bytes)
        if mem_total <= 0:
            try:
                with open("/proc/meminfo") as f:
                    for line in f:
                        if line.startswith("MemTotal"):
                            mem_total = int(line.split()[1]) * 1024
                            break
            except OSError:
                pass
        # the node's memory budget in bytes (virtual envelope or
        # MemTotal): the `memory` resource total for bin-packing, and
        # the denominator behind the kill receipts' self-poisoning
        # discriminator (a victim whose OWN RSS exceeds
        # threshold*total can never fit, even alone)
        self._mem_total_bytes = max(0, mem_total)
        if "memory" not in resources and mem_total > 0:
            resources["memory"] = float(mem_total)
        self.resources = NodeResources(ResourceSet(resources))
        # concrete chip indices behind the fungible "TPU" count: leases
        # holding TPU resources get specific chips, exported to the task
        # as TPU_VISIBLE_CHIPS (reference: accelerators/tpu.py:30
        # set_current_process_visible_accelerator_ids)
        self._free_tpu_chips: List[int] = list(
            range(int(resources.get("TPU", 0))))
        self.local = LocalScheduler(self.resources)
        # placement-group bundles reserved on this node: "pgid:idx" ->
        # LocalScheduler over the reserved resources (reference:
        # src/ray/raylet/placement_group_resource_manager.h)
        self._bundles: Dict[str, LocalScheduler] = {}
        self.cluster_view: Dict[str, Any] = {}
        self._cluster_view_version = -1
        # sharded-object-directory replica (object_directory.py): shard
        # updates past our seen versions ride heartbeat replies; local
        # store reports go up as deltas built by the reporter, with the
        # head's boot epoch handshaking full re-sends
        from ray_tpu._private.object_directory import (DeltaReporter,
                                                       DirectoryMirror)

        self._dir_mirror = DirectoryMirror(int(config.object_directory_shards))
        self._dir_reporter = DeltaReporter()
        self._head_dir_epoch: Optional[str] = None
        # gauge summary the head has ACKED: heartbeats carry only the
        # keys that changed since (None retires a vanished gauge); reset
        # to {} to force a full re-send (head restart / need_metrics)
        self._metrics_sent: Dict[str, float] = {}
        self._server: Optional[RpcServer] = None
        self.port = 0
        self.host = "127.0.0.1"
        self._head: Optional[RpcClient] = None
        self._peers: Dict[Tuple[str, int], RpcClient] = {}
        # bulk object-transfer plane (object_transfer.py): own listener +
        # pooled raw streams per peer; control RPC stays on self._peers
        self._xfer = ObjectTransferServer(self.store)
        self.xfer_port = 0
        self._xfer_clients: Dict[Tuple[str, int], ObjectTransferClient] = {}
        # observability for pulls (also surfaced via rpc_node_info)
        self.xfer_stats: Dict[str, int] = {
            "pulls": 0, "bulk_pulls": 0, "rpc_pulls": 0, "bytes_in": 0,
            "prefetch_started": 0, "alt_source_retries": 0,
            "bulk_fallbacks": 0, "checksum_failures": 0}
        # worker pool
        self._workers: Dict[str, _Worker] = {}   # worker_id -> worker
        self._idle: List[_Worker] = []
        self._starting = 0
        # bounds concurrent worker spawns (worker_startup_parallelism);
        # created lazily so __init__ needs no running loop
        self._spawn_sem: Optional[asyncio.Semaphore] = None
        # (ts, breakdown) reused by heartbeats — see _memory_breakdown
        self._breakdown_cache: Optional[Tuple[float, Dict[str, Any]]] = None
        self._leases: Dict[str, _Lease] = {}
        self._lease_counter = 0
        self._lease_waiters: Dict[object, asyncio.Future] = {}
        # in-flight pulls: oid -> future
        self._pulls: Dict[str, asyncio.Future] = {}
        self._tasks: List[asyncio.Task] = []
        self._shutdown = asyncio.Event()
        # infeasible-but-scalable lease demands, parked while the
        # autoscaler grows the cluster: key -> (demand dict, expiry)
        self._infeasible: Dict[str, Tuple[Dict[str, float], float]] = {}
        self.scalable_shapes: List[ResourceSet] = []
        # blocked leases whose unblock re-acquire is waiting on capacity
        self._unblock_pending: Set[str] = set()
        # set whenever resources free up: triggers an immediate (coalesced)
        # heartbeat so the head's availability view refreshes in ~ms, not a
        # full heartbeat period — pending placement groups replan on it
        # (reference: gcs_placement_group_manager.cc retries pending groups
        # on resource-change notifications from the syncer)
        self._hb_wake = asyncio.Event()
        self._last_reclaim = 0.0  # rate limit for _reclaim_idle_leases
        self._reclaim_followup = False  # trailing-edge push scheduled
        # queued lease requests by client request id, so owners can
        # cancel requests whose demand drained before a grant
        # (reference: node_manager.proto CancelWorkerLease)
        self._lease_req_tokens: Dict[str, Tuple[object, LocalScheduler]] = {}
        # queued bundle reservations by bundle key, so the head can
        # cancel a waited reservation whose RPC failed on its side
        self._reserve_tokens: Dict[str, Tuple[object, LocalScheduler]] = {}
        # live introspection: worker-log tailing for subscribed drivers
        # (log_monitor.py) + the latest loop-lag probe sample, folded
        # into heartbeat metric summaries for the head time-series ring
        self._log = LogMonitor(self.node_id)
        self._last_loop_lag = 0.0
        # chaos gossip state: last rule-set version applied from the head
        self._seen_chaos_version = 0
        # memory watchdog state: last sampled node pressure (rides
        # heartbeats into the cluster view for pressure-aware
        # scheduling), receipts for kills awaiting the head report, and
        # the head-gossiped poison-task quarantine (fid -> detail dict)
        self._last_pressure: Optional[float] = None
        self._oom_reported: Dict[str, Dict[str, Any]] = {}
        self._quarantine: Dict[str, Dict[str, Any]] = {}
        self._seen_quarantine_version = 0
        # graceful scale-down: while draining this agent grants no new
        # leases (owners re-route on the head's drained cluster view),
        # advertises no pending demand, and has its warm leases reclaimed
        self._draining = False
        # set by stop(): loops that might swallow their cancellation
        # (wait_for racing a wake event) exit on it instead
        self._stopping = False

    # ---- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self.host = host
        self._server = RpcServer(self, host, port)
        self.port = await self._server.start()
        self.xfer_port = await self._xfer.start(host)
        self._head = RpcClient(self.head_addr[0], self.head_addr[1], label="head",
                               on_push=self._on_head_push)
        reply = await self._head.call(
            "register_node", node_id=self.node_id, host=self.host,
            port=self.port, arena_path=self.arena_path,
            resources=self.resources.total.to_dict(),
            is_head_node=self.is_head_node, labels=self.labels,
            xfer_port=self.xfer_port)
        self._apply_cluster_view(reply.get("cluster"), reply.get("version"))
        self._apply_dir_reply(reply)
        self._tasks.append(asyncio.ensure_future(self._heartbeat_loop()))
        self._tasks.append(asyncio.ensure_future(self._reap_loop()))

        def _lag(sample: float) -> None:
            self._last_loop_lag = sample

        self._tasks.append(asyncio.ensure_future(
            loop_lag_probe("agent", on_sample=_lag)))
        if config.memory_monitor_refresh_ms > 0:
            self._tasks.append(
                asyncio.ensure_future(self._memory_monitor_loop()))
        await self._start_metrics(host)
        for _ in range(config.worker_pool_prestart_workers):
            self._spawn_worker()
        return self.port

    async def _start_metrics(self, host: str) -> None:
        """Per-node Prometheus endpoint: agent gauges + re-exported
        worker snapshots (reference: reporter_agent.py — one scrape
        target per node)."""
        from ray_tpu._private.metrics import (Gauge, default_registry,
                                              start_metrics_http_server)

        default_registry.default_tags = {"node_id": self.node_id[:12]}
        store_bytes = Gauge("rt_object_store_bytes", "plasma bytes in use")
        store_objs = Gauge("rt_object_store_objects", "objects in plasma")
        store_cap = Gauge("rt_object_store_capacity_bytes", "plasma capacity")
        workers_g = Gauge("rt_worker_pool_size", "worker processes alive")
        leases_g = Gauge("rt_leases_active", "granted worker leases")
        queued_g = Gauge("rt_lease_queue_depth", "lease requests queued")

        from ray_tpu._private.metrics import object_store_breakdown_gauge

        breakdown_g = object_store_breakdown_gauge()

        def collect():
            try:
                u = self.store.usage()
                store_bytes.set(u.get("allocated", 0))
                store_objs.set(u.get("num_objects", 0))
                store_cap.set(u.get("capacity", 0))
                b = self._memory_breakdown(max_age_s=5.0)
                for kind, key in (("arena_used", "arena_used"),
                                  ("arena_free", "arena_free"),
                                  ("pinned", "pinned_bytes"),
                                  ("spilled", "spilled_bytes"),
                                  ("channel", "channel_bytes"),
                                  ("mmap_cache", "mmap_cache_bytes")):
                    breakdown_g.set(b.get(key, 0), tags={"kind": kind})
            except Exception:
                pass
            workers_g.set(len(self._workers))
            leases_g.set(len(self._leases))
            queued_g.set(len(self._lease_waiters))

        # keep the handle: the registry is a process-lifetime singleton,
        # and the closure captures the whole agent — stop() must remove
        # it or every in-process agent (tests) stays pinned forever
        self._metrics_collector = collect
        default_registry.add_collector(collect)
        try:
            self._metrics_server, self.metrics_port = \
                await start_metrics_http_server(default_registry, host)
        except Exception:
            self.metrics_port = 0

    async def rpc_report_metrics(self, source: str, text: bytes):
        """A worker pushes its rendered metrics snapshot for re-export."""
        from ray_tpu._private.metrics import default_registry

        default_registry.ingest_foreign(
            source, text.decode() if isinstance(text, bytes) else text)

    async def rpc_metrics_port(self):
        return {"port": self.metrics_port}

    async def rpc_list_objects(self, limit: int = 1000):
        """Object summaries for the state API (reference:
        node_manager.proto:405 GetObjectsInfo)."""
        return {"objects": self.store.list_objects(limit)}

    def _memory_breakdown(self, max_age_s: float = 0.0) -> Dict[str, Any]:
        """Store byte breakdown plus the agent-side caches the store
        can't see: the transfer plane's cross-pull mmap cache and pulls
        in flight right now.  byte_breakdown() walks every store entry,
        so periodic callers (heartbeats) pass max_age_s to reuse a
        recent snapshot instead of re-walking a large store each beat;
        the memory view's fan-out always computes fresh."""
        now = time.monotonic()
        if (max_age_s > 0.0 and self._breakdown_cache is not None
                and now - self._breakdown_cache[0] <= max_age_s):
            return self._breakdown_cache[1]
        b = self.store.byte_breakdown()
        cache = self._xfer.cache_stats()
        b["mmap_cache_bytes"] = cache["bytes"]
        b["mmap_cache_files"] = cache["files"]
        b["inflight_pulls"] = len(self._pulls)
        self._breakdown_cache = (now, b)
        return b

    async def rpc_node_memory(self, limit: int = 0,
                              include_workers: bool = True,
                              timeout_s: float = 5.0):
        """The node's full memory/object accounting payload for the
        head aggregator: byte breakdown, per-object store entries, and
        (fan-out, like node_stacks) every pooled worker's reference
        summary."""
        limit = int(limit) or int(config.memory_summary_max_refs)
        result: Dict[str, Any] = {
            "node_id": self.node_id,
            "breakdown": self._memory_breakdown(),
            # `limit` caps refs per WORKER summary; the store listing has
            # its own, much higher cap — truncating it marks the whole
            # view partial and turns the leak tripwires off
            "objects": self.store.list_objects(
                int(config.memory_summary_max_objects)),
            "workers": {},
        }

        async def one(w: _Worker):
            try:
                result["workers"][w.worker_id] = await asyncio.wait_for(
                    self._call_worker(w, "memory_summary", timeout_s,
                                      limit=limit),
                    timeout_s + 1.0)
            except Exception as e:
                result["workers"][w.worker_id] = {
                    "error": f"{type(e).__name__}: {e}"}

        if include_workers:
            await asyncio.gather(
                *(one(w) for w in list(self._workers.values())
                  if w.ready.is_set() and w.port and w.proc.poll() is None))
        return result

    async def stop(self):
        # belt for a 3.10 wait_for edge: a cancel landing exactly as
        # _hb_wake fires can be swallowed by the wait (bpo-42130 family),
        # leaving the heartbeat loop alive against a closed head forever
        # — the flag makes the next iteration exit regardless
        self._stopping = True
        self._log.stop()
        for t in self._tasks:
            t.cancel()
        for w in list(self._workers.values()):
            try:
                w.proc.terminate()
            except Exception:
                pass
        for w in list(self._workers.values()):
            try:
                w.proc.wait(timeout=2)
            except Exception:
                try:
                    w.proc.kill()
                except Exception:
                    pass
        for w in list(self._workers.values()):
            if w.iclient is not None:
                await w.iclient.close()
                w.iclient = None
        if self._head:
            await self._head.close()
        for c in self._peers.values():
            await c.close()
        self._peers.clear()
        for xc in self._xfer_clients.values():
            xc.close()
        self._xfer_clients.clear()
        await self._xfer.stop()
        if getattr(self, "_metrics_server", None) is not None:
            self._metrics_server.close()
        if getattr(self, "_metrics_collector", None) is not None:
            from ray_tpu._private.metrics import default_registry

            default_registry.remove_collector(self._metrics_collector)
            self._metrics_collector = None
        if self._server:
            await self._server.stop()
        self.store.close(unlink=True)
        self._shutdown.set()

    async def wait_for_shutdown(self):
        await self._shutdown.wait()

    def _apply_cluster_view(self, view, version, scalable=None) -> None:
        """Last-write-wins would let an older RPC-reply snapshot clobber a
        fresher pushed view; only apply monotonically newer versions.
        (Object locations no longer ride the cluster view — the sharded
        directory mirror carries them, refreshed per shard version.)"""
        if scalable is not None:
            self.scalable_shapes = [ResourceSet(s) for s in scalable]
        if view is None:
            return
        if version is None:
            version = self._cluster_view_version  # legacy: accept equal
        if version >= self._cluster_view_version:
            self.cluster_view = view
            self._cluster_view_version = version

    def _apply_dir_reply(self, reply: Dict[str, Any]) -> None:
        """Fold a head reply's directory piece into the mirror and track
        the head's boot epoch.  An epoch change means a NEW directory
        whose shard versions restarted at 0: reset the mirror (stale
        high seen-versions would suppress every update and pin dead
        holders forever) — the re-send of our own objects is handled by
        the reporter's epoch handshake."""
        epoch = reply.get("dir_epoch")
        if epoch is not None and epoch != self._head_dir_epoch:
            if self._head_dir_epoch is not None:
                self._dir_mirror.reset()
            self._head_dir_epoch = epoch
        self._dir_mirror.apply_updates(reply.get("dir"))

    def _on_head_push(self, method: str, payload):
        if method == "cluster_update":
            self._apply_cluster_view(payload.get("cluster"),
                                     payload.get("version"),
                                     payload.get("scalable"))
        elif method == "chaos_rules":
            self._apply_chaos(payload)

    def _apply_chaos(self, payload: Optional[Dict[str, Any]]) -> None:
        """Install a gossiped chaos rule set (idempotent by version) and
        execute the imperative rules the agent owns: ``agent.kill``
        (SIGKILL myself — the real agent-death signal, PDEATHSIG takes
        my workers down with me) and ``worker.kill`` (SIGKILL matching
        worker processes).  Everything else fires inline at its site."""
        if not payload:
            return
        version = payload.get("version", 0)
        if version == self._seen_chaos_version:
            return
        # acknowledge the version even when opted out (chaos_enabled=
        # False), or the head re-ships the full rule set in every
        # heartbeat reply for the life of the session
        self._seen_chaos_version = version
        if not config.chaos_enabled:
            return
        fault_injection.install(payload.get("rules", []), version)
        self._run_chaos_kills()
        self._forward_chaos_to_workers(payload)

    def _forward_chaos_to_workers(self, payload: Dict[str, Any]) -> None:
        """Worker-side chaos sites (worker.oom, rpc.*) need the rules in
        the WORKER processes: newborns get them via the spawn env
        (RT_CHAOS_RULES); already-running pooled workers get this
        best-effort push over the introspection client."""

        async def _one(w: _Worker):
            try:
                await self._call_worker(w, "chaos_rules", timeout=5.0,
                                        rules=payload.get("rules", []),
                                        version=payload.get("version"))
            except Exception:
                pass

        for w in list(self._workers.values()):
            if w.ready.is_set() and w.proc.poll() is None:
                asyncio.ensure_future(_one(w))

    def _apply_quarantine(self, payload: Optional[Dict[str, Any]]) -> None:
        """Install the head-gossiped poison-task quarantine set (full
        replacement, idempotent by version): lease requests for a
        quarantined function/class id are refused with a typed
        "poisoned" error so enforcement is cluster-wide within one
        heartbeat of the quarantine tripping."""
        if not payload:
            return
        version = payload.get("version", 0)
        if version == self._seen_quarantine_version:
            return
        self._seen_quarantine_version = version
        self._quarantine = dict(payload.get("entries") or {})

    def _quarantined_entry(self, fid: str) -> Optional[Dict[str, Any]]:
        """The live quarantine record for fid, or None.  TTL expiry is
        enforced here too (belt and braces — the head also prunes), so
        a stale gossiped entry can never outlive its window."""
        ent = self._quarantine.get(fid)
        if ent is None:
            return None
        until = float(ent.get("until", 0.0))
        if until and time.time() >= until:
            self._quarantine.pop(fid, None)
            return None
        return ent

    def _run_chaos_kills(self) -> None:
        chaos = fault_injection.decide("agent.kill", key=self.node_id)
        if chaos is not None and chaos.action == "kill":
            delay = chaos.delay_s if chaos.delay_s > 0 else 0.0

            def _die():
                os.kill(os.getpid(), signal.SIGKILL)

            asyncio.get_running_loop().call_later(delay, _die)
            return
        for wid, w in list(self._workers.items()):
            self._maybe_chaos_kill_worker(wid, w)
            self._maybe_chaos_stall_worker(wid, w)

    def _maybe_chaos_kill_worker(self, worker_id: str, w: "_Worker") -> None:
        chaos = fault_injection.decide("worker.kill", key=worker_id)
        if chaos is None or chaos.action != "kill":
            return
        try:
            w.proc.kill()
        except Exception:
            pass
        # the reap loop notices the death within its 0.2s poll and runs
        # the normal worker-death path (lease release, head report)

    def _maybe_chaos_stall_worker(self, worker_id: str,
                                  w: "_Worker") -> None:
        """``worker.stall``: the gray-failure site.  The worker is told
        to busy-hang its RPC IO loop for the rule's delay_s — it stays
        ALIVE (process up, heartbeats fine) but every push, reply, and
        stream item stalls, which is exactly what a replica wedged in
        GC / a stalled decode loop looks like from outside.  Distinct
        from worker.kill: nothing crashes, nothing restarts — only
        deadline/hedging/circuit-breaker layers can route around it."""
        chaos = fault_injection.decide("worker.stall", key=worker_id)
        if chaos is None or chaos.action != "stall":
            return

        async def _stall():
            try:
                if not w.ready.is_set() or not w.port:
                    await asyncio.wait_for(w.ready.wait(), timeout=30)
                c = RpcClient("127.0.0.1", w.port,
                              label=f"stall-{worker_id[:8]}")
                # oneway: the stalled loop cannot reply until it wakes
                await c.oneway("chaos_stall", duration_s=chaos.delay_s)
                await c.close()
            except Exception:
                pass  # worker died first: nothing to stall

        asyncio.ensure_future(_stall())

    def _metric_summary(self) -> Dict[str, float]:
        """Small per-node gauge snapshot piggybacked on every heartbeat;
        the head folds it into the bounded time-series ring behind
        /api/timeseries and `rtpu status --watch` (reference role: the
        reporter agent's periodic node stats push)."""
        out = {
            "loop_lag_seconds": round(self._last_loop_lag, 6),
            "workers": float(len(self._workers)),
            "leases": float(len(self._leases)),
            "lease_queue_depth": float(len(self._lease_waiters)),
        }
        try:
            u = self.store.usage()
            out["object_store_bytes"] = float(u.get("allocated", 0))
        except Exception:
            pass
        # LLM serving pressure: replica engines on this node push these
        # gauges with their worker metric snapshots; summing them here
        # puts queue depth / tokens-per-step into the head time-series
        # ring so `rtpu status --watch` shows serving load per node
        from ray_tpu._private.metrics import default_registry

        for key, family in (("llm_queue_depth", "ray_tpu_llm_queue_depth"),
                            ("llm_tokens_per_step",
                             "ray_tpu_llm_tokens_per_step")):
            try:
                v = default_registry.foreign_sample_sum(family)
            except Exception:
                v = None
            if v is not None:
                out[key] = float(v)
        return out

    def _pending_for_heartbeat(self) -> List[Dict[str, float]]:
        """Queued lease demands plus parked infeasible-but-scalable
        demands (the autoscaler's input; reference: load_metrics.py)."""
        if self._draining:
            # a draining node's backlog must not read as scale-up demand
            return []
        now = time.monotonic()
        self._infeasible = {k: v for k, v in self._infeasible.items()
                            if v[1] > now}
        return (self.local.pending_demands()
                + [dict(d) for d, _ in self._infeasible.values()])

    async def _heartbeat_loop(self):
        period = config.gcs_health_check_period_ms / 1000.0
        while not self._stopping:
            try:
                # object report as a DELTA vs what the head last acked:
                # a steady-state beat costs O(1) directory bytes no
                # matter how many objects this node holds
                delta = self._dir_reporter.build(
                    self.store.object_summary(
                        int(config.locality_min_bytes),
                        int(config.object_directory_max_entries)),
                    self._head_dir_epoch)
                # gauge summary as a DELTA vs what the head last acked
                # (same version-gating idea as the directory delta): a
                # steady-state beat re-serializes nothing
                summary = self._metric_summary()
                metrics_delta: Dict[str, Optional[float]] = {
                    k: v for k, v in summary.items()
                    if self._metrics_sent.get(k) != v}
                for gone in self._metrics_sent.keys() - summary.keys():
                    metrics_delta[gone] = None  # retire vanished gauge
                reply = await self._head.call(
                    "heartbeat", node_id=self.node_id,
                    available=self.resources.available.to_dict(),
                    pending=self._pending_for_heartbeat(),
                    objects_delta=delta,
                    dir_versions=self._dir_mirror.seen_versions(),
                    metrics=metrics_delta or None,
                    memory=self._memory_breakdown(max_age_s=5.0),
                    pressure=self._last_pressure,
                    seen_chaos_version=self._seen_chaos_version,
                    seen_quarantine_version=self._seen_quarantine_version,
                    chaos_fired=fault_injection.fired_counts() or None)
                if reply.get("unknown_node") or reply.get("need_metrics"):
                    # the head restarted with no gauge cache for us (or
                    # discarded this beat entirely): clear so the NEXT
                    # beat re-sends the full summary — bounded one-beat
                    # staleness, same handshake as the dir epoch reset
                    self._metrics_sent = {}
                else:
                    # the head folded this delta: commit the acked state
                    self._metrics_sent = dict(summary)
                self._apply_chaos(reply.get("chaos"))
                self._apply_quarantine(reply.get("quarantine"))
                if reply.get("unknown_node"):
                    # the head restarted without our entry (or reaped us
                    # during its downtime): re-register under the SAME
                    # node id so live actor/PG records stay valid
                    # (reference: node_manager.proto:352 NotifyGCSRestart
                    # — raylets resync after a GCS restart).  The reaped
                    # head dropped our directory entries too: reset the
                    # reporter so the next beat re-sends everything.
                    from ray_tpu._private.object_directory import \
                        DeltaReporter

                    self._dir_reporter = DeltaReporter()
                    reply = await self._head.call(
                        "register_node", node_id=self.node_id,
                        host=self.host, port=self.port,
                        arena_path=self.arena_path,
                        resources=self.resources.total.to_dict(),
                        is_head_node=self.is_head_node, labels=self.labels,
                        xfer_port=self.xfer_port)
                else:
                    self._dir_reporter.ack()
                self._apply_cluster_view(reply.get("cluster"),
                                         reply.get("version"),
                                         reply.get("scalable"))
                self._apply_dir_reply(reply)
            except Exception:
                pass  # head unreachable (possibly restarting) — keep trying
            try:
                await asyncio.wait_for(self._hb_wake.wait(), period)
            except asyncio.TimeoutError:
                continue
            # resources freed: coalesce a burst of releases into one
            # off-cycle heartbeat, capping the extra rate at ~20/s
            await asyncio.sleep(0.05)
            self._hb_wake.clear()

    # ---- object store RPCs (PlasmaClient protocol) -------------------------

    async def rpc_store_create(self, oid: str, size: int, primary: bool = True,
                               wait_s: float = 0.0):
        if wait_s > 0:
            return await self.store.create_with_backpressure(
                oid, size, primary=primary, wait_s=float(wait_s))
        return self.store.create(oid, size, primary=primary)

    async def rpc_store_seal(self, oid: str):
        self.store.seal(oid)
        entry = self.store.objects.get(oid)
        if entry is not None and self._directory_worthy(entry.size):
            # a directory-worthy object appeared: refresh the head's
            # object directory now, not a full heartbeat period later —
            # locality scheduling and multi-source retry see it in ~ms
            self._hb_wake.set()
        return {"ok": True}

    @staticmethod
    def _directory_worthy(size: int) -> bool:
        min_bytes = int(config.locality_min_bytes)
        return min_bytes > 0 and size >= min_bytes

    async def rpc_store_abort(self, oid: str):
        self.store.abort(oid)
        return {"ok": True}

    async def rpc_store_get(self, oids: List[str], client_id: str,
                            wait_timeout: Optional[float] = None):
        return await self.store.get(oids, client_id, wait_timeout=wait_timeout)

    async def rpc_store_release(self, oid: str, client_id: str):
        self.store.release(oid, client_id)

    async def rpc_store_free(self, oids: List[str]):
        self.store.free(oids)
        return {"ok": True}

    async def rpc_store_contains(self, oid: str):
        return self.store.contains(oid)

    async def rpc_store_write(self, oid: str, offset: int, data: bytes):
        """Write into an unsealed object on behalf of a client-mode
        driver that has no arena mmap (reference: ray client proxies
        puts through the cluster; util/client/server/server.py)."""
        entry = self.store.objects.get(oid)
        if entry is None or entry.sealed:
            return {"ok": False, "error": "object missing or sealed"}
        if offset < 0 or offset + len(data) > entry.size:
            # a bad offset must never scribble over neighboring objects
            # in the shared arena
            return {"ok": False,
                    "error": f"write [{offset}, {offset + len(data)}) outside "
                             f"object of size {entry.size}"}
        if entry.location == "shm":
            self.store.arena.view[
                entry.offset + offset: entry.offset + offset + len(data)] = data
        else:
            with open(entry.path, "r+b") as f:
                f.seek(offset)
                f.write(data)
        return {"ok": True}

    async def rpc_store_usage(self):
        return self.store.usage()

    async def rpc_store_promote(self, oids: List[str]):
        """Drain hand-off: copies this node pulled become PRIMARY so
        eviction can't discard them once the original holder is gone.
        ``missing`` names oids with no sealed local copy — the caller
        must not count those as handed off."""
        promoted, missing = self.store.promote(list(oids or ()))
        return {"promoted": promoted, "missing": missing}

    # ---- graceful drain participation (head drain state machine) -----------

    async def rpc_prepare_drain(self):
        """Enter drain mode: refuse new leases, cancel queued lease
        waiters so their owners re-route (the head's drained view no
        longer targets us), and push an UNBOUNDED warm-lease reclaim
        (need={}) to every lease owner — the whole warm pool on this
        node returns instead of waiting out its TTL."""
        self._draining = True
        # queued waiters: wake with "canceled" — the owner's pump
        # retries the demand and the fresh view routes it elsewhere
        for token in list(self._lease_waiters):
            entry = self._lease_waiters.pop(token, None)
            if entry is None:
                continue
            fut, _demand, sched = entry
            _found, granted = sched.cancel(token)
            for tok in granted:
                self._grant_token(tok)
            if not fut.done():
                fut.set_result("canceled")
        payload = {"agent": [self.host, self.port], "need": {}}
        conns = {id(l.owner_conn): l.owner_conn
                 for l in self._leases.values()
                 if l.owner_conn is not None}

        async def _push(conn):
            try:
                await conn.push("reclaim_idle_leases", payload)
            except Exception:
                pass

        for conn in conns.values():
            asyncio.ensure_future(_push(conn))
        self._hb_wake.set()
        return {"ok": True, "leases": len(self._leases)}

    async def rpc_cancel_drain(self):
        """Drain abandoned (head-side failure/timeout): resume granting."""
        self._draining = False
        return {"ok": True}

    async def rpc_drain_info(self):
        """Drain progress the head polls: remaining leases are the
        quiesce gate (idle pooled workers don't block a drain)."""
        return {"draining": self._draining,
                "leases": len(self._leases),
                "workers": len(self._workers),
                "queued": len(self._lease_waiters)}

    # ---- compiled-DAG channels (see dag/channel.py) ------------------------
    # A channel slot is a reusable pinned shm allocation: the writer-node
    # slot plus one mirror per remote reader node, all under the same
    # oid.  Version bytes normally arrive over the bulk transfer plane
    # (write-flagged range requests straight into the arena); the
    # channel_write/channel_read RPCs are the compat path for peers
    # without a reachable transfer listener.

    def _channel_entry(self, oid: str):
        entry = self.store.objects.get(oid)
        if entry is None or not entry.channel:
            return None
        return entry

    async def rpc_channel_create(self, oid: str, size: int,
                                 header: Dict[str, Any]):
        from ray_tpu.dag import channel as chmod

        loc = self.store.create_channel(oid, size)
        view = self.store.arena.view[loc["offset"]:loc["offset"] + size]
        if int.from_bytes(view[0:8], "little") != chmod.MAGIC:
            chmod.init_view(view, header)
        return {"ok": True, "offset": loc["offset"], "size": size}

    async def rpc_channel_destroy(self, oid: str):
        self.store.destroy_channel(oid)
        return {"ok": True}

    async def rpc_channel_map(self, oid: str):
        """Local attach: a driver/worker on this node maps the slot
        zero-copy out of the arena it already has mmap'd."""
        entry = self._channel_entry(oid)
        if entry is None:
            return {"found": False}
        return {"found": True, "offset": entry.offset, "size": entry.size}

    async def rpc_channel_write(self, oid: str, offset: int, data: bytes):
        """Compat push path: version bytes over control RPC when the
        bulk plane cannot reach this node."""
        entry = self._channel_entry(oid)
        if entry is None:
            return {"ok": False, "error": f"no channel {oid[:16]} here"}
        if offset < 0 or offset + len(data) > entry.size:
            return {"ok": False, "error": "write outside channel slot"}
        base = entry.offset
        self.store.arena.view[base + offset:base + offset + len(data)] = data
        return {"ok": True}

    async def rpc_channel_read(self, oid: str, offset: int, length: int):
        entry = self._channel_entry(oid)
        if entry is None:
            return {"ok": False, "error": f"no channel {oid[:16]} here"}
        if offset < 0 or length < 0 or offset + length > entry.size:
            return {"ok": False, "error": "read outside channel slot"}
        base = entry.offset
        return {"ok": True,
                "data": bytes(self.store.arena.view[base + offset:
                                                    base + offset + length])}

    async def rpc_channel_poison(self, oid: str, error: bytes = b"",
                                 close_only: bool = False):
        """Poison (actor death) or close (teardown) the local copy of a
        channel, waking every blocked reader/writer on this node."""
        from ray_tpu.dag import channel as chmod

        entry = self._channel_entry(oid)
        if entry is None:
            return {"ok": False}
        view = self.store.arena.view[entry.offset:entry.offset + entry.size]
        if close_only:
            chmod.close_view(view)
        else:
            chmod.poison_view(view, error)
        return {"ok": True}

    # ---- object transfer (pull-based) --------------------------------------
    # Control (size lookup, pin/unpin) rides the msgpack RPC connection;
    # bytes ride the bulk plane (object_transfer.py) — a dedicated raw
    # stream pool on its own listener — with the chunked obj_chunk RPC
    # kept as the compat/fallback path (and the bench baseline).

    async def rpc_obj_info(self, oid: str, pin_for: str = ""):
        """Peer asks for size before pulling; pins so chunks stay valid.
        Carries the seal-fixed CRC32 so the puller can verify the
        payload it assembles (checksummed transfers).  A first-export
        hash runs on an executor thread — the entry is pinned (above)
        and sealed bytes are immutable, and a multi-GB crc32 must not
        stall the control loop."""
        locs = await self.store.get([oid], pin_for or "xfer", wait_timeout=0.0)
        loc = locs[0]
        if loc is None or loc.get("deleted"):
            return {"found": False}
        crc = await asyncio.get_running_loop().run_in_executor(
            None, self.store.checksum, oid)
        return {"found": True, "size": loc["size"],
                "xfer_port": self.xfer_port, "crc": crc}

    async def rpc_obj_corrupt(self, oid: str, reporter: str = ""):
        """A puller's payload from US failed checksum verification:
        re-hash our own copy against its seal-time CRC.  A genuinely
        corrupt SECONDARY copy is dropped (the directory stops
        advertising it within a beat; primaries stay — dropping the
        only durable copy converts detected corruption into data loss,
        and lineage reconstruction is the owner's call).  An intact
        copy means the corruption was in transit — nothing to do, the
        puller's alternate-holder retry (or a fresh stream) covers it."""
        verdict = await asyncio.get_running_loop().run_in_executor(
            None, self.store.verify_crc, oid)
        if verdict is False:
            entry = self.store.objects.get(oid)
            if entry is not None and not entry.primary:
                # evict the copy only — free() would mark the oid
                # owner-deleted here and fail local getters with
                # "freed" though the owner never freed it
                dropped = self.store.drop_copy(oid)
                if dropped:
                    self._hb_wake.set()  # directory: this holder is gone
                return {"dropped": dropped}
            return {"dropped": False, "corrupt_primary": True}
        return {"dropped": False, "intact": verdict is True}

    async def rpc_obj_chunk(self, oid: str, offset: int, length: int):
        # memoryview reply: msgpack serializes buffer-protocol objects
        # directly, so the chunk is copied once into the reply frame
        # instead of bytes()-copied first; disk-fallback objects come
        # from the transfer server's mmap cache (held across the pull,
        # not reopened per chunk)
        view = self._xfer.object_view(oid, offset, length)
        if view is None:
            return {"found": False}
        return {"found": True, "data": view}

    async def rpc_obj_unpin(self, oid: str, pin_for: str = ""):
        self.store.release(oid, pin_for or "xfer")
        self._xfer.release(oid)  # drop mappings held across the pull
        return {"ok": True}

    async def rpc_ensure_local(self, oid: str, src: Optional[List] = None):
        """Pull oid into the local store from the node at `src` (host,port).

        Concurrent pulls of the same oid are deduplicated
        (reference: pull_manager.h).  A pull whose source fails mid-way
        re-resolves holders from the head's object directory and retries
        once from an alternate before erroring.
        """
        if self.store.contains(oid):
            return {"ok": True, "local": True}
        if not src or (src[0] == self.host and src[1] == self.port):
            # no usable source given: the head's directory may know one
            alts = await self._alt_sources(oid)
            if not alts:
                return {"ok": False, "error": "object not local and no source"}
            src = alts[0]
        try:
            await self._ensure_pull(oid, (src[0], src[1]))
            return {"ok": True}
        except Exception as e:
            return {"ok": False, "error": str(e)}

    async def rpc_ensure_local_batch(self, items: List[List[Any]]):
        """Vectorized ensure_local: one frame carries every (oid, src)
        pair of a driver's get() round; pulls run concurrently, deduped
        against in-flight pulls, and the reply is per-item — localizing
        N objects costs one RPC round, not N (round-5 verdict item)."""
        results = await asyncio.gather(
            *[self.rpc_ensure_local(oid, src=src) for oid, src in items])
        return {"results": list(results)}

    def _ensure_pull(self, oid: str, src: Tuple[str, int]):
        """The deduplicated pull future for oid (shared by ensure_local
        and prefetch-on-lease); shielded so one cancelled waiter cannot
        kill the transfer for the others."""
        fut = self._pulls.get(oid)
        if fut is None:
            fut = asyncio.ensure_future(self._pull_with_retry(oid, src))
            self._pulls[oid] = fut
            fut.add_done_callback(lambda _: self._pulls.pop(oid, None))
        return asyncio.shield(fut)

    async def _pull_with_retry(self, oid: str, src: Tuple[str, int]):
        try:
            return await self._pull(oid, src)
        except Exception:
            # the source may have died mid-pull: ask the head who else
            # holds a copy and retry once from an alternate
            alts = await self._alt_sources(oid, exclude={tuple(src)})
            if not alts:
                raise
            self.xfer_stats["alt_source_retries"] += 1
            return await self._pull(oid, alts[0])

    async def _alt_sources(self, oid: str,
                           exclude=frozenset()) -> List[Tuple[str, int]]:
        if self._head is None:
            return []
        try:
            r = await self._head.call("object_locations", oids=[oid])
        except Exception:
            return []
        out = []
        for host, port in r.get("locations", {}).get(oid, []):
            addr = (host, port)
            if addr not in exclude and addr != (self.host, self.port):
                out.append(addr)
        return out

    async def _pull(self, oid: str, src: Tuple[str, int]):
        from ray_tpu._private.metrics import object_transfer_metrics

        peer = self._peer(src)
        pin_id = f"xfer:{self.node_id[:12]}"
        info = await peer.call("obj_info", oid=oid, pin_for=pin_id)
        if not info.get("found"):
            raise KeyError(f"object {oid} not found at {src}")
        size = info["size"]
        xfer_port = info.get("xfer_port", 0)
        use_bulk = bool(xfer_port) and bool(config.object_transfer_enabled)
        t0 = time.monotonic()
        try:
            loc = self.store.create(oid, size, primary=False)
            try:
                if use_bulk:
                    try:
                        client = self._xfer_client((src[0], xfer_port))
                        view, mapped = dest_view(self.store, loc)
                        try:
                            await client.fetch_into(oid, view)
                        finally:
                            if mapped is not None:
                                mapped.close()
                    except (TransferError, OSError):
                        # transfer listener unreachable (filtered port,
                        # dead thread) while the control RPC to this
                        # peer demonstrably works — the chunk path must
                        # still serve the bytes (refetch is idempotent)
                        use_bulk = False
                        self.xfer_stats["bulk_fallbacks"] += 1
                        await self._pull_chunks_rpc(peer, oid, size, loc)
                else:
                    await self._pull_chunks_rpc(peer, oid, size, loc)
                # verify OUTSIDE the bulk-fallback try: a checksum
                # mismatch must go to an ALTERNATE holder (the retry in
                # _pull_with_retry), never refetch the same corrupt
                # source over a different plane
                await self._verify_pull(oid, loc, info.get("crc"), peer)
                self.store.seal(oid)
            except BaseException:
                self.store.abort(oid)
                raise
        finally:
            try:
                await peer.oneway("obj_unpin", oid=oid, pin_for=pin_id)
            except Exception:
                pass
        plane = "bulk" if use_bulk else "rpc"
        bytes_total, seconds = object_transfer_metrics()
        bytes_total.inc(size, tags={"plane": plane, "direction": "in"})
        seconds.observe(time.monotonic() - t0,
                        tags={"plane": plane, "direction": "in"})
        self.xfer_stats["pulls"] += 1
        self.xfer_stats[f"{plane}_pulls"] += 1
        self.xfer_stats["bytes_in"] += size
        if self._directory_worthy(size):
            self._hb_wake.set()  # new holder: refresh the directory fast

    async def _verify_pull(self, oid: str, loc: Dict[str, Any],
                           expected_crc, peer: RpcClient) -> None:
        """Checksum the just-assembled pull payload against the
        holder's seal-time CRC32.  A mismatch counts in
        ray_tpu_object_checksum_failures_total, tells the holder to
        re-verify (it drops a genuinely-corrupt secondary — the
        quarantined copy), and raises TransferError so the pull retries
        from an alternate holder via the existing alt-source path —
        the xfer.corrupt chaos site becomes detectable end to end
        instead of silent pickle roulette."""
        if expected_crc is None or not config.object_checksums:
            return
        entry = self.store.objects.get(oid)
        if entry is None:
            return  # aborted underneath us: nothing to verify
        # executor thread: a multi-GB hash must not stall the agent
        # control loop (heartbeats, lease grants, watchdog ticks) — the
        # unsealed allocation is exclusively ours until seal, so the
        # entry's bytes are stable off-loop.  compute_crc handles the
        # shm/disk location split in ONE place
        actual = await asyncio.get_running_loop().run_in_executor(
            None, self.store.compute_crc, entry)
        if actual is None:
            return  # bytes unreadable: cannot verify, let the seal land
        if actual == int(expected_crc):
            entry.crc = int(expected_crc)  # verified: no later re-hash
            return
        from ray_tpu._private.metrics import \
            object_checksum_failures_counter

        object_checksum_failures_counter().inc()
        self.xfer_stats["checksum_failures"] = \
            self.xfer_stats.get("checksum_failures", 0) + 1
        try:
            await peer.oneway("obj_corrupt", oid=oid,
                              reporter=self.node_id)
        except Exception:
            pass
        raise TransferError(
            f"checksum mismatch pulling {oid[:16]}: payload crc "
            f"{actual:#010x} != sealed crc {int(expected_crc):#010x} "
            f"(copy reported to holder; retrying from an alternate)")

    async def _pull_chunks_rpc(self, peer: RpcClient, oid: str, size: int,
                               loc: Dict[str, Any]):
        """Legacy stop-and-wait chunk pull over the control RPC (used
        against agents without a transfer plane, and as the bench
        baseline for the bulk plane)."""
        chunk = config.object_transfer_chunk_bytes
        pos = 0
        while pos < size:
            n = min(chunk, size - pos)
            r = await peer.call("obj_chunk", oid=oid, offset=pos, length=n)
            if not r.get("found"):
                raise KeyError(f"object {oid} vanished mid-pull")
            data = r["data"]
            if loc["location"] == "shm":
                self.store.arena.view[
                    loc["offset"] + pos: loc["offset"] + pos + len(data)] = data
            else:
                with open(loc["path"], "r+b") as f:
                    f.seek(pos)
                    f.write(data)
            pos += len(data)

    def _peer(self, addr: Tuple[str, int]) -> RpcClient:
        addr = (addr[0], addr[1])
        client = self._peers.get(addr)
        if client is None or client.dead:
            if client is not None:
                # close the replaced dead client: dropping it on the
                # floor leaks its fd and read task until process exit
                asyncio.ensure_future(client.close())
            client = RpcClient(addr[0], addr[1], label=f"peer-{addr[1]}")
            self._peers[addr] = client
        return client

    def _xfer_client(self, addr: Tuple[str, int]) -> ObjectTransferClient:
        addr = (addr[0], addr[1])
        client = self._xfer_clients.get(addr)
        if client is None or client.closed:
            client = ObjectTransferClient(addr[0], addr[1])
            self._xfer_clients[addr] = client
        return client

    # ---- locality + prefetch -----------------------------------------------

    def _arg_bytes_by_node(self, ts: TaskSpec) -> Dict[str, float]:
        """Argument bytes already resident per node, from the spec's
        owner-stamped hints plus the sharded-directory mirror (which
        also sees secondary copies made by earlier prefetches) plus our
        own store.  Mirror lookups are O(1) per argument — the old
        per-node object maps made this O(nodes) per argument."""
        out: Dict[str, float] = {}
        addr_to_node = {tuple(v["addr"]): nid
                        for nid, v in self.cluster_view.items()}
        addr_to_node[(self.host, self.port)] = self.node_id
        for arg in ts.args:
            oid = arg.object_id
            if oid is None or not arg.size:
                continue
            holders = set(self._dir_mirror.holders(oid))
            if arg.loc:
                nid = addr_to_node.get(tuple(arg.loc))
                if nid is not None:
                    holders.add(nid)
            if self.store.contains(oid):
                holders.add(self.node_id)
            for nid in holders:
                out[nid] = out.get(nid, 0.0) + arg.size
        return out

    def _prefetch_args(self, ts: TaskSpec) -> None:
        """The lease will be serviced here: start pulling hinted remote
        args NOW, in one gather deduped against in-flight pulls, so the
        transfer overlaps queue wait and worker startup instead of
        serializing in front of execution (reference: the raylet's pull
        manager fetching task dependencies while the lease queues)."""
        pulls: Dict[str, Tuple[str, int]] = {}
        for arg in ts.args:
            oid = arg.object_id
            if oid is None or not arg.loc or oid in pulls:
                continue
            src = (arg.loc[0], arg.loc[1])
            if src == (self.host, self.port) or self.store.contains(oid) \
                    or oid in self._pulls:
                continue
            pulls[oid] = src
        if not pulls:
            return
        self.xfer_stats["prefetch_started"] += len(pulls)

        async def _gather():
            await asyncio.gather(
                *[self._ensure_pull(oid, src) for oid, src in pulls.items()],
                return_exceptions=True)  # the worker's get() retries/errors

        asyncio.ensure_future(_gather())

    # ---- worker pool -------------------------------------------------------

    def _spawn_worker(self, env_key: str = "",
                      extra_env: Optional[Dict[str, str]] = None,
                      working_dir: Optional[str] = None,
                      path_dirs: Optional[List[str]] = None) -> _Worker:
        worker_id = WorkerID.from_random().hex()
        env = dict(os.environ)
        # user env_vars first: the runtime-env control vars below must win
        if extra_env:
            env.update(extra_env)
        env.update({
            "RT_HEAD_HOST": self.head_addr[0],
            "RT_HEAD_PORT": str(self.head_addr[1]),
            "RT_AGENT_HOST": self.host,
            "RT_AGENT_PORT": str(self.port),
            "RT_ARENA_PATH": self.arena_path,
            "RT_NODE_ID": self.node_id,
            "RT_WORKER_ID": worker_id,
            "RT_SESSION_DIR": self.session_dir,
            # unbuffered stdout: a task's print() reaches the log file
            # (and any subscribed driver) immediately, not at the next
            # 8KB block flush
            "PYTHONUNBUFFERED": "1",
        })
        chaos_state = fault_injection.status()
        if chaos_state.get("rules"):
            # worker-side chaos sites (worker.oom, rpc.*) fire in the
            # worker process: ship the live rule set with the spawn
            import json as _json

            env["RT_CHAOS_RULES"] = _json.dumps(chaos_state)
        if working_dir:
            env["RT_WORKING_DIR"] = working_dir
        if path_dirs:
            env["RT_PY_MODULES"] = os.pathsep.join(path_dirs)
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"worker-{worker_id[:12]}.log")
        out = open(log_path, "ab")
        from ray_tpu._private.spawn import fast_python_cmd, set_pdeathsig

        cmd, env_up = fast_python_cmd("ray_tpu._private.worker_main")
        env.update(env_up)
        proc = subprocess.Popen(
            cmd, env=env, stdout=out, stderr=subprocess.STDOUT,
            start_new_session=True, preexec_fn=set_pdeathsig)
        out.close()
        # stream THIS agent's worker logs only (the session logs dir may
        # be shared by several agents) — each line reaches a subscribed
        # driver exactly once
        self._log.add_file(log_path, proc.pid, worker_id)
        w = _Worker(worker_id, proc, env_key=env_key)
        self._workers[worker_id] = w
        self._starting += 1
        return w

    async def rpc_worker_ready(self, worker_id: str, port: int):
        w = self._workers.get(worker_id)
        if w is None:
            return {"ok": False}
        # armed worker.kill / worker.stall rules also catch workers
        # born after them
        self._maybe_chaos_kill_worker(worker_id, w)
        w.port = port
        self._maybe_chaos_stall_worker(worker_id, w)
        self._starting = max(0, self._starting - 1)
        if not w.ready.is_set():
            w.ready.set()
            w.idle_since = time.monotonic()
            self._idle.append(w)
        self._drain_lease_queue()
        return {"ok": True, "node_id": self.node_id}

    async def _reap_loop(self):
        """Poll child processes for deaths (reference: raylet SIGCHLD),
        and retire idle runtime-env workers: env-keyed workers can only
        serve their own env, so without a timeout every distinct env
        would permanently leak one process (reference: worker_pool.h
        kill_idle_workers / idle_worker_killing_time_threshold)."""
        while True:
            await asyncio.sleep(0.2)
            for wid, w in list(self._workers.items()):
                if w.proc.poll() is not None:
                    self._on_worker_dead(wid, f"exit code {w.proc.returncode}")
            cutoff = time.monotonic() - config.worker_idle_timeout_ms / 1000.0
            for w in [w for w in self._idle
                      if w.env_key and w.idle_since < cutoff]:
                try:
                    w.proc.kill()
                except Exception:
                    pass
                self._on_worker_dead(w.worker_id, "idle env worker retired")

    def _on_worker_dead(self, worker_id: str, reason: str):
        w = self._workers.pop(worker_id, None)
        if w is None:
            return
        # log monitor drains the file once more, then evicts it
        self._log.mark_dead(worker_id)
        if w.iclient is not None:
            asyncio.ensure_future(w.iclient.close())
            w.iclient = None
        if w in self._idle:
            self._idle.remove(w)
        if not w.ready.is_set():
            self._starting = max(0, self._starting - 1)
            w.ready.set()  # wake lease grant path; it will re-check
        if w.lease_id is not None:
            lease = self._leases.pop(w.lease_id, None)
            if lease is not None:
                self._free_tpu_chips.extend(lease.tpu_chips)
                self._release_lease_resources(lease)
        self.store.release_client(worker_id)
        if self._head is not None:
            asyncio.ensure_future(self._report_worker_death(worker_id, reason))
        self._drain_lease_queue()

    # ---- memory monitor ----------------------------------------------------

    def _worker_samples(self) -> List[memory_monitor.WorkerSample]:
        """Per-LEASED-worker RSS + policy flags for this tick.  Only
        leased workers are candidates — an idle pooled worker holds no
        task to retry and its memory is the interpreter baseline."""
        out: List[memory_monitor.WorkerSample] = []
        for lease in self._leases.values():
            w = lease.worker
            if w.proc.poll() is not None:
                continue
            rss = memory_monitor.read_rss_bytes(w.pid)
            if rss is None:
                continue
            out.append(memory_monitor.WorkerSample(
                worker_id=w.worker_id, rss=rss, lease_seq=lease.seq,
                retriable=lease.retriable, pinned=w.pinned,
                saving=w.saving, fid=lease.fid, name=lease.task_name))
        return out

    def _memory_usage_fraction(
            self, samples: Optional[List] = None) -> Optional[float]:
        """Node memory pressure in [0, 1]; None if unreadable.  Sources
        (memory_monitor.usage_fraction): the test hook file, the virtual
        per-agent envelope (memory_monitor_node_total_bytes), or
        /proc/meminfo."""
        virtual = int(config.memory_monitor_node_total_bytes)
        rss_sum = 0
        if virtual > 0:
            if samples is None:
                samples = self._worker_samples()
            rss_sum = sum(s.rss for s in samples)
        return memory_monitor.usage_fraction(
            config.memory_monitor_test_usage_file, virtual, rss_sum)

    def _oom_receipt(self, victim, usage: float,
                     samples: List) -> Dict[str, Any]:
        """The typed-kill payload: everything the owner needs to turn a
        worker death into a retriable OutOfMemoryError with evidence."""
        return {
            "worker_id": victim.worker_id,
            "node_id": self.node_id,
            "rss": victim.rss,
            "usage": usage,
            "threshold": float(config.memory_usage_threshold),
            # the node's kill ceiling in bytes: victims whose own RSS
            # approaches it are SELF-poisoning — the poison-quarantine
            # counter only counts those, so contention victims of
            # aggregate pressure retry without building a poison record.
            # 0 (= count every kill) when the test usage-file hook
            # drives pressure: synthetic usage says nothing about RSS
            "limit": 0 if config.memory_monitor_test_usage_file
            else int(self._mem_total_bytes
                     * float(config.memory_usage_threshold)),
            "fid": victim.fid,
            "name": victim.name,
            "breakdown": {
                "workers": [[s.worker_id[:12], s.rss] for s in samples],
                "store": {k: v for k, v in self.store.usage().items()
                          if isinstance(v, (int, float))},
            },
        }

    async def _memory_monitor_loop(self):
        """The node OOM watchdog (reference: memory_monitor.h:52):
        sample usage + per-worker RSS each period; past the threshold,
        kill the policy's victim (highest-RSS retriable task first,
        pinned/saving workers last resort — memory_monitor.pick_victim)
        and reply to the owner with a typed receipt BEFORE the SIGKILL,
        so the owner's worker-death accounting draws from the separate
        OOM retry budget instead of max_retries."""
        from ray_tpu._private.metrics import memory_pressure_metrics

        period = config.memory_monitor_refresh_ms / 1000.0
        watchdog = memory_monitor.OomWatchdog(
            threshold=float(config.memory_usage_threshold),
            min_kill_gap_s=config.memory_monitor_min_kill_interval_ms
            / 1000.0)
        oom_kills, pressure_gauge, _ = memory_pressure_metrics()
        while True:
            await asyncio.sleep(period)
            try:
                samples = self._worker_samples()
                usage = self._memory_usage_fraction(samples)
                if usage is not None:
                    self._last_pressure = usage
                    pressure_gauge.set(usage)
                victim = watchdog.tick(usage, samples)
                if victim is None:
                    continue
                oom_kills.inc(tags={"reason": "node_pressure"})
                await self._oom_kill(victim, usage, samples)
            except Exception:
                pass  # the watchdog must survive any single bad tick

    async def _oom_kill(self, victim, usage: float, samples: List) -> None:
        """Execute one watchdog kill: receipt to the owner first (its
        own connection — best-effort, ordered ahead of the worker-socket
        reset it is about to observe), then SIGKILL, then the normal
        death bookkeeping (which reports to the head with the receipt
        attached for poison-task accounting)."""
        w = self._workers.get(victim.worker_id)
        if w is None or w.proc.poll() is not None:
            return
        receipt = self._oom_receipt(victim, usage, samples)
        lease = self._leases.get(w.lease_id) if w.lease_id else None
        if lease is not None and lease.owner_conn is not None \
                and not lease.owner_conn.writer.is_closing():
            try:
                await lease.owner_conn.push("oom_kill", receipt)
            except Exception:
                pass  # owner gone: the generic death path still covers it
        reason = (f"OOM-killed by the memory monitor: node memory "
                  f"{usage:.0%} >= threshold {receipt['threshold']:.0%}, "
                  f"worker RSS {victim.rss >> 20} MiB "
                  f"(victim policy: highest-RSS retriable task)")
        self._oom_reported[victim.worker_id] = receipt
        try:
            w.proc.kill()
        except Exception:
            pass
        self._on_worker_dead(victim.worker_id, reason)

    async def rpc_worker_flags(self, worker_id: str,
                               pinned: Optional[bool] = None,
                               saving: Optional[bool] = None):
        """Worker-pushed OOM-policy flags: entering/leaving a pinned
        __rt_dag_* loop, and the __rt_save__ critical section."""
        w = self._workers.get(worker_id)
        if w is not None:
            if pinned is not None:
                w.pinned = bool(pinned)
            if saving is not None:
                w.saving = bool(saving)
        return {"ok": True}

    async def _report_worker_death(self, worker_id: str, reason: str):
        oom = self._oom_reported.pop(worker_id, None)
        try:
            await self._head.call("worker_died", node_id=self.node_id,
                                  worker_id=worker_id, reason=reason,
                                  oom=oom)
        except Exception:
            pass

    # ---- placement group bundles -------------------------------------------

    async def rpc_reserve_bundle(self, pg_id: str, bundle_index: int,
                                 resources: Dict[str, float],
                                 wait_ms: int = 0, _conn=None):
        """Atomically carve a bundle's resources out of the node pool
        (reference: node_manager.proto PrepareBundleResources).

        With ``wait_ms`` > 0 a reservation that cannot be satisfied right
        now joins the FIFO lease queue instead of failing: the moment a
        warm-pooled task lease returns (worker.py _WARM_LEASE_TTL_S, or
        sooner via the demand-aware reclaim push) the freed capacity
        grants the reservation — placement groups preempt the warm pool
        event-driven rather than the head polling."""
        key = f"{pg_id}:{bundle_index}"
        if key in self._bundles:
            return {"ok": True, "already": True}
        demand = ResourceSet(resources)
        if self.local.try_acquire(demand):
            self._bundles[key] = LocalScheduler(NodeResources(demand))
            return {"ok": True}
        if wait_ms <= 0 or not self.resources.is_feasible(demand):
            return {"ok": False, "error": "insufficient resources"}
        status = await self._queue_for_resources(
            self.local, demand, wait_ms / 1000.0,
            cancel_key=key, registry=self._reserve_tokens)
        if status != "granted":
            return {"ok": False, "error": "insufficient resources"
                    if status == "timeout" else "canceled"}
        if _conn is not None and _conn.writer.is_closing():
            # the head that asked is gone and cannot learn of this grant;
            # its rollback only covers acknowledged reservations — give
            # the capacity back instead of leaking a phantom carve-out
            for tok in self.local.release(demand):
                self._grant_token(tok)
            return {"ok": False, "error": "caller disconnected"}
        self._bundles[key] = LocalScheduler(NodeResources(demand))
        return {"ok": True}

    async def rpc_reserve_bundles(self, pg_id: str, items: List[List[Any]],
                                  wait_ms: int = 0, _conn=None):
        """Batched bundle reservation: every bundle this node hosts for
        one placement group rides a single frame (the PG-commit half of
        the lease-frame batching).  Items reserve in order; the first
        failure stops the pass — the head rolls back what this reply
        reports reserved, so later items must not burn queue waits."""
        out: List[Dict[str, Any]] = []
        for bundle_index, resources in items:
            r = await self.rpc_reserve_bundle(pg_id, int(bundle_index),
                                              resources, wait_ms=wait_ms,
                                              _conn=_conn)
            out.append(r)
            if not r.get("ok"):
                break
        return {"results": out}

    async def rpc_return_bundles(self, pg_id: str, indices: List[int]):
        """Batched bundle return (remove/rollback path)."""
        return {"results": [await self.rpc_return_bundle(pg_id, int(i))
                            for i in indices]}

    async def rpc_cancel_bundle_reservation(self, pg_id: str,
                                            bundle_index: int):
        """Head-side reserve RPC failed (connection drop mid-wait): drop
        the queued reservation, or return the bundle if it already
        granted — either way no capacity stays carved out for a
        reservation the head gave up on."""
        key = f"{pg_id}:{bundle_index}"
        entry = self._reserve_tokens.get(key)
        if entry is not None:
            token, sched = entry
            waiter = self._lease_waiters.pop(token, None)
            if waiter is not None:
                fut = waiter[0]
                _found, granted = sched.cancel(token)
                for tok in granted:
                    self._grant_token(tok)
                if not fut.done():
                    fut.set_result("canceled")
                return {"ok": True}
        if key in self._bundles:
            return await self.rpc_return_bundle(pg_id, bundle_index)
        return {"ok": False}

    async def rpc_return_bundle(self, pg_id: str, bundle_index: int):
        key = f"{pg_id}:{bundle_index}"
        sched = self._bundles.pop(key, None)
        if sched is None:
            return {"ok": False}
        # wake queued lease requests; they re-check and see the bundle gone
        for token in sched.cancel_all():
            self._grant_token(token)
        # kill leases still running against the bundle (reference: PG
        # removal kills its tasks/actors)
        for lease_id, lease in list(self._leases.items()):
            if lease.bundle_key == key:
                self._leases.pop(lease_id, None)
                lease.worker.lease_id = None
                try:
                    lease.worker.proc.terminate()
                except Exception:
                    pass
        for tok in self.local.release(sched.resources.total):
            self._grant_token(tok)
        self._hb_wake.set()
        return {"ok": True}

    def _sched_for(self, ts: TaskSpec):
        """(scheduler, bundle_key) for a task; bundle-targeted tasks draw
        from their reserved bundle, not the free node pool."""
        if ts.placement_group_id:
            key = f"{ts.placement_group_id}:{max(ts.bundle_index, 0)}"
            return self._bundles.get(key), key
        return self.local, ""

    # ---- lease protocol ----------------------------------------------------

    async def rpc_request_lease(self, spec: Dict[str, Any],
                                grant_only: bool = False, req_id: str = "",
                                _conn=None):
        """Grant a worker lease for the task's resource shape.

        Replies: {"granted": {...}} | {"spillback": {...}} | {"error": ...}
        (reference: node_manager.h:520 HandleRequestWorkerLease — the
        spillback reply mirrors the reference's retry_at_raylet_address).
        """
        ts = TaskSpec.from_wire(spec)
        demand = ts.resource_set()
        poisoned = self._quarantined_entry(ts.function_id)
        if poisoned is not None:
            # fail fast BEFORE spending a worker: the class already
            # killed workers poison_task_threshold consecutive times
            return {"error": "poisoned",
                    "error_str": poisoned.get("detail", "quarantined"),
                    "history": poisoned.get("history", [])}
        if self._draining:
            # owners treat this as a retriable lease timeout; by their
            # next ask the drained cluster view routes them elsewhere
            await asyncio.sleep(0.2)  # pace retries against a drainer
            return {"error": "lease timeout", "error_str": "node draining"}
        if not grant_only:
            self._rebind_owner_leases(ts.caller_id, _conn)
        chaos = fault_injection.decide("lease.grant",
                                       key=ts.actor_id or ts.function_id)
        if chaos is not None and chaos.action == "delay":
            await fault_injection.sleep_async(chaos.delay_s)
        if ts.placement_group_id:
            # same grant_only exemption as below: PG-placed ACTORS are
            # head-created, and their leases must never die with a head
            # connection blip
            return await self._request_bundle_lease(
                ts, demand, None if grant_only else _conn, req_id)
        if not grant_only:
            routed = await self._route_lease(ts, demand)
            if routed is not None:
                return routed
        if not self.resources.is_feasible(demand):
            return {"error": "infeasible",
                    "error_str": f"node cannot satisfy {demand.to_dict()}"}
        # the task will run here (or queue here): overlap its argument
        # transfers with the queue wait / worker startup.  grant_only
        # requests come from the head (actor creation): their leases'
        # lifetimes are head-managed, not connection-scoped
        self._prefetch_args(ts)
        return await self._acquire_and_grant(
            self.local, demand, "", ts, None if grant_only else _conn,
            req_id)

    async def _route_lease(self, ts: TaskSpec, demand: ResourceSet):
        """Cluster-policy half of a lease request: None when the task
        should be serviced locally, else the spillback/error reply."""
        cluster = {
            nid: NodeResources.from_dict(
                {"total": v["res"]["total"], "available": v["res"]["available"]})
            for nid, v in self.cluster_view.items()
            # draining nodes accept no new work — never spill back there
            if not v.get("draining")
        }
        # our own view is fresher than the gossiped one
        if not self._draining:
            cluster[self.node_id] = self.resources
        labels = {nid: v.get("labels", {})
                  for nid, v in self.cluster_view.items()}
        labels[self.node_id] = self.labels
        # pressure-aware demotion: nodes past the watchdog threshold
        # (gossiped gauge; our own sample is fresher) rank behind
        # healthy ones, so new work stops piling onto a node whose
        # watchdog is about to start killing
        pressure = {nid: float(v["pressure"])
                    for nid, v in self.cluster_view.items()
                    if v.get("pressure") is not None}
        if self._last_pressure is not None:
            pressure[self.node_id] = self._last_pressure
        target = pick_node(
            cluster, demand, self.node_id,
            spread_threshold=config.scheduler_spread_threshold,
            top_k_fraction=config.scheduler_top_k_fraction,
            top_k_absolute=config.scheduler_top_k_absolute,
            strategy=ts.scheduling_strategy, labels_by_node=labels,
            arg_bytes_by_node=self._arg_bytes_by_node(ts),
            locality_min_bytes=int(config.locality_min_bytes),
            pressure_by_node=pressure,
            pressure_threshold=float(config.memory_usage_threshold))
        if target is None:
            # hard affinity/label constraints name specific nodes;
            # autoscaled capacity can never satisfy them, so they
            # fail now instead of parking forever
            if self._demand_is_scalable(demand) \
                    and not _is_hard_strategy(ts.scheduling_strategy):
                # an autoscaler can launch a node this fits: park the
                # demand (visible to the scale-up loop via heartbeat)
                # and tell the submitter to keep waiting — mirrors the
                # reference, where infeasible tasks pend until the
                # autoscaler resolves them (autoscaler.py demand loop)
                key = repr(sorted(demand.to_dict().items()))
                self._infeasible[key] = (demand.to_dict(),
                                         time.monotonic() + 30.0)
                await asyncio.sleep(1.0)  # pace the submitter's retries
                return {"error": "lease timeout",
                        "error_str": "waiting for cluster scale-up"}
            return {"error": "infeasible",
                    "error_str": f"no node can ever satisfy {demand.to_dict()}"}
        if target != self.node_id:
            view = self.cluster_view.get(target)
            if view is not None:
                return {"spillback": {"node_id": target, "addr": view["addr"]}}
        return None

    async def rpc_request_leases(self, spec: Dict[str, Any], count: int = 1,
                                 req_id: str = "", _conn=None):
        """Batched lease grant: one frame asks for up to `count` workers
        of one resource shape; the reply carries every lease grantable
        RIGHT NOW ({"granted_list": [...]}) so a submission burst costs
        O(1) lease RPC rounds instead of one round (and one agent-FIFO
        slot) per missing lease.

        When nothing is grantable immediately the request degrades to
        the classic single-lease queued wait — capacity freed mid-burst
        still turns into exactly one grant, FIFO-fairly, and the owner's
        post-reply pump re-asks for the rest."""
        ts = TaskSpec.from_wire(spec)
        demand = ts.resource_set()
        poisoned = self._quarantined_entry(ts.function_id)
        if poisoned is not None:
            return {"error": "poisoned",
                    "error_str": poisoned.get("detail", "quarantined"),
                    "history": poisoned.get("history", [])}
        if self._draining:
            await asyncio.sleep(0.2)
            return {"error": "lease timeout", "error_str": "node draining"}
        self._rebind_owner_leases(ts.caller_id, _conn)
        chaos = fault_injection.decide("lease.grant",
                                       key=ts.actor_id or ts.function_id)
        if chaos is not None and chaos.action == "delay":
            await fault_injection.sleep_async(chaos.delay_s)
        count = max(1, min(int(count), int(config.lease_request_batch_max)))
        if ts.placement_group_id:
            sched, key = self._sched_for(ts)
            if sched is None:
                return {"error": "bundle not reserved",
                        "error_str": f"bundle {key} is not on node "
                                     f"{self.node_id[:12]}"}
            if not sched.resources.is_feasible(demand):
                return {"error": "infeasible",
                        "error_str": f"demand {demand.to_dict()} exceeds "
                                     f"bundle {key} capacity"}
            self._prefetch_args(ts)
            return await self._grant_many(sched, demand, count, key, ts,
                                          _conn, req_id)
        routed = await self._route_lease(ts, demand)
        if routed is not None:
            return routed
        if not self.resources.is_feasible(demand):
            return {"error": "infeasible",
                    "error_str": f"node cannot satisfy {demand.to_dict()}"}
        self._prefetch_args(ts)
        return await self._grant_many(self.local, demand, count, "", ts,
                                      _conn, req_id)

    async def _grant_many(self, sched: LocalScheduler, demand: ResourceSet,
                          count: int, bundle_key: str, ts: TaskSpec,
                          conn=None, req_id: str = ""):
        n = sched.acquire_many(demand, count)
        if n == 0:
            # nothing free right now: fall back to ONE queued request so
            # the frame still resolves the moment capacity frees
            r = await self._acquire_and_grant(sched, demand, bundle_key,
                                              ts, conn, req_id)
            return self._as_grant_list(r)
        # the reply ships at FIRST worker ready (plus a short straggler
        # window), not when the slowest of n spawns registers — a cold
        # burst must start executing at first-worker-ready, exactly like
        # the old serial per-lease requests did.  Late-materializing
        # grants park into the idle pool; the owner's follow-up ask
        # (its deficit persists) pops them with no spawn cost.
        futs = [asyncio.ensure_future(
            self._grant_safe(sched, demand, bundle_key, ts, conn))
            for _ in range(n)]
        done, pending = await asyncio.wait(
            futs, return_when=asyncio.FIRST_COMPLETED)
        if pending:
            done2, pending = await asyncio.wait(pending, timeout=0.05)
            done |= done2
        for f in pending:
            f.add_done_callback(self._park_late_grant)
        granted = [f.result()["granted"] for f in done
                   if "granted" in f.result()]
        if granted:
            return {"granted_list": granted}
        if pending:
            # every completed attempt failed but workers are still
            # starting: tell the owner to re-ask, not to error out
            return {"error": "lease timeout",
                    "error_str": "workers still starting"}
        return self._as_grant_list(next(iter(done)).result())

    def _park_late_grant(self, fut) -> None:
        """A grant completed after its request_leases frame shipped: the
        owner never heard of this lease, so hand it straight back — the
        worker idles in the pool and the resources free for the owner's
        follow-up ask."""
        try:
            r = fut.result()
        except Exception:
            return
        g = r.get("granted")
        if g:
            asyncio.ensure_future(
                self.rpc_return_lease(g["lease_id"], kill_worker=False))

    @staticmethod
    def _as_grant_list(reply: Dict[str, Any]) -> Dict[str, Any]:
        if "granted" in reply:
            return {"granted_list": [reply["granted"]]}
        return reply

    def _demand_is_scalable(self, demand: ResourceSet) -> bool:
        """True if some autoscaler-launchable node type could fit this."""
        return any(shape.fits(demand) for shape in self.scalable_shapes)

    async def _request_bundle_lease(self, ts: TaskSpec, demand: ResourceSet,
                                    conn=None, req_id: str = ""):
        sched, key = self._sched_for(ts)
        if sched is None:
            return {"error": "bundle not reserved",
                    "error_str": f"bundle {key} is not on node "
                                 f"{self.node_id[:12]}"}
        if not sched.resources.is_feasible(demand):
            return {"error": "infeasible",
                    "error_str": f"demand {demand.to_dict()} exceeds bundle "
                                 f"{key} capacity"}
        self._prefetch_args(ts)
        return await self._acquire_and_grant(sched, demand, key, ts, conn,
                                             req_id)

    async def _queue_for_resources(self, sched: LocalScheduler,
                                   demand: ResourceSet, wait_s: float,
                                   cancel_key: Optional[str] = None,
                                   registry: Optional[Dict] = None) -> str:
        """Enqueue demand on a scheduler's FIFO and wait for it.

        Returns "granted" (the demand's resources are acquired — note a
        bundle-removal wake also reports granted; callers re-check their
        bundle), "canceled" (dropped via cancel_key, nothing acquired),
        or "timeout" (nothing acquired).  Handles the
        granted-between-timeout-and-cancel race in one place for lease
        requests and bundle reservations alike."""
        token = object()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._lease_waiters[token] = (fut, demand, sched)
        if registry is not None and cancel_key is not None:
            registry[cancel_key] = (token, sched)
        sched.enqueue(token, demand)
        if sched is self.local:
            # only node-pool demand benefits from reclaiming lingering
            # leases; bundle-internal queues resolve within the bundle
            self._reclaim_idle_leases()
        try:
            res = await asyncio.wait_for(fut, wait_s)
        except asyncio.TimeoutError:
            found, granted = sched.cancel(token)
            self._lease_waiters.pop(token, None)
            for tok in granted:
                self._grant_token(tok)
            if not found and fut.done() and not fut.cancelled() \
                    and fut.result() != "canceled":
                return "granted"  # won the race; resources are ours
            # if fut is cancelled, _grant_token already gave the
            # acquired resources back — nothing more to do here
            return "timeout"
        finally:
            if registry is not None and cancel_key is not None:
                registry.pop(cancel_key, None)
        return "canceled" if res == "canceled" else "granted"

    async def _acquire_and_grant(self, sched: LocalScheduler,
                                 demand: ResourceSet, bundle_key: str,
                                 ts: Optional[TaskSpec] = None, conn=None,
                                 req_id: str = ""):
        if sched.try_acquire(demand):
            return await self._grant_safe(sched, demand, bundle_key, ts, conn)
        # a deadlined spec queues only for its remaining budget: a lease
        # request whose task can no longer finish in time is dropped
        # from the FIFO and the owner notified (it fails the expired
        # tasks fast instead of letting them camp on this agent's queue)
        wait_s = config.worker_lease_timeout_ms / 1000.0
        dl = ts.deadline if ts is not None else 0.0
        if dl:
            rem = dl - time.time()
            if rem <= 0:
                return {"error": "deadline exceeded",
                        "error_str": "task deadline expired before a "
                                     "worker lease was available"}
            wait_s = min(wait_s, rem)
        status = await self._queue_for_resources(
            sched, demand, wait_s,
            cancel_key=req_id or None, registry=self._lease_req_tokens)
        if status == "canceled":
            # owner's demand drained before a grant; nothing was acquired
            return {"error": "canceled",
                    "error_str": "lease request canceled by owner"}
        if status == "timeout":
            if dl and time.time() >= dl:
                return {"error": "deadline exceeded",
                        "error_str": "task deadline expired while queued "
                                     "for a worker lease"}
            return {"error": "lease timeout",
                    "error_str": "timed out waiting for resources"}
        if bundle_key and bundle_key not in self._bundles:
            # woken because the bundle was removed, not granted
            return {"error": "bundle not reserved",
                    "error_str": "placement group removed while queued"}
        return await self._grant_safe(sched, demand, bundle_key, ts, conn)

    async def rpc_cancel_lease_request(self, req_id: str):
        """Owner-side demand for a queued lease request drained: drop it
        from the FIFO so it is never granted into an idle linger
        (reference: node_manager.proto CancelWorkerLease)."""
        entry = self._lease_req_tokens.pop(req_id, None)
        if entry is None:
            return {"ok": False}  # unknown, or already granted
        token, sched = entry
        waiter = self._lease_waiters.pop(token, None)
        if waiter is None:
            return {"ok": False}  # granted in the meantime
        fut = waiter[0]
        _found, granted = sched.cancel(token)
        for tok in granted:
            self._grant_token(tok)
        if not fut.done():
            fut.set_result("canceled")
        return {"ok": True}

    def _reclaim_idle_leases(self) -> None:
        """Demand just queued behind granted leases: ask every lease's
        owner to hand back warm-pooled leases RIGHT NOW instead of
        letting them sit out the owner-side warm-lease TTL (worker.py
        _WARM_LEASE_TTL_S).  The push carries the aggregate queued
        demand so owners return only enough capacity to cover it and
        keep the rest of their pool warm.  Best-effort oneway pushes; an
        owner that just assigned a task simply ignores the request.
        This is what keeps placement-group reservation latency flat
        right after a task burst (reference: the raylet revoking unused
        workers via ReleaseUnusedWorkers when demand arrives)."""
        now = time.monotonic()
        if now - self._last_reclaim < 0.05:  # coalesce bursts of queuers
            # trailing edge: a waiter that queued just after the last
            # push still gets its demand to owners once the window ends
            # (the need snapshot below is recomputed at fire time), so
            # owners' need-bounded covered() check can't strand it until
            # the warm-lease TTL sweep
            if not self._reclaim_followup:
                self._reclaim_followup = True

                def _fire():
                    self._reclaim_followup = False
                    # only node-pool waiters count — they are what the
                    # need snapshot aggregates; a push for a purely
                    # bundle-internal queue would carry need={}, which
                    # owners read as unbounded and answer by evicting
                    # their whole warm pool
                    if any(sched is self.local for _, _, sched
                           in self._lease_waiters.values()):
                        self._reclaim_idle_leases()

                asyncio.get_running_loop().call_later(
                    0.05 - (now - self._last_reclaim), _fire)
            return
        self._last_reclaim = now
        conns = {id(l.owner_conn): l.owner_conn
                 for l in self._leases.values()
                 if l.owner_conn is not None}

        # aggregate node-pool demand currently queued (bundle-internal
        # queues resolve within their bundle and are excluded)
        need: Dict[str, float] = {}
        for fut, demand, sched in self._lease_waiters.values():
            if sched is not self.local:
                continue
            for k, v in demand.to_dict().items():
                need[k] = need.get(k, 0.0) + v

        payload = {"agent": [self.host, self.port], "need": need}

        async def _push(conn):
            try:
                await conn.push("reclaim_idle_leases", payload)
            except Exception:
                pass

        for conn in conns.values():
            asyncio.ensure_future(_push(conn))

    def _grant_token(self, token: object):
        entry = self._lease_waiters.pop(token, None)
        if entry is None:
            return
        fut, demand, sched = entry
        if not fut.done():
            fut.set_result(True)
        else:
            # waiter gave up after the queue acquired on its behalf
            for tok in sched.release(demand):
                self._grant_token(tok)

    def _drain_lease_queue(self):
        # unblocked-but-unreacquired leases first: they represent work
        # ALREADY running oversubscribed, ahead of queued new work
        self._retry_unblocks()
        for sched in [self.local, *self._bundles.values()]:
            for tok in sched.drain():
                self._grant_token(tok)

    async def _grant_safe(self, sched: LocalScheduler, demand: ResourceSet,
                          bundle_key: str = "",
                          ts: Optional[TaskSpec] = None, conn=None):
        """_grant, releasing the already-acquired resources if it raises
        unexpectedly — a grant-path bug must not leak node capacity."""
        try:
            return await self._grant(sched, demand, bundle_key, ts, conn)
        except Exception as exc:
            for tok in sched.release(demand):
                self._grant_token(tok)
            return {"error": "grant failed",
                    "error_str": f"{type(exc).__name__}: {exc}"}

    async def _grant(self, sched: LocalScheduler, demand: ResourceSet,
                     bundle_key: str = "", ts: Optional[TaskSpec] = None,
                     conn=None):
        # `demand` resources are already acquired from `sched`
        renv = ts.runtime_env if ts is not None else {}
        try:
            worker = await self._pop_worker(renv)
        except RuntimeEnvSetupError as exc:
            worker = None
            for tok in sched.release(demand):
                self._grant_token(tok)
            return {"error": "runtime env setup failed",
                    "error_str": str(exc)}
        if worker is None:
            for tok in sched.release(demand):
                self._grant_token(tok)
            return {"error": "worker spawn failed",
                    "error_str": "could not start a worker process"}
        self._lease_counter += 1
        lease_id = f"{self.node_id[:12]}-{self._lease_counter}"
        lease = _Lease(lease_id, worker, demand, bundle_key,
                       seq=self._lease_counter, owner_conn=conn,
                       owner_id=ts.caller_id if ts is not None else "",
                       owner_addr=ts.owner_addr if ts is not None else None,
                       # actors hold their lease for life: killing one is
                       # an actor death, never a transparent task retry.
                       # Normal tasks are ALWAYS OOM-retriable — even
                       # max_retries=0 ones, since watchdog kills draw
                       # from the separate task_oom_retries budget
                       retriable=(ts is not None
                                  and ts.kind == NORMAL_TASK),
                       fid=ts.function_id if ts is not None else "",
                       task_name=(ts.name or ts.method_name)
                       if ts is not None else "")
        n_tpu = int(demand.to_dict().get("TPU", 0))
        take = min(n_tpu, len(self._free_tpu_chips))
        if take > 0:
            lease.tpu_chips = self._free_tpu_chips[:take]
            del self._free_tpu_chips[:take]
        worker.lease_id = lease_id
        self._leases[lease_id] = lease
        if conn is not None and conn.writer.is_closing():
            # the owner's connection died while the worker spawned: the
            # reply goes nowhere and on_peer_disconnect scanned BEFORE
            # this lease existed — hand it straight back (worker idles
            # for reuse) instead of leaking it forever
            asyncio.ensure_future(
                self.rpc_return_lease(lease_id, kill_worker=False))
            return {"error": "caller disconnected",
                    "error_str": "owner connection closed mid-grant"}
        return {"granted": {
            "lease_id": lease_id,
            "worker_id": worker.worker_id,
            "addr": [self.host, worker.port],
            "node_id": self.node_id,
            "tpu_chips": lease.tpu_chips,
        }}

    def _spawn_gate(self) -> asyncio.Semaphore:
        if self._spawn_sem is None:
            self._spawn_sem = asyncio.Semaphore(
                max(1, int(config.worker_startup_parallelism)))
        return self._spawn_sem

    async def _pop_worker(self, renv: Optional[Dict[str, Any]] = None
                          ) -> Optional[_Worker]:
        from ray_tpu._private.runtime_env import env_key as _env_key

        renv = renv or {}
        key = _env_key(renv)
        spawn_kwargs: Dict[str, Any] = {}
        if renv:
            # materialize BEFORE spawning: fetch/extract packages once
            # per content hash (cached under session_dir/runtime_envs)
            from ray_tpu._private import runtime_env as renv_mod

            try:
                env_vars, working_dir, path_dirs = await renv_mod.materialize(
                    renv, self.session_dir, self._head)
            except Exception as exc:
                raise RuntimeEnvSetupError(
                    f"runtime env materialization failed: {exc}") from exc
            spawn_kwargs = {"env_key": key, "extra_env": env_vars,
                            "working_dir": working_dir,
                            "path_dirs": path_dirs}
        def pop_idle() -> Optional[_Worker]:
            for i in range(len(self._idle) - 1, -1, -1):
                w = self._idle[i]
                if w.env_key != key:
                    continue
                del self._idle[i]
                if w.proc.poll() is None:
                    return w
                self._on_worker_dead(w.worker_id, "dead on pop")
            return None

        for _attempt in range(3):
            w = pop_idle()
            if w is not None:
                return w
            # spawn throttle: N concurrent lease grants must not fork N
            # interpreters at once — an unbounded spawn storm (200 actor
            # creations) starves every child of CPU until ALL of them
            # miss the register timeout and the whole batch dies.  The
            # gate bounds concurrent starting workers to
            # worker_startup_parallelism; the register-timeout clock only
            # starts once the spawn actually begins.
            async with self._spawn_gate():
                w = pop_idle()  # freed while queued at the gate
                if w is not None:
                    return w
                w = self._spawn_worker(**spawn_kwargs)
                try:
                    await asyncio.wait_for(w.ready.wait(),
                                           config.worker_register_timeout_s)
                except asyncio.TimeoutError:
                    try:
                        w.proc.kill()
                    except Exception:
                        pass
                    self._on_worker_dead(w.worker_id, "startup timeout")
                    return None
            if w.worker_id not in self._workers:  # died during startup
                return None
            if w.lease_id is not None:
                # a queued lease drained on worker_ready and claimed this
                # worker before our wait resumed — start over
                continue
            if w in self._idle:
                self._idle.remove(w)
            return w
        return None

    def _lease_sched(self, lease: _Lease) -> LocalScheduler:
        if lease.bundle_key:
            sched = self._bundles.get(lease.bundle_key)
            if sched is not None:
                return sched
            # bundle already returned: its resources went back to the
            # node pool wholesale; nothing further to release
            return LocalScheduler(NodeResources(lease.resources))
        return self.local

    async def rpc_return_lease(self, lease_id: str, kill_worker: bool = False):
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return {"ok": False}
        self._free_tpu_chips.extend(lease.tpu_chips)
        w = lease.worker
        w.lease_id = None
        if kill_worker or w.proc.poll() is not None:
            try:
                w.proc.terminate()
            except Exception:
                pass
        else:
            w.idle_since = time.monotonic()
            self._idle.append(w)
        self._release_lease_resources(lease)
        return {"ok": True}

    def _release_lease_resources(self, lease: _Lease) -> None:
        """Return a finished lease's still-held resources to the pool —
        the full set normally, or only the undonated (accelerator)
        remainder when the lease died/returned while blocked."""
        if lease.blocked and lease.donated is not None:
            donated_keys = set(lease.donated.to_dict())
            held = ResourceSet({k: v for k, v in
                                lease.resources.to_dict().items()
                                if k not in donated_keys})
        else:
            held = lease.resources
        sched = self._lease_sched(lease)
        sched.resources.release(held)
        # already-running oversubscribed work re-acquires BEFORE queued
        # new work gets the freed capacity
        self._retry_unblocks()
        for tok in sched.drain():
            self._grant_token(tok)
        self._hb_wake.set()

    # ---- blocked-worker resource release -----------------------------------
    # A worker blocked in get() inside a task hands its lease's resources
    # back so nested tasks can schedule — without this, N-deep task
    # nesting deadlocks once depth exceeds the node's CPU count
    # (reference: node_manager.cc HandleWorkerBlocked: "the worker is
    # blocked waiting for objects; release its CPU resources").

    def _lease_of_worker(self, worker_id: str) -> Optional[_Lease]:
        w = self._workers.get(worker_id)
        if w is None or w.lease_id is None:
            return None
        return self._leases.get(w.lease_id)

    async def rpc_worker_blocked(self, worker_id: str):
        lease = self._lease_of_worker(worker_id)
        if lease is not None:
            # a worker re-blocking must cancel any stale pending
            # re-acquire — retrying it would hand CPU to a worker that is
            # genuinely blocked, starving the nested task it waits on
            self._unblock_pending.discard(lease.lease_id)
        if lease is not None and not lease.blocked:
            # CPU only, exactly the reference's HandleWorkerBlocked:
            # accelerator counts map to concrete chips the lease keeps,
            # gang-anchor resources (TPU-<type>-head, node:<id>) must not
            # double-place while their holder merely waits on objects
            cpu = lease.resources.to_dict().get("CPU", 0.0)
            if cpu > 0:
                donated = ResourceSet({"CPU": cpu})
                lease.blocked = True
                lease.donated = donated
                for tok in self._lease_sched(lease).release(donated):
                    self._grant_token(tok)
        return {"ok": True}

    async def rpc_worker_unblocked(self, worker_id: str):
        lease = self._lease_of_worker(worker_id)
        if lease is not None and lease.blocked:
            self._try_reacquire(lease)
            if lease.blocked:
                # pool busy right now: _drain_lease_queue retries on
                # every release, so the oversubscription window closes
                # as soon as capacity frees
                self._unblock_pending.add(lease.lease_id)
        return {"ok": True}

    def _try_reacquire(self, lease: _Lease) -> None:
        """Direct re-acquire, bypassing the FIFO queue: the task is
        already running and must not stall behind queued leases."""
        if self._lease_sched(lease).resources.acquire(lease.donated):
            lease.blocked = False
            lease.donated = None
            self._unblock_pending.discard(lease.lease_id)

    def _retry_unblocks(self) -> None:
        for lease_id in list(self._unblock_pending):
            lease = self._leases.get(lease_id)
            if lease is None or not lease.blocked:
                self._unblock_pending.discard(lease_id)
                continue
            self._try_reacquire(lease)

    # ---- live introspection (profiling.py + log_monitor.py) ----------------

    def on_peer_disconnect(self, conn) -> None:
        self._log.unsubscribe(conn)
        # leases granted over this connection die with it: an owner that
        # exited without returning its leases (driver crash, or a clean
        # shutdown racing the warm-pool TTL sweep) would otherwise pin
        # node capacity forever — with batched grants a single dead
        # owner could hold EVERY cpu (reference: raylet DisconnectClient
        # destroying the client's leased workers).  Head-granted actor
        # leases carry owner_conn=None (grant_only) and are exempt: a
        # head connection blip must never kill live actors.  The reap
        # waits out a grace window first: a TRANSIENT drop from a live
        # owner is survivable — its next lease request (reconnect-on-
        # demand) re-binds the leases to the new connection.
        orphaned = [lid for lid, lease in self._leases.items()
                    if lease.owner_conn is conn]
        if orphaned:
            asyncio.get_running_loop().call_later(
                float(config.lease_orphan_grace_s),
                self._reap_orphans, conn, orphaned)

    def _reap_orphans(self, conn, lease_ids: List[str]) -> None:
        asyncio.ensure_future(self._reap_orphans_async(conn, lease_ids))

    async def _reap_orphans_async(self, conn, lease_ids: List[str]) -> None:
        leases = [l for l in (self._leases.get(lid) for lid in lease_ids)
                  if l is not None and l.owner_conn is conn]
        if not leases:
            return  # returned, or re-bound by a reconnected owner
        owner_addr = next((l.owner_addr for l in leases if l.owner_addr),
                          None)
        if owner_addr is not None:
            # the control connection dropped but the owner may be alive
            # (transient network blip, long-running tasks needing no new
            # leases): ping its own RPC server before killing anything.
            # A live owner keeps its leases — it returns them itself
            # (warm-pool TTL sweep / explicit returns, both of which
            # work over a fresh connection).
            probe = RpcClient(owner_addr[0], owner_addr[1],
                              label="owner-probe")
            try:
                await probe.call("ping", timeout=3.0)
                return  # owner alive
            except Exception:
                pass  # unreachable: genuinely dead — reclaim
            finally:
                await probe.close()
        for lease in leases:
            if lease.owner_conn is conn:  # still unclaimed
                await self.rpc_return_lease(lease.lease_id,
                                            kill_worker=True)

    def _rebind_owner_leases(self, caller_id: str, conn) -> None:
        """An owner is talking to us on `conn`: any lease it holds whose
        recorded connection has died (transient drop, since replaced)
        re-binds here, cancelling the pending orphan reap for it."""
        if not caller_id or conn is None:
            return
        for lease in self._leases.values():
            if (lease.owner_id == caller_id
                    and lease.owner_conn is not None
                    and lease.owner_conn is not conn
                    and lease.owner_conn.writer.is_closing()):
                lease.owner_conn = conn

    async def rpc_subscribe_logs(self, tail: int = 0, _conn=None):
        """Stream this node's worker-log increments to the caller as
        ``log_lines`` oneway pushes on this connection (reference:
        _private/log_monitor.py:103 — the driver-side `(pid=, node=)`
        log streaming).  Returns up to ``tail`` backlog lines/file."""
        if _conn is None:
            return {"ok": False, "error": "no connection"}
        backlog = self._log.subscribe(_conn, tail=int(tail))
        return {"ok": True, "node_id": self.node_id, "backlog": backlog}

    async def rpc_unsubscribe_logs(self, _conn=None):
        if _conn is not None:
            self._log.unsubscribe(_conn)
        return {"ok": True}

    async def rpc_tail_logs(self, lines: int = 100):
        """One-shot: last N lines of every worker log this agent owns."""
        return {"ok": True, "node_id": self.node_id,
                "batch": self._log.tail(int(lines))}

    async def _call_worker(self, w: _Worker, method: str, timeout: float,
                           **payload):
        """Introspection RPC to a pooled worker's server over a pooled
        per-worker client (reconnect-on-demand): the 5s memory scan
        fans out to every worker, so a transient connection per call
        would be N dial/close cycles per scan, forever.  Closed by
        _on_worker_dead / stop()."""
        if w.iclient is None:
            w.iclient = RpcClient("127.0.0.1", w.port,
                                  label=f"introspect-{w.pid}")
        return await w.iclient.call(method, timeout=timeout, **payload)

    async def rpc_node_stacks(self, timeout_s: float = 5.0):
        """Aggregate live stack dumps: this agent process plus every
        ready worker it pools (the `rtpu stack <node>` payload)."""
        from ray_tpu._private.profiling import proc_stack_payload

        result: Dict[str, Any] = {"node_id": self.node_id,
                                  "agent": proc_stack_payload(),
                                  "workers": {}}

        async def one(w: _Worker):
            try:
                result["workers"][w.worker_id] = await asyncio.wait_for(
                    self._call_worker(w, "proc_stack", timeout_s),
                    timeout_s + 1.0)
            except Exception as e:
                result["workers"][w.worker_id] = {
                    "pid": w.pid, "error": f"{type(e).__name__}: {e}"}

        await asyncio.gather(*(one(w) for w in list(self._workers.values())
                               if w.ready.is_set() and w.port
                               and w.proc.poll() is None))
        return result

    async def rpc_profile_worker(self, worker: str, hz: float = 0,
                                 duration_s: float = 2.0,
                                 fmt: str = "collapsed"):
        """Proxy a sampling-profiler run to one of this node's workers
        (matched by worker-id prefix).  Blocks for the duration."""
        target = next((w for wid, w in self._workers.items()
                       if wid.startswith(worker) and w.ready.is_set()
                       and w.port and w.proc.poll() is None), None)
        if target is None:
            return {"found": False}
        reply = await self._call_worker(
            target, "profile", float(duration_s) + 30.0, op="run", hz=hz,
            duration_s=duration_s, fmt=fmt)
        reply["found"] = True
        reply["worker_id"] = target.worker_id
        return reply

    # ---- misc --------------------------------------------------------------

    async def rpc_node_info(self):
        return {
            "node_id": self.node_id,
            "addr": [self.host, self.port],
            "arena_path": self.arena_path,
            "resources": self.resources.to_dict(),
            "num_workers": len(self._workers),
            "num_idle": len(self._idle),
            "num_leases": len(self._leases),
            "draining": self._draining,
            "store": self.store.usage(),
            "xfer_port": self.xfer_port,
            "xfer_stats": dict(self.xfer_stats),
        }

    async def rpc_ping(self):
        return {"pong": True}

    async def rpc_shutdown_node(self):
        self._shutdown.set()


def main():
    """Entry: `python -m ray_tpu._private.node_agent ...`."""
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--head-host", required=True)
    ap.add_argument("--head-port", type=int, required=True)
    ap.add_argument("--session-dir", required=True)
    ap.add_argument("--resources", default="{}")  # JSON dict
    ap.add_argument("--capacity", type=int, default=0)
    ap.add_argument("--is-head-node", action="store_true")
    ap.add_argument("--port-file", default="")
    ap.add_argument("--node-id", default="")
    ap.add_argument("--labels", default="{}")  # JSON dict
    args = ap.parse_args()

    async def run():
        agent = NodeAgent(
            (args.head_host, args.head_port), args.session_dir,
            json.loads(args.resources), capacity=args.capacity,
            is_head_node=args.is_head_node, node_id=args.node_id,
            labels=json.loads(args.labels))
        port = await agent.start()
        if args.port_file:
            tmp = args.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{port}\n{agent.node_id}\n{agent.arena_path}")
            os.replace(tmp, args.port_file)
        sys.stdout.write(f"ray_tpu node agent {agent.node_id[:12]} on port {port}\n")
        sys.stdout.flush()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, agent._shutdown.set)
        await agent.wait_for_shutdown()
        await agent.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
