"""Streaming generator returns (``num_returns="streaming"``).

TPU-native re-imagining of the reference's streaming generator machinery
(reference: python/ray/_raylet.pyx:272 ObjectRefGenerator, :1104
execute_streaming_generator_*; core_worker.proto
ReportGeneratorItemReturns): a task or actor method whose body is a
generator reports each yielded value to its owner AS IT IS PRODUCED —
the owner consumes items while the task is still running, which is what
token-streaming inference (the TPU serving shape) and streaming data
ingestion ride on.

Design differences from the reference, on purpose:
  * items ride the already-open owner->worker RPC connection as oneway
    server->client pushes (ordered by TCP), not a separate
    ReportGeneratorItemReturns RPC with acks — one in-order byte stream
    replaces the reference's item-index reordering buffer;
  * item ObjectIDs reuse the deterministic return-index scheme
    (ObjectID.from_index(task_id, i+1)), so a streamed item IS an
    ordinary owned object afterwards: plasma-stored when large, inline
    in the owner's memory store when small, gettable/borrowable like any
    return value;
  * backpressure is the transport's (TCP + the consumer draining);
    the reference's _generator_backpressure_num_objects is not needed
    for the target workloads (small token/batch items).

Known limits (v1, documented not hidden): streaming tasks are not
automatically retried on worker death (consumed prefixes cannot be
un-consumed; the error surfaces at the next ``__next__``), and an
``ObjectRefGenerator`` cannot be pickled or passed to another task.
"""

from __future__ import annotations

import threading
from typing import Optional

from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef

STREAMING = -1  # TaskSpec.num_returns wire value for streaming tasks


class StreamState:
    """Owner-side record of one in-flight generator task's stream."""

    __slots__ = ("arrived", "total", "error", "event", "_async_waiters",
                 "_wlock")

    def __init__(self):
        self.arrived = 0                 # contiguous items reported so far
        self.total: Optional[int] = None  # set when the task finishes
        self.error: Optional[BaseException] = None
        self.event = threading.Event()   # wakes blocked consumers
        # one-shot zero-arg callbacks fired on any stream transition
        # (item arrival, error, completion) — the async consumption path
        # (next_ref_async) parks event-loop futures here instead of a
        # thread on `event`
        self._async_waiters: list = []
        self._wlock = threading.Lock()

    def wake(self) -> None:
        self.event.set()
        with self._wlock:
            waiters, self._async_waiters = self._async_waiters, []
        for cb in waiters:
            try:
                cb()
            except Exception:
                pass

    def add_async_waiter(self, cb) -> None:
        """Register a callback for the next wake().  Callers MUST
        re-check stream state after registering (a wake between their
        check and the registration is otherwise lost) — a stale callback
        firing later is harmless, so no dedup/removal is needed."""
        with self._wlock:
            self._async_waiters.append(cb)


class ObjectRefGenerator:
    """Iterator of ObjectRefs for a streaming task's yields.

    Each ``__next__`` blocks until the worker has reported item i, then
    returns an ObjectRef resolving to it (already local to the owner:
    inline bytes in the memory store, or a recorded plasma location).
    Ends with StopIteration after the task finishes and every yielded
    item has been handed out; a task error raises at the position where
    the stream broke (items before it stay consumable).
    """

    def __init__(self, worker, task_id: str):
        self._worker = worker
        self._task_id = task_id
        self._next = 0

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        return self.next_ref(timeout=None)

    @property
    def task_id(self) -> str:
        return self._task_id

    def next_ref(self, timeout: Optional[float] = None) -> ObjectRef:
        """__next__ with an optional timeout (raises TimeoutError)."""
        import time as _time

        w = self._worker
        s = w._streams.get(self._task_id)
        if s is None:
            raise StopIteration
        deadline = None if timeout is None else _time.monotonic() + timeout
        # consuming inside a task blocks this worker like get() does:
        # donate the lease's CPU so the producer can schedule on a full
        # node (reference: HandleWorkerBlocked — same rule as get)
        notify = self._should_notify(s)
        if notify:
            w._notify_blocked(True)
        try:
            while True:
                if self._next < s.arrived:
                    tid = TaskID.from_hex(self._task_id)
                    oid = ObjectID.from_index(tid, self._next + 1).hex()
                    self._next += 1
                    return ObjectRef(oid, owner_addr=w.address)
                if s.error is not None:
                    raise s.error
                if s.total is not None and self._next >= s.total:
                    w._streams.pop(self._task_id, None)
                    raise StopIteration
                s.event.clear()
                # re-check after clear: the producer may have fired
                # between the checks above and the clear (lost-wake
                # guard).  total alone is not progress — only
                # total-with-all-items-handed-out is (a broader check
                # would spin when total lands before trailing items)
                if (self._next < s.arrived or s.error is not None
                        or (s.total is not None and self._next >= s.total)):
                    continue
                remaining = None if deadline is None \
                    else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"no streamed item within {timeout}s")
                s.event.wait(min(0.5, remaining) if remaining is not None
                             else 0.5)
        finally:
            if notify:
                w._notify_blocked(False)

    def __aiter__(self) -> "ObjectRefGenerator":
        return self

    async def __anext__(self) -> ObjectRef:
        return await self.next_ref_async()

    async def next_ref_async(self,
                             timeout: Optional[float] = None) -> ObjectRef:
        """Awaitable ``__next__``: resolves on the calling event loop via
        stream-state wake callbacks — no thread parked per consumer (the
        async Serve ingress awaits many streams on one loop).  Raises
        StopAsyncIteration at end-of-stream and TimeoutError on timeout.

        Unlike the sync path this never donates the lease's CPU
        (worker-blocked notification): it is meant for event-loop
        consumers (driver/proxy loops), which hold no exec lease."""
        import asyncio
        import time as _time

        w = self._worker
        s = w._streams.get(self._task_id)
        if s is None:
            raise StopAsyncIteration
        loop = asyncio.get_running_loop()
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            if self._next < s.arrived:
                tid = TaskID.from_hex(self._task_id)
                oid = ObjectID.from_index(tid, self._next + 1).hex()
                self._next += 1
                return ObjectRef(oid, owner_addr=w.address)
            if s.error is not None:
                raise s.error
            if s.total is not None and self._next >= s.total:
                w._streams.pop(self._task_id, None)
                raise StopAsyncIteration
            fut = loop.create_future()
            s.add_async_waiter(lambda: loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(None)))
            # lost-wake guard: the producer may have fired between the
            # checks above and the registration — re-check before
            # parking.  The total condition must include the index
            # comparison: total-set-with-items-still-in-flight would
            # otherwise spin here without awaiting or timing out.
            if (self._next < s.arrived or s.error is not None
                    or (s.total is not None and self._next >= s.total)):
                continue
            remaining = None if deadline is None \
                else deadline - _time.monotonic()
            if remaining is not None and remaining <= 0:
                raise TimeoutError(f"no streamed item within {timeout}s")
            try:
                # 0.5 s recheck cap mirrors the sync path's robustness
                # against a missed wake; the common case resolves via the
                # callback well before it
                await asyncio.wait_for(
                    fut, min(0.5, remaining) if remaining is not None
                    else 0.5)
            except asyncio.TimeoutError:
                pass

    def _should_notify(self, s: StreamState) -> bool:
        from ray_tpu._private.worker import MODE_WORKER

        w = self._worker
        return (w.mode == MODE_WORKER and bool(w._exec.task_id)
                and not (self._next < s.arrived or s.total is not None
                         or s.error is not None))

    def completed(self) -> bool:
        s = self._worker._streams.get(self._task_id)
        return s is None or s.total is not None or s.error is not None

    def cancel(self) -> None:
        """Fire-and-forget cancellation of the producing task: the
        worker raises TaskCancelledError in the replica-side generator,
        whose finally releases whatever it holds (an LLM decode's KV
        pages, file handles, ...).  Non-blocking — posted to the owner's
        IO loop so an event-loop caller (the Serve proxy tearing down an
        abandoned SSE stream) is never parked behind the cancel RPC."""
        w = self._worker
        try:
            w._spawn(w._cancel_async(self._task_id, False))
        except Exception:
            pass  # runtime shutting down: the stream dies with it

    def __reduce__(self):
        raise TypeError(
            "ObjectRefGenerator cannot be pickled or passed to tasks; "
            "consume it in the owning process")

    def __del__(self):
        # stop accepting items for an abandoned stream; already-arrived
        # unconsumed items are released with the owner's memory store
        try:
            self._worker._streams.pop(self._task_id, None)
        except Exception:
            pass
