"""User-facing exception taxonomy.

Equivalent of the reference's exception set
(reference: python/ray/exceptions.py — RayError, RayTaskError,
RayActorError, WorkerCrashedError, ObjectLostError, ObjectFreedError,
GetTimeoutError).
"""

from __future__ import annotations


class RayError(Exception):
    """Base for all framework errors."""


class RayTaskError(RayError):
    """A task/actor method raised; carries the remote traceback.

    Like the reference (python/ray/exceptions.py RayTaskError.as_instanceof_cause),
    the original exception is chained as `cause` when it was picklable.
    """

    def __init__(self, function_name: str, traceback_str: str,
                 cause: BaseException | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"{function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        # default exception pickling would replay __init__ with the joined
        # message as the only argument; rebuild from the real fields
        return (type(self), (self.function_name, self.traceback_str, self.cause))


class RayWorkerError(RayError):
    """The worker process executing the task died."""


class OutOfMemoryError(RayWorkerError):
    """The node agent's memory watchdog deliberately killed the worker
    running this task because node memory crossed
    ``memory_usage_threshold`` — a kill with a receipt, not a mystery
    death (reference: python/ray/exceptions.py OutOfMemoryError +
    memory_monitor.h).  Carries the victim's RSS and the node's memory
    breakdown at kill time.  Subclasses RayWorkerError so every handler
    that treats worker death as retriable replica/worker loss (Serve
    dead-replica retry, the circuit breaker's error accounting) applies
    unchanged.  Owner-side, OOM kills draw from the separate
    ``task_oom_retries`` budget — never from ``max_retries``."""

    def __init__(self, message: str = "worker killed by the memory "
                 "monitor", rss_bytes: int = 0, node_usage: float = 0.0,
                 node_id: str = "", worker_id: str = "",
                 breakdown: dict | None = None):
        self.rss_bytes = int(rss_bytes)
        self.node_usage = float(node_usage)
        self.node_id = node_id
        self.worker_id = worker_id
        # node memory breakdown at kill time (per-worker RSS list +
        # store arena buckets) — the "receipt" the owner can log/act on
        self.breakdown = dict(breakdown or {})
        super().__init__(message)

    def __reduce__(self):
        return (type(self), (str(self.args[0]) if self.args else "",
                             self.rss_bytes, self.node_usage,
                             self.node_id, self.worker_id,
                             self.breakdown))


class PoisonedTaskError(RayError):
    """Submissions of this task/actor class are quarantined: its
    executions OOM-killed or crashed workers ``poison_task_threshold``
    consecutive times across the cluster, so further attempts would
    only churn workers.  Fails fast at submission/lease time with the
    kill history instead of burning retries into the same wall.  The
    quarantine expires after ``poison_task_ttl_s`` and can be lifted
    early via ``rtpu quarantine clear``."""

    def __init__(self, message: str = "task class is quarantined",
                 key: str = "", history: list | None = None):
        self.key = key          # function/class id the quarantine keys on
        self.history = list(history or [])  # human-readable kill records
        super().__init__(message)

    def __reduce__(self):
        return (type(self), (str(self.args[0]) if self.args else "",
                             self.key, self.history))


class ActorDiedError(RayError):
    """The actor is dead (creation failed, killed, or out of restarts)."""


class ActorUnavailableError(RayError):
    """The actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayError):
    """The object's value was lost (e.g. the node holding it died)."""


class ObjectFreedError(RayError):
    """The object was freed by its owner; the value is permanently gone."""


class GetTimeoutError(RayError, TimeoutError):
    """ray_tpu.get(..., timeout=...) expired."""


class DeadlineExceededError(RayError, TimeoutError):
    """The request's end-to-end deadline (``.options(timeout_s=...)``
    or an ``X-Request-Deadline-Ms`` ingress header) expired before the
    work completed.  Raised owner-side for tasks still queued, by the
    deadline sweep for running tasks, by ``get()`` when the ambient
    budget runs out, and by the LLM engine at admission when the
    remaining budget cannot cover prefill + one decode step
    (see _private/deadlines.py)."""

    def __init__(self, message: str = "deadline exceeded",
                 where: str = ""):
        self.where = where  # queued | running | get | admission
        super().__init__(message)

    def __reduce__(self):
        return (type(self), (str(self.args[0]) if self.args else
                             "deadline exceeded", self.where))


class SchedulingError(RayError):
    """The task's resource demand can never be satisfied by the cluster."""


class RuntimeEnvSetupError(RayError):
    """The task's runtime environment could not be prepared on the node
    (reference: python/ray/exceptions.py RuntimeEnvSetupError)."""


class TaskCancelledError(RayError):
    """The task was cancelled via ray_tpu.cancel()
    (reference: python/ray/exceptions.py TaskCancelledError)."""


class DeploymentFailedError(RayError):
    """A serve deployment could not come healthy: replica constructors
    failed or did not pass the health check within
    ``serve_replica_health_timeout_s``."""
