"""Function/actor-class table over the head's internal KV.

Equivalent of the reference's GCS function table
(reference: python/ray/_private/function_manager.py — export_function /
fetch_and_register; storage is internal KV keys "fn:<job>:<id>").

Functions are cloudpickled once per driver and cached per worker process.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict

import cloudpickle


class FunctionManager:
    def __init__(self, head_rpc):
        self._head = head_rpc  # SyncRpcClient to the head
        self._cache: Dict[str, Any] = {}
        self._exported: set = set()
        self._lock = threading.Lock()

    @staticmethod
    def function_id(pickled: bytes) -> str:
        return hashlib.sha256(pickled).hexdigest()[:40]

    def export(self, fn_or_class: Any) -> str:
        """Pickle and upload; returns the function id (content-addressed,
        so re-exports of the same code are free)."""
        pickled = cloudpickle.dumps(fn_or_class)
        fid = self.function_id(pickled)
        with self._lock:
            if fid in self._exported:
                return fid
        self._head.call("kv_put", key=f"fn:{fid}", value=pickled, overwrite=False)
        with self._lock:
            self._exported.add(fid)
            self._cache[fid] = fn_or_class
        return fid

    def fetch(self, fid: str) -> Any:
        with self._lock:
            if fid in self._cache:
                return self._cache[fid]
        reply = self._head.call("kv_get", key=f"fn:{fid}")
        blob = reply.get("value")
        if blob is None:
            raise KeyError(f"function {fid} not found in cluster function table")
        obj = cloudpickle.loads(blob)
        with self._lock:
            self._cache[fid] = obj
        return obj
