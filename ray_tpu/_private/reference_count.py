"""Distributed reference counting (v1).

Equivalent role to the reference's ReferenceCounter
(reference: src/ray/core_worker/reference_count.h): every object has an
owner (the worker that created it); the owner frees the object only when

  - its own local (Python) references are gone,
  - no in-flight task submission still carries the ref as an argument,
  - and every registered borrower has reported its references gone.

Borrowers are workers that deserialized the ref (from task args or from
another object); they register with the owner on first sight and send
`remove_borrow` when their local count drops to zero.  This is a
simplification of the reference's borrower chains (a borrower that
forwards a ref to a third worker tells that worker to register with the
*owner* directly, so the owner always has the full borrower set —
reference handles this with WaitForRefRemoved chains instead).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple


class _Ref:
    __slots__ = ("local", "submitted", "borrowers", "owned", "freed",
                 "lineage_pinned", "call_site", "name", "created")

    def __init__(self, owned: bool):
        self.local = 0
        self.submitted = 0           # in-flight task submissions using it
        self.borrowers: Set[Tuple[str, int]] = set()   # remote borrower addrs
        self.owned = owned
        self.freed = False
        self.lineage_pinned = False  # keep TaskSpec for lineage re-execution
        # memory accounting (`rtpu memory`): where the ref was minted
        # (user frame of the put()/.remote() call), the producing
        # task/actor-method name, and creation time for leak-TTL checks
        self.call_site = ""
        self.name = ""
        self.created = time.monotonic()


class ReferenceCounter:
    """Thread-safe; `on_release(oid)` fires (outside the lock) when an
    *owned* object's count reaches zero."""

    def __init__(self, on_release: Callable[[str], None]):
        self._lock = threading.Lock()
        self._refs: Dict[str, _Ref] = {}
        self._on_release = on_release

    # ---- local references --------------------------------------------------

    def add_local(self, oid: str, owned: bool) -> None:
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                ref = self._refs[oid] = _Ref(owned)
            ref.local += 1

    def remove_local(self, oid: str) -> bool:
        """Returns True if this was a *borrowed* ref whose count hit zero
        (caller should notify the owner)."""
        release = False
        borrowed_done = False
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                return False
            ref.local -= 1
            if ref.local <= 0 and ref.submitted <= 0:
                if ref.owned:
                    if not ref.borrowers and not ref.freed:
                        ref.freed = True
                        release = True
                else:
                    self._refs.pop(oid, None)
                    borrowed_done = True
        if release:
            self._on_release(oid)
        return borrowed_done

    # ---- submission pins ---------------------------------------------------

    def add_submitted(self, oid: str) -> None:
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                ref = self._refs[oid] = _Ref(owned=True)
            ref.submitted += 1

    def remove_submitted(self, oid: str) -> bool:
        release = False
        borrowed_done = False
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                return False
            ref.submitted -= 1
            if ref.local <= 0 and ref.submitted <= 0:
                if ref.owned:
                    if not ref.borrowers and not ref.freed:
                        ref.freed = True
                        release = True
                else:
                    self._refs.pop(oid, None)
                    borrowed_done = True
        if release:
            self._on_release(oid)
        return borrowed_done

    # ---- borrower protocol (owner side) ------------------------------------

    def add_borrower(self, oid: str, borrower: Tuple[str, int]) -> None:
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                ref = self._refs[oid] = _Ref(owned=True)
            ref.borrowers.add(tuple(borrower))

    def remove_borrower(self, oid: str, borrower: Tuple[str, int]) -> None:
        release = False
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                return
            ref.borrowers.discard(tuple(borrower))
            if (ref.local <= 0 and ref.submitted <= 0 and not ref.borrowers
                    and ref.owned and not ref.freed):
                ref.freed = True
                release = True
        if release:
            self._on_release(oid)

    # ---- introspection -----------------------------------------------------

    def set_meta(self, oid: str, call_site: str = "", name: str = "") -> None:
        """Attach creation metadata to an existing ref (no-op for unknown
        oids — the caller registers the ref first via add_local)."""
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                return
            if call_site:
                ref.call_site = call_site
            if name:
                ref.name = name

    def summary(self) -> List[Dict[str, Any]]:
        """One record per live ref — the worker half of `rtpu memory`
        (reference: CoreWorker's ownership-table dump behind `ray
        memory`).  Snapshot under the lock, dict-building outside it."""
        with self._lock:
            snap = [(oid, r.owned, r.local, r.submitted, len(r.borrowers),
                     r.lineage_pinned, r.call_site, r.name, r.created)
                    for oid, r in self._refs.items() if not r.freed]
        now = time.monotonic()
        return [{"oid": oid, "owned": owned, "local": local,
                 "submitted": submitted, "borrowers": borrowers,
                 "lineage_pinned": pinned, "call_site": cs, "name": name,
                 "age_s": round(now - created, 3)}
                for (oid, owned, local, submitted, borrowers, pinned,
                     cs, name, created) in snap]

    def count(self, oid: str) -> int:
        with self._lock:
            ref = self._refs.get(oid)
            return 0 if ref is None else ref.local + ref.submitted

    def owned_ids(self) -> List[str]:
        with self._lock:
            return [oid for oid, r in self._refs.items() if r.owned and not r.freed]

    def is_freed(self, oid: str) -> bool:
        with self._lock:
            ref = self._refs.get(oid)
            return ref is not None and ref.freed

    def pin_lineage(self, oid: str) -> None:
        with self._lock:
            ref = self._refs.get(oid)
            if ref is not None:
                ref.lineage_pinned = True
