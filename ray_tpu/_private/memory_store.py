"""In-process memory store for small objects.

Equivalent of the reference's CoreWorkerMemoryStore
(reference: src/ray/core_worker/store_provider/memory_store/memory_store.h):
inlined task returns and errors live here, keyed by object id; values too
large to inline are represented by an IN_PLASMA sentinel that redirects
`get` to the shared-memory store.

Thread model: the user thread blocks in `wait_ready`; the RPC IO thread
calls `set_*` — coordination is a per-entry threading.Event.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple


class _Entry:
    __slots__ = ("event", "value", "raw", "error", "in_plasma", "node_addr")

    def __init__(self):
        self.event = threading.Event()
        self.value: Any = None       # cached deserialized value
        self.raw: Optional[bytes] = None  # serialized inline bytes
        self.error: Optional[BaseException] = None
        self.in_plasma = False
        self.node_addr: Optional[Tuple[str, int]] = None


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}

    def _entry(self, oid: str) -> _Entry:
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                e = self._entries[oid] = _Entry()
            return e

    # ---- producer side -----------------------------------------------------

    def ensure(self, oid: str) -> None:
        """Pre-create a pending entry (a submitted task's future return)."""
        self._entry(oid)

    def set_value(self, oid: str, value: Any) -> None:
        e = self._entry(oid)
        e.value = value
        e.event.set()

    def set_raw(self, oid: str, raw: bytes) -> None:
        """Store serialized inline bytes; deserialized lazily on first get."""
        e = self._entry(oid)
        e.raw = raw
        e.event.set()

    def set_error(self, oid: str, error: BaseException) -> None:
        e = self._entry(oid)
        e.error = error
        e.event.set()

    def set_in_plasma(self, oid: str, node_addr: Tuple[str, int]) -> None:
        e = self._entry(oid)
        e.in_plasma = True
        e.node_addr = node_addr
        e.event.set()

    def reset(self, oid: str) -> None:
        """Forget a resolution (used when re-executing a task for recovery)."""
        with self._lock:
            self._entries.pop(oid, None)

    def clear_resolution(self, oid: str) -> None:
        """Flip a resolved entry back to pending IN PLACE, so existing
        waiters (holding the entry object) block until the recomputed
        value arrives.  A racing reader may still see the old resolution;
        its fetch fails and it retries through the reconstruction path."""
        with self._lock:
            e = self._entries.get(oid)
        if e is not None:
            e.event.clear()
            e.value = None
            e.raw = None
            e.error = None
            e.in_plasma = False
            e.node_addr = None

    # ---- consumer side -----------------------------------------------------

    def known(self, oid: str) -> bool:
        with self._lock:
            return oid in self._entries

    def ready(self, oid: str) -> bool:
        with self._lock:
            e = self._entries.get(oid)
        return e is not None and e.event.is_set()

    def peek(self, oid: str) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(oid)
        return e if e is not None and e.event.is_set() else None

    def wait_ready(self, oid: str, timeout: Optional[float] = None) -> Optional[_Entry]:
        """Block until the entry resolves; None on timeout or unknown id."""
        with self._lock:
            e = self._entries.get(oid)
        if e is None:
            return None
        if not e.event.wait(timeout):
            return None
        return e

    def wait_any(self, oids: List[str], num_ready: int,
                 timeout: Optional[float]) -> Set[str]:
        """Poll-free wait for `num_ready` of `oids` (for ray.wait).

        Uses a shared condition signaled piggyback on entry events via
        polling at a short interval — entries are also settable from the
        IO thread, so a simple bounded poll keeps this correct and simple.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: Set[str] = set()
        while True:
            for oid in oids:
                if oid not in ready and self.ready(oid):
                    ready.add(oid)
            if len(ready) >= num_ready:
                return ready
            if deadline is not None and time.monotonic() >= deadline:
                return ready
            remaining = 0.01 if deadline is None else min(
                0.01, max(0.0, deadline - time.monotonic()))
            time.sleep(remaining)

    def evict(self, oid: str) -> None:
        with self._lock:
            self._entries.pop(oid, None)
