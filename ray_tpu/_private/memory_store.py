"""In-process memory store for small objects.

Equivalent of the reference's CoreWorkerMemoryStore
(reference: src/ray/core_worker/store_provider/memory_store/memory_store.h):
inlined task returns and errors live here, keyed by object id; values too
large to inline are represented by an IN_PLASMA sentinel that redirects
`get` to the shared-memory store.

Thread model: the user thread blocks in `wait_ready`; the RPC IO thread
calls `set_*` — coordination is a per-entry threading.Event.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple


class _Entry:
    __slots__ = ("event", "value", "raw", "error", "in_plasma", "node_addr",
                 "waiters")

    def __init__(self):
        self.event = threading.Event()
        self.value: Any = None       # cached deserialized value
        self.raw: Optional[bytes] = None  # serialized inline bytes
        self.error: Optional[BaseException] = None
        self.in_plasma = False
        self.node_addr: Optional[Tuple[str, int]] = None
        self.waiters: Dict[int, Any] = {}  # token -> callback


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._waiter_tokens = 0

    def _entry(self, oid: str) -> _Entry:
        with self._lock:
            e = self._entries.get(oid)
            if e is None:
                e = self._entries[oid] = _Entry()
            return e

    def _fire(self, e: _Entry) -> None:
        e.event.set()
        with self._lock:
            waiters = list(e.waiters.values())
            e.waiters.clear()
        for cb in waiters:
            try:
                cb()
            except Exception:
                pass

    # ---- producer side -----------------------------------------------------

    def ensure(self, oid: str) -> None:
        """Pre-create a pending entry (a submitted task's future return)."""
        self._entry(oid)

    def set_value(self, oid: str, value: Any) -> None:
        e = self._entry(oid)
        e.value = value
        self._fire(e)

    def set_raw(self, oid: str, raw: bytes) -> None:
        """Store serialized inline bytes; deserialized lazily on first get."""
        e = self._entry(oid)
        e.raw = raw
        self._fire(e)

    def set_error(self, oid: str, error: BaseException) -> None:
        e = self._entry(oid)
        e.error = error
        self._fire(e)

    def set_in_plasma(self, oid: str, node_addr: Tuple[str, int]) -> None:
        e = self._entry(oid)
        e.in_plasma = True
        e.node_addr = node_addr
        self._fire(e)

    def fail_pending(self, error: BaseException) -> None:
        """Resolve every still-pending entry with an error — wakes all
        blocked waiters (get()s, dependency resolution threads parked on
        entry events).  Called at shutdown so no executor thread stays
        blocked on an object that can no longer arrive."""
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if not e.event.is_set():
                e.error = error
                self._fire(e)

    def reset(self, oid: str) -> None:
        """Forget a resolution (used when re-executing a task for recovery)."""
        with self._lock:
            self._entries.pop(oid, None)

    def clear_resolution(self, oid: str) -> None:
        """Flip a resolved entry back to pending IN PLACE, so existing
        waiters (holding the entry object) block until the recomputed
        value arrives.  A racing reader may still see the old resolution;
        its fetch fails and it retries through the reconstruction path."""
        with self._lock:
            e = self._entries.get(oid)
        if e is not None:
            e.event.clear()
            e.value = None
            e.raw = None
            e.error = None
            e.in_plasma = False
            e.node_addr = None

    # ---- consumer side -----------------------------------------------------

    def known(self, oid: str) -> bool:
        with self._lock:
            return oid in self._entries

    def ready(self, oid: str) -> bool:
        with self._lock:
            e = self._entries.get(oid)
        return e is not None and e.event.is_set()

    def peek(self, oid: str) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(oid)
        return e if e is not None and e.event.is_set() else None

    def wait_ready(self, oid: str, timeout: Optional[float] = None) -> Optional[_Entry]:
        """Block until the entry resolves; None on timeout or unknown id."""
        with self._lock:
            e = self._entries.get(oid)
        if e is None:
            return None
        if not e.event.wait(timeout):
            return None
        return e

    def add_waiter(self, oid: str, callback) -> Optional[int]:
        """Register a callback fired (once) when the entry resolves.

        Returns None and does NOT register if the entry is already ready
        (caller should count it immediately); otherwise returns a token
        for remove_waiter.  Callbacks run on the resolving thread (the
        RPC IO thread) and must not block.
        """
        e = self._entry(oid)
        with self._lock:
            if e.event.is_set():
                return None
            self._waiter_tokens += 1
            token = self._waiter_tokens
            e.waiters[token] = callback
            return token

    def remove_waiter(self, oid: str, token: int) -> None:
        with self._lock:
            e = self._entries.get(oid)
            if e is not None:
                e.waiters.pop(token, None)

    def evict(self, oid: str) -> None:
        with self._lock:
            self._entries.pop(oid, None)
