"""Per-task/actor runtime environments.

Equivalent of the reference's runtime_env subsystem
(reference: python/ray/_private/runtime_env/working_dir.py:1, pip.py:1,
packaging.py — the driver packages local dirs into content-addressed
zips uploaded to GCS; agents download + extract once per content hash;
workers start inside the env).

Supported keys:
  env_vars:    {str: str} merged into the worker's process env
  working_dir: local dir, packaged + extracted; worker chdirs into it
               and prepends it to sys.path
  py_modules:  list of local dirs, packaged; prepended to sys.path
  pip:         GATED — this image has no network; requirements already
               present in the base env pass (validated via
               importlib.metadata), anything else raises at submission

Packages travel through the head's internal KV (`pkg:<sha256>` keys) —
fine for the code-dir sizes these carry; bulk data belongs in the
object store.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import zipfile
from typing import Any, Dict, List, Optional, Tuple

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip", "config"}
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules", ".eggs"}
MAX_PACKAGE_BYTES = 100 * 1024 * 1024


class RuntimeEnvError(Exception):
    pass


def _package_dir(path: str) -> Tuple[str, bytes]:
    """Deterministic zip of a directory -> (sha256, bytes).

    Timestamps are pinned so identical trees hash identically across
    machines (reference: packaging.py's content-addressed pkg URIs).
    """
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise RuntimeEnvError(f"runtime_env dir does not exist: {path}")
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for fname in sorted(files):
            if fname.endswith(".pyc"):
                continue
            full = os.path.join(root, fname)
            entries.append((os.path.relpath(full, path), full))
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for rel, full in entries:
            with open(full, "rb") as f:
                data = f.read()
            total += len(data)
            if total > MAX_PACKAGE_BYTES:
                raise RuntimeEnvError(
                    f"runtime_env package {path} exceeds "
                    f"{MAX_PACKAGE_BYTES >> 20} MiB")
            info = zipfile.ZipInfo(rel, date_time=(2000, 1, 1, 0, 0, 0))
            info.external_attr = 0o644 << 16
            zf.writestr(info, data)
    blob = buf.getvalue()
    return hashlib.sha256(blob).hexdigest(), blob


def _check_pip(requirements: List[str]) -> None:
    """No network in this image: accept requirements the base env already
    satisfies (name AND version specifier), reject the rest loudly
    rather than failing at runtime."""
    import importlib.metadata as md

    from packaging.requirements import Requirement

    missing = []
    for req in requirements:
        try:
            parsed = Requirement(req)
        except Exception:
            missing.append(f"{req} (unparseable)")
            continue
        try:
            installed = md.version(parsed.name)
        except md.PackageNotFoundError:
            missing.append(req)
            continue
        if parsed.specifier and not parsed.specifier.contains(
                installed, prereleases=True):
            missing.append(f"{req} (installed: {installed})")
    if missing:
        raise RuntimeEnvError(
            f"pip runtime_env cannot be satisfied offline; unsatisfied in "
            f"the base environment: {missing}")


def normalize(renv: Dict[str, Any], head) -> Dict[str, Any]:
    """Driver-side: validate, package dirs, upload once, and rewrite to
    the wire form ({'pkg_working_dir': sha, 'pkg_py_modules': [sha...]}).

    `head` is the driver's sync head client (kv transport).
    """
    bad = set(renv) - _SUPPORTED
    if bad:
        raise RuntimeEnvError(f"unsupported runtime_env key(s): {sorted(bad)}")
    out: Dict[str, Any] = {}
    env_vars = renv.get("env_vars") or {}
    if env_vars:
        if not all(isinstance(k, str) and isinstance(v, str)
                   for k, v in env_vars.items()):
            raise RuntimeEnvError("env_vars must be {str: str}")
        out["env_vars"] = dict(env_vars)
    if renv.get("pip"):
        _check_pip(list(renv["pip"]))
        out["pip_checked"] = sorted(renv["pip"])
    for key, many in (("working_dir", False), ("py_modules", True)):
        src = renv.get(key)
        if not src:
            continue
        paths = list(src) if many else [src]
        shas = []
        for p in paths:
            sha, blob = _package_dir(p)
            kv_key = f"pkg:{sha}"
            # presence check via key listing — kv_get would ship the
            # whole blob back just to discard it
            if not head.call("kv_keys", prefix=kv_key)["keys"]:
                head.call("kv_put", key=kv_key, value=blob, overwrite=True)
            shas.append(sha)
        out["pkg_py_modules" if many else "pkg_working_dir"] = \
            shas if many else shas[0]
    return out


def env_key(renv: Dict[str, Any]) -> str:
    """Stable identity of a normalized runtime env; workers are pooled
    per key (reference: worker_pool.h keys idle workers by runtime env
    hash so an env-X lease never reuses an env-Y worker)."""
    if not renv:
        return ""
    return hashlib.sha256(
        json.dumps(renv, sort_keys=True).encode()).hexdigest()[:16]


async def materialize(renv: Dict[str, Any], session_dir: str,
                      head) -> Tuple[Dict[str, str], Optional[str], List[str]]:
    """Agent-side: fetch + extract packages (cached per content hash);
    returns (env_vars, working_dir or None, extra sys.path dirs).

    `head` is the agent's async head RpcClient.
    """
    env_vars = dict(renv.get("env_vars") or {})
    cache_root = os.path.join(session_dir, "runtime_envs")
    extracted: Dict[str, str] = {}

    async def ensure(sha: str) -> str:
        dest = os.path.join(cache_root, sha)
        if not os.path.isdir(dest):
            reply = await head.call("kv_get", key=f"pkg:{sha}")
            blob = reply["value"]
            if blob is None:
                raise RuntimeEnvError(f"package pkg:{sha} missing from KV")
            tmp = dest + ".tmp"
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.replace(tmp, dest)
            except OSError:
                if not os.path.isdir(dest):  # concurrent extraction lost
                    raise
                shutil.rmtree(tmp, ignore_errors=True)
        return dest

    working_dir = None
    if renv.get("pkg_working_dir"):
        working_dir = await ensure(renv["pkg_working_dir"])
    path_dirs = []
    for sha in renv.get("pkg_py_modules", []):
        path_dirs.append(await ensure(sha))
    return env_vars, working_dir, path_dirs


def merge(job_env: Dict[str, Any], task_env: Dict[str, Any]) -> Dict[str, Any]:
    """Task-level runtime_env overrides the job default; env_vars merge
    key-wise (reference: runtime_env merge semantics)."""
    if not job_env:
        return task_env
    if not task_env:
        return job_env
    out = {**job_env, **task_env}
    if job_env.get("env_vars") or task_env.get("env_vars"):
        out["env_vars"] = {**(job_env.get("env_vars") or {}),
                           **(task_env.get("env_vars") or {})}
    return out
