"""Object serialization: pickle5 with out-of-band buffers.

Equivalent of the reference's msgpack+pickle5 split
(reference: python/ray/_private/serialization.py): control metadata is
msgpack-framed, values are pickled with protocol 5 so large contiguous
buffers (numpy / jax host arrays, Arrow blocks) are captured out-of-band
and can be written into the shared-memory object store without a copy,
then mmap'd back zero-copy on read.

Wire layout of a serialized object (single contiguous buffer, so a sealed
plasma object can be read in place):

    [u32 magic][u32 nframes][u64 len_0]...[u64 len_{n-1}]
    [pad to 64][frame_0][pad to 64][frame_1]...

Frame 0 is the pickle bytestream; frames 1..n-1 are the out-of-band
buffers in callback order.  64-byte alignment keeps mmap'd array frames
cache-line/SIMD aligned.
"""

from __future__ import annotations

import pickle
from typing import Any, List, Tuple

import cloudpickle

_MAGIC = 0x52545031  # "RTP1"
_ALIGN = 64


class SerializationError(Exception):
    pass


def _aligned(offset: int) -> int:
    return (offset + _ALIGN - 1) & ~(_ALIGN - 1)


def serialize(value: Any) -> Tuple[List[memoryview], int]:
    """Serialize to a list of frames. Returns (frames, total_packed_size)."""
    buffers: List[pickle.PickleBuffer] = []
    try:
        payload = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    except Exception as e:
        raise SerializationError(f"Could not serialize {type(value)}: {e}") from e
    frames: List[memoryview] = [memoryview(payload)]
    for buf in buffers:
        mv = buf.raw()
        if not mv.contiguous:
            mv = memoryview(bytes(mv))
        frames.append(mv.cast("B"))
    return frames, packed_size(frames)


def packed_size(frames: List[memoryview]) -> int:
    header = 8 + 8 * len(frames)
    offset = header
    for f in frames:
        offset = _aligned(offset) + f.nbytes
    return offset


def pack_into(frames: List[memoryview], out: memoryview) -> int:
    """Pack frames into a pre-allocated buffer (e.g. a plasma allocation).

    Large frames (numpy/jax host buffers) copy via the native
    multithreaded memcpy when available — the single-threaded Python
    slice copy caps put bandwidth at ~4.6 GB/s on this host
    (reference: plasma client.cc multithreaded WriteObject)."""
    from ray_tpu import _native

    n = len(frames)
    out[0:4] = _MAGIC.to_bytes(4, "little")
    out[4:8] = n.to_bytes(4, "little")
    pos = 8
    for f in frames:
        out[pos : pos + 8] = f.nbytes.to_bytes(8, "little")
        pos += 8
    for f in frames:
        pos = _aligned(pos)
        if f.nbytes >= (1 << 21):
            _native.copy_into(out[pos : pos + f.nbytes], f)
        else:
            out[pos : pos + f.nbytes] = f
        pos += f.nbytes
    return pos


def serialize_to_bytes(value: Any) -> bytes:
    frames, size = serialize(value)
    out = bytearray(size)
    pack_into(frames, memoryview(out))
    return bytes(out)


def unpack_frames(data: memoryview) -> List[memoryview]:
    data = data.cast("B") if data.format != "B" else data
    magic = int.from_bytes(data[0:4], "little")
    if magic != _MAGIC:
        raise SerializationError(f"Bad magic {magic:#x} in serialized object")
    n = int.from_bytes(data[4:8], "little")
    lengths = []
    pos = 8
    for _ in range(n):
        lengths.append(int.from_bytes(data[pos : pos + 8], "little"))
        pos += 8
    frames = []
    for ln in lengths:
        pos = _aligned(pos)
        frames.append(data[pos : pos + ln])
        pos += ln
    return frames

def deserialize(data) -> Any:
    """Deserialize from a contiguous buffer; array frames view into `data`.

    The caller keeps `data`'s backing memory alive for the lifetime of the
    returned value (the plasma client pins the mmap while refs exist).
    """
    if isinstance(data, (bytes, bytearray)):
        data = memoryview(data)
    frames = unpack_frames(data)
    return pickle.loads(frames[0], buffers=frames[1:])
