"""Binary entity IDs for the runtime.

Mirrors the lineage-embedding layout of the reference's id scheme
(reference: src/ray/common/id.h): a JobID is embedded in every ActorID,
an ActorID in every TaskID, and an ObjectID is its producing TaskID plus
a return/put index.  This lets any component recover "who made this"
from the ID bytes alone, without a directory lookup.

Sizes (bytes):
    JobID    4
    ActorID  16 = 12 random + 4 job
    TaskID   24 = 8 random + 16 actor (zeros for non-actor tasks' actor part
                  except the embedded job id)
    ObjectID 28 = 24 task + 4 little-endian index
    NodeID / WorkerID / PlacementGroupID: 28 random
"""

from __future__ import annotations

import os
import random as _random
import threading as _threading

_JOB_ID_SIZE = 4
_ACTOR_ID_SIZE = 16
_TASK_ID_SIZE = 24
_OBJECT_ID_SIZE = 28
_UNIQUE_ID_SIZE = 28

_rand_lock = _threading.Lock()
_rand_state = None  # [pid, Random, buffer, position]
_RAND_CHUNK = 4096


def _random_id_bytes(n: int) -> bytes:
    """Process-local PRNG for ID minting.  os.urandom is a SYSCALL per
    call — ~1 ms on syscall-throttled sandboxes, and it sat directly on
    every task-submission hot path (one TaskID per .remote()).  IDs
    need uniqueness, not cryptographic strength: a 128-bit-seeded PRNG
    stream gives the same 8-byte collision behavior.  Seeded from
    os.urandom once per process and re-seeded on pid change, so a
    forked child can never clone the parent's stream.  Bytes are drawn
    from a buffered chunk: one bigint draw amortizes over ~300 ids
    (id minting showed up at ~7% of driver submit-path samples)."""
    global _rand_state
    pid = os.getpid()
    with _rand_lock:
        st = _rand_state
        if st is None or st[0] != pid:
            rng = _random.Random(int.from_bytes(os.urandom(16), "little"))
            st = [pid, rng, rng.randbytes(_RAND_CHUNK), 0]
            _rand_state = st
        pos = st[3]
        if pos + n > _RAND_CHUNK:
            st[2] = st[1].randbytes(_RAND_CHUNK)
            pos = 0
        st[3] = pos + n
        return st[2][pos:pos + n]


class BaseID:
    SIZE = _UNIQUE_ID_SIZE
    __slots__ = ("_bytes",)

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)

    @classmethod
    def from_random(cls):
        return cls(_random_id_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(_JOB_ID_SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._bytes, "little")


class UniqueID(BaseID):
    SIZE = _UNIQUE_ID_SIZE


class NodeID(UniqueID):
    pass


class WorkerID(UniqueID):
    pass


class ClusterID(UniqueID):
    pass


class PlacementGroupID(UniqueID):
    pass


class ActorID(BaseID):
    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_random_id_bytes(_ACTOR_ID_SIZE - _JOB_ID_SIZE)
                   + job_id.binary())

    @classmethod
    def nil_for_job(cls, job_id: JobID) -> "ActorID":
        """Actor part zeroed but job id embedded (used for non-actor tasks)."""
        return cls(b"\x00" * (_ACTOR_ID_SIZE - _JOB_ID_SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-_JOB_ID_SIZE:])


class TaskID(BaseID):
    SIZE = _TASK_ID_SIZE

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(_random_id_bytes(8)
                   + ActorID.nil_for_job(job_id).binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_random_id_bytes(8) + actor_id.binary())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        return cls(b"\xff" * 8 + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(b"\x00" * 8 + ActorID.nil_for_job(job_id).binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[8:])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    SIZE = _OBJECT_ID_SIZE

    @classmethod
    def from_index(cls, task_id: TaskID, index: int) -> "ObjectID":
        """index >= 1 for task returns; matches the reference's return-index scheme."""
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_TASK_ID_SIZE])

    def index(self) -> int:
        return int.from_bytes(self._bytes[_TASK_ID_SIZE:], "little")

    def job_id(self) -> JobID:
        return self.task_id().job_id()
