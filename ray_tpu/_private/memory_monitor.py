"""Node memory-pressure watchdog: sampling + victim-selection policy.

Equivalent of the reference's memory monitor + worker-killing policy
(reference: src/ray/common/memory_monitor.h:52 +
raylet/worker_killing_policy_group_by_owner.cc): sample node usage and
per-worker RSS every ``memory_monitor_refresh_ms``; when usage crosses
``memory_usage_threshold`` pick ONE victim worker, kill it deliberately
and hand its owner a typed receipt (OutOfMemoryError with the RSS and
the node breakdown) — the alternative is the kernel OOM killer taking
the whole agent down and every owner seeing a mystery death.

The policy favors progress preservation over strict LIFO:

  1. the highest-RSS worker running a RETRIABLE task (the one actually
     ballooning, and the one whose owner can transparently resubmit),
     ties broken toward the LAST-started lease (earlier work keeps its
     progress — the reference's "kill the task submitted last");
  2. then non-retriable task / plain actor workers;
  3. pinned-loop actors (compiled-DAG / pipeline / LLM decode loops —
     killing one tears down a whole graph) and workers mid-__rt_save__
     (killing mid-snapshot risks the actor's durable state) only as a
     last resort.

Everything here is a pure function of its inputs (injectable clock,
sampler fed by the caller) so the kill policy unit-tests run without a
cluster or any real memory pressure.  The node_agent owns the asyncio
loop that drives ``OomWatchdog.tick`` and executes the kill.

Usage sources, first match wins:
  - ``memory_monitor_test_usage_file``: a fraction in a file (tests
    steer pressure without allocating anything);
  - ``memory_monitor_node_total_bytes`` > 0: sum(worker RSS) / total —
    a VIRTUAL node envelope, so several agents on one host each see
    only their own workers' pressure (bench/test overcommit stays safe);
  - /proc/meminfo: 1 - MemAvailable/MemTotal, the real node.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def read_rss_bytes(pid: int) -> Optional[int]:
    """ANONYMOUS resident bytes of a live process (RssAnon from
    /proc/<pid>/status), falling back to full RSS from statm; None when
    the process is gone/unreadable.

    Anonymous-only is deliberate: every worker mmaps the node's shared
    object-store arena, and prefaulted tmpfs pages show up in each
    attacher's VmRSS — a 512MB arena would make every worker look like
    a 500MB hog and the victim policy meaningless.  Task allocations
    (and the watchdog's quarry, a ballooning heap) are anonymous."""
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("RssAnon"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return None
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return None


def read_meminfo_fraction() -> Optional[float]:
    """Real node pressure in [0, 1] from /proc/meminfo; None if
    unreadable (non-Linux)."""
    try:
        fields: Dict[str, int] = {}
        with open("/proc/meminfo") as f:
            for line in f:
                key, _, rest = line.partition(":")
                fields[key] = int(rest.split()[0])
        total = fields.get("MemTotal", 0)
        avail = fields.get("MemAvailable", fields.get("MemFree", 0))
        if total <= 0:
            return None
        return 1.0 - avail / total
    except (OSError, ValueError):
        return None


@dataclass
class WorkerSample:
    """One leased worker as the victim policy sees it."""

    worker_id: str
    rss: int                 # bytes, sampled this tick
    lease_seq: int = 0       # grant order; larger = started later
    retriable: bool = True   # granting spec had max_retries != 0
    pinned: bool = False     # running a __rt_dag_* pinned loop
    saving: bool = False     # mid-__rt_save__ state snapshot
    fid: str = ""            # granting spec's function/class id
    name: str = ""           # task/actor display name


def pick_victim(samples: List[WorkerSample]) -> Optional[WorkerSample]:
    """The worker to kill under pressure, or None when there is nothing
    killable.  Ordering: retriable-task workers first (highest RSS,
    then last-started), then non-retriable, with pinned-loop and
    mid-save workers demoted to last resort within both groups."""
    if not samples:
        return None

    def rank(s: WorkerSample) -> tuple:
        # lower tuple = better victim
        return (1 if (s.pinned or s.saving) else 0,
                0 if s.retriable else 1,
                -s.rss, -s.lease_seq)

    return min(samples, key=rank)


def is_self_poisoning(rss: int, limit: int, factor: float = 0.9) -> bool:
    """Whether one watchdog kill counts toward the poison-task
    quarantine: the victim's own RSS approached the node's kill
    ceiling (``limit`` = threshold * node total, carried in the kill
    receipt), so the task can never fit even alone.  A modest-RSS
    victim of AGGREGATE pressure just retries — counting it would
    quarantine healthy classes under overcommit.  ``limit`` <= 0 means
    no ceiling is known (test usage-file pressure): count every kill.
    The single definition both counting sites (owner task kills, head
    actor kills) share."""
    return limit <= 0 or rss >= factor * limit


def usage_fraction(test_usage_file: str = "",
                   virtual_total_bytes: int = 0,
                   worker_rss_sum: int = 0) -> Optional[float]:
    """Node memory pressure per the source precedence in the module
    docstring; None when no source is readable."""
    if test_usage_file:
        try:
            with open(test_usage_file) as f:
                return float(f.read().strip())
        except (OSError, ValueError):
            return None
    if virtual_total_bytes > 0:
        return worker_rss_sum / float(virtual_total_bytes)
    return read_meminfo_fraction()


@dataclass
class OomWatchdog:
    """The kill-decision engine: threshold crossing + kill-rate limit.
    Pure against an injectable clock; the caller supplies the sampled
    usage and worker set each tick and executes any returned kill."""

    threshold: float = 0.95
    min_kill_gap_s: float = 1.0
    clock: Callable[[], float] = time.monotonic
    last_kill_at: float = field(default=0.0, init=False)
    kills: int = field(default=0, init=False)

    def tick(self, usage: Optional[float],
             samples: List[WorkerSample]) -> Optional[WorkerSample]:
        """The victim to kill this tick, or None.  A kill is produced at
        most once per ``min_kill_gap_s`` so the previous kill's memory
        actually returns before the next decision reads the gauge."""
        if usage is None or usage < self.threshold:
            return None
        now = self.clock()
        if self.last_kill_at and now - self.last_kill_at < self.min_kill_gap_s:
            return None
        victim = pick_victim(samples)
        if victim is None:
            return None
        self.last_kill_at = now
        self.kills += 1
        return victim
