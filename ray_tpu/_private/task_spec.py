"""Typed task specification and wire argument encoding.

Equivalent of the reference's TaskSpecification
(reference: src/ray/common/task/task_spec.h, protobuf common.proto
TaskSpec): everything a node agent / worker needs to schedule and run a
task, as a msgpack-able dict. Args follow the reference's inline-vs-ref
split (reference: ray_config_def.h:206 max_direct_call_object_size):
small serialized values travel inside the spec; large ones are put into
the object store and travel as (object_id, owner_address) references.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.resources import ResourceSet

NORMAL_TASK = 0
ACTOR_CREATION_TASK = 1
ACTOR_TASK = 2


@dataclass
class WireArg:
    """One positional/keyword argument on the wire."""

    # exactly one of `value` (serialized bytes, inline) or `object_id` is set
    value: Optional[bytes] = None
    object_id: Optional[str] = None  # hex
    owner_addr: Optional[Tuple[str, int]] = None  # (host, port) of owner's RPC
    kw: Optional[str] = None  # keyword name; None for positional
    # locality hints, stamped from the owner's reference table at submit
    # time (reference: lease_policy.cc best-effort locality data): the
    # node-agent addr holding the primary plasma copy, and its size —
    # pick_node scores feasible nodes by argument bytes already local,
    # and the granting agent prefetches hinted args on lease grant
    size: int = 0
    loc: Optional[Tuple[str, int]] = None  # (host, port) of a holder agent

    def to_wire(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.value is not None:
            d["v"] = self.value
        elif self.object_id is None:
            raise ValueError("WireArg needs exactly one of value/object_id")
        else:
            d["oid"] = self.object_id
            if self.owner_addr:
                d["owner"] = list(self.owner_addr)
            if self.size:
                d["sz"] = self.size
            if self.loc:
                d["loc"] = list(self.loc)
        if self.kw:
            d["kw"] = self.kw
        return d

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "WireArg":
        owner = d.get("owner")
        loc = d.get("loc")
        return cls(
            value=d.get("v"),
            object_id=d.get("oid"),
            owner_addr=tuple(owner) if owner else None,
            kw=d.get("kw"),
            size=d.get("sz", 0),
            loc=tuple(loc) if loc else None,
        )


@dataclass
class TaskSpec:
    task_id: str  # hex
    job_id: str
    kind: int = NORMAL_TASK
    function_id: str = ""  # hex key into the head's function table
    args: List[WireArg] = field(default_factory=list)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 3
    # actor fields
    actor_id: str = ""
    method_name: str = ""
    seqno: int = 0  # per-(caller, actor) ordered delivery
    caller_id: str = ""  # worker id of the submitter, for seqno namespacing
    max_restarts: int = 0  # actor creation only
    max_concurrency: int = 1  # actor creation only
    # scheduling hints
    name: str = ""
    owner_addr: Optional[Tuple[str, int]] = None  # owner RPC addr for returns
    placement_group_id: str = ""
    bundle_index: int = -1
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    # {} | {"type": "spread"} | {"type": "node_affinity", ...} |
    # {"type": "node_label", "hard": {...}} (see util/scheduling_strategies)
    scheduling_strategy: Dict[str, Any] = field(default_factory=dict)
    # active trace context at submission ({"tid": ..., "sid": ...});
    # only present for sampled traces — the worker-side execute span
    # parents to it (see _private/tracing.py)
    trace_ctx: Optional[Dict[str, str]] = None
    # absolute wall-clock deadline (epoch seconds; 0.0 = unbounded),
    # stamped at submission from .options(timeout_s=...) / the ambient
    # deadline context and re-activated by the executing worker so
    # nested .remote() calls inherit the caller's remaining budget
    # (see _private/deadlines.py)
    deadline: float = 0.0

    def resource_set(self) -> ResourceSet:
        return ResourceSet(self.resources)

    def scheduling_class(self) -> tuple:
        """Tasks with the same shape share worker leases (reference:
        SchedulingClassDescriptor in task_spec.h keys on resources AND
        function descriptor — including the function keeps per-class
        service-time stats meaningful, so one fast function can't drag a
        slow one into deep pipelining)."""
        from ray_tpu._private.runtime_env import env_key

        import json

        return (ResourceSet(self.resources).key(), self.kind,
                self.function_id, self.placement_group_id, self.bundle_index,
                env_key(self.runtime_env),
                json.dumps(self.scheduling_strategy, sort_keys=True))

    def to_wire(self) -> Dict[str, Any]:
        d = {
            "tid": self.task_id,
            "jid": self.job_id,
            "kind": self.kind,
            "fid": self.function_id,
            "args": [a.to_wire() for a in self.args],
            "nret": self.num_returns,
            "res": self.resources,
            "retries": self.max_retries,
            "aid": self.actor_id,
            "method": self.method_name,
            "seq": self.seqno,
            "caller": self.caller_id,
            "max_restarts": self.max_restarts,
            "max_conc": self.max_concurrency,
            "name": self.name,
            "owner": list(self.owner_addr) if self.owner_addr else None,
            "pg": self.placement_group_id,
            "bundle": self.bundle_index,
            "renv": self.runtime_env,
            "strat": self.scheduling_strategy,
        }
        if self.trace_ctx:
            d["trace"] = self.trace_ctx
        if self.deadline:
            d["dl"] = self.deadline
        return d

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "TaskSpec":
        owner = d.get("owner")
        return cls(
            task_id=d["tid"],
            job_id=d["jid"],
            kind=d.get("kind", NORMAL_TASK),
            function_id=d.get("fid", ""),
            args=[WireArg.from_wire(a) for a in d.get("args", [])],
            num_returns=d.get("nret", 1),
            resources=d.get("res", {}),
            max_retries=d.get("retries", 3),
            actor_id=d.get("aid", ""),
            method_name=d.get("method", ""),
            seqno=d.get("seq", 0),
            caller_id=d.get("caller", ""),
            max_restarts=d.get("max_restarts", 0),
            max_concurrency=d.get("max_conc", 1),
            name=d.get("name", ""),
            owner_addr=tuple(owner) if owner else None,
            placement_group_id=d.get("pg", ""),
            bundle_index=d.get("bundle", -1),
            runtime_env=d.get("renv", {}),
            scheduling_strategy=d.get("strat", {}),
            trace_ctx=d.get("trace"),
            deadline=d.get("dl", 0.0),
        )
