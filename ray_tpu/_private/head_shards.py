"""Head control-plane ingest shards (multi-loop head, ISSUE 18).

Every control-plane message in the cluster used to funnel through the
single head event loop: task-event frames, heartbeats carrying
object-directory deltas and gauge summaries, trace spans, dashboard
polls, autoscaler snapshots — all interleaved with the latency-critical
scheduling work (actor/PG state machines, lease placement).  ROADMAP
item 2 measured the result: 6.8% multi-client scaling efficiency at 8
drivers, with the head loop as the structural ceiling.

This module splits the head into a scheduling core plus independent
*ingest shards*, each on its own event-loop thread (the pattern from
"Exploring the limits of Concurrency in ML Training on Google TPUs",
arxiv 2011.03641: keep the latency-critical decision path on one
thread, push everything that only observes cluster state onto parallel
ingest planes):

  - ``TaskEventPlane`` owns the task-event inbox + store, the
    sched-latency histogram feed, and the trace-span store.  The rpc
    surface (``task_events``, ``trace_spans``, ``list_tasks``,
    ``list_traces``, ``get_trace``) dispatches onto its loop directly
    (rpc.py per-op loop routing), so a 10k-task burst's event merge
    never steals a cycle from scheduling.
  - ``TelemetryPlane`` owns heartbeat ingest: object-directory delta
    application (the PR-8 sharded directory is already lock-per-shard
    and safe to write from this thread), the gauge-summary time-series
    ring, and pressure/chaos-version bookkeeping.  It assembles the
    heartbeat reply from a *membership snapshot* the scheduling core
    publishes (versioned, lock-free read) and forwards the per-node
    state the core does need (availability, pending demands, heartbeat
    liveness) over a single-producer queue drained once per core tick.

Consistency model (the PR-8 ``DirectoryMirror`` epoch/version handshake
generalized):

  - core -> shards: ``VersionedSnapshot`` — the publisher swaps an
    immutable (version, payload) cell; readers on any thread see either
    the old or the new snapshot, never a torn one.  Staleness is
    bounded by one publish (membership changes republish synchronously
    with the mutation).
  - shards -> core: ``CrossShardQueue`` — producers append under a
    lock, the consumer loop drains the whole backlog in ONE scheduled
    callback per tick (the head-side half of event batching, applied to
    cross-thread writes).  Entry updates land within one core tick.

``head_ingest_shards=0`` (config) is the single-loop compat mode: the
planes still exist and run the same code, but on the head's own loop —
one code path, two deployment shapes.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private.config import config

__all__ = ["VersionedSnapshot", "CrossShardQueue", "IngestShard",
           "HeadShards", "TaskEventPlane", "TelemetryPlane"]


class VersionedSnapshot:
    """Single-writer published snapshot with a monotonic version.

    ``publish`` swaps one (version, payload) tuple; ``read`` returns it.
    Both are single attribute operations — atomic under the GIL, so a
    reader on a foreign thread sees a consistent pair without a lock
    (the DirectoryMirror version-handshake pattern, minus the wire).

    The version seed is the wall clock in nanoseconds: a publisher that
    restarts (head restart rebuilding its snapshots) seeds ABOVE every
    version the old incarnation could have published, so downstream
    "only apply newer" guards stay correct across the boundary without
    persisting a counter.
    """

    __slots__ = ("_cell",)

    def __init__(self, payload: Any = None,
                 start_version: Optional[int] = None):
        v0 = int(time.time_ns() if start_version is None else start_version)
        self._cell: Tuple[int, Any] = (v0, payload)

    def publish(self, payload: Any) -> int:
        version = self._cell[0] + 1
        self._cell = (version, payload)
        return version

    def read(self) -> Tuple[int, Any]:
        return self._cell

    @property
    def version(self) -> int:
        return self._cell[0]

    @property
    def payload(self) -> Any:
        return self._cell[1]


class CrossShardQueue:
    """Single-producer-per-shard queue drained once per consumer tick.

    Producers (shard loops) append under a lock and schedule AT MOST one
    drain callback on the consumer loop; the callback sweeps the whole
    backlog, so a heartbeat burst from 100 agents costs the scheduling
    core one callback, not 100.  ``high_water`` tracks the deepest
    backlog since the last ``take_high_water`` — exported as
    ``ray_tpu_head_inbox_depth{shard=...}`` so ingest saturation is
    visible before anything is dropped.
    """

    def __init__(self, consumer_loop: asyncio.AbstractEventLoop,
                 drain_cb: Callable[[List[Any]], None], name: str = ""):
        self.name = name
        self._loop = consumer_loop
        self._drain_cb = drain_cb
        self._lock = threading.Lock()
        self._items: List[Any] = []
        self._scheduled = False
        self._high_water = 0

    def put(self, item: Any) -> None:
        with self._lock:
            self._items.append(item)
            depth = len(self._items)
            if depth > self._high_water:
                self._high_water = depth
            if self._scheduled:
                return
            self._scheduled = True
        try:
            self._loop.call_soon_threadsafe(self._drain)
        except RuntimeError:
            # consumer loop closed (head shutting down): drop silently
            with self._lock:
                self._scheduled = False

    def _drain(self) -> None:
        with self._lock:
            items, self._items = self._items, []
            self._scheduled = False
        if not items:
            return
        try:
            self._drain_cb(items)
        except Exception:
            import traceback

            traceback.print_exc()

    def take_high_water(self) -> int:
        with self._lock:
            hw, self._high_water = self._high_water, len(self._items)
        return hw


class IngestShard:
    """One ingest plane: a dedicated event-loop thread plus its own
    loop-lag probe (``ray_tpu_event_loop_lag_seconds{role=head_shard,
    shard=<name>}``) so `rtpu status --watch` shows WHICH plane is hot.

    In single-loop compat mode the shard wraps the head's own loop
    (``own_thread=False``): same API, no thread, no extra probe — the
    head's existing role=head probe already covers it.
    """

    def __init__(self, name: str,
                 loop: Optional[asyncio.AbstractEventLoop] = None,
                 loop_thread: Optional[Any] = None):
        from ray_tpu._private.rpc import EventLoopThread

        self.name = name
        if loop is not None:
            self._elt = None
            self.loop = loop
            self.own_thread = False
        else:
            self._elt = loop_thread or EventLoopThread(
                name=f"rt-head-{name}")
            self.loop = self._elt.loop
            self.own_thread = loop_thread is None
        self.loop_lag = 0.0
        self._probe: Optional[Any] = None

    def start_lag_probe(self) -> None:
        if self._elt is None:
            return

        from ray_tpu._private.profiling import loop_lag_probe

        def _lag(sample: float) -> None:
            self.loop_lag = sample

        self._probe = self._elt.spawn(loop_lag_probe(
            "head_shard", on_sample=_lag, tags={"shard": self.name}))

    def on_loop(self) -> bool:
        try:
            return asyncio.get_running_loop() is self.loop
        except RuntimeError:
            return False

    async def run_sync(self, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` on the shard loop and await the result from any
        loop.  Same-loop calls execute inline (compat mode and handlers
        already routed here pay nothing)."""
        if self.on_loop():
            return fn()

        async def _call():
            return fn()

        return await asyncio.wrap_future(
            asyncio.run_coroutine_threadsafe(_call(), self.loop))

    def stop(self) -> None:
        if self._elt is not None and self.own_thread:
            self._elt.stop()


class HeadShards:
    """The head's shard set, shaped by ``config.head_ingest_shards``:

      0  -> compat: both planes on the head loop (no threads)
      1  -> one shared ingest loop hosting both planes
      2+ -> a task-event loop and a telemetry loop (the two ingest
            planes are the natural partition; more shards would split
            the task-event STORE and force cross-shard reads)
    """

    def __init__(self, count: int, head_loop: asyncio.AbstractEventLoop):
        self.count = max(0, int(count))
        if self.count == 0:
            self.task_events = IngestShard("task_events", loop=head_loop)
            self.telemetry = IngestShard("telemetry", loop=head_loop)
        elif self.count == 1:
            from ray_tpu._private.rpc import EventLoopThread

            shared = EventLoopThread(name="rt-head-ingest")
            self.task_events = IngestShard("task_events",
                                           loop_thread=shared)
            self.telemetry = IngestShard("telemetry", loop_thread=shared)
            self._shared = shared
        else:
            self.task_events = IngestShard("task_events")
            self.telemetry = IngestShard("telemetry")

    @property
    def sharded(self) -> bool:
        return self.count > 0

    def start(self) -> None:
        self.task_events.start_lag_probe()
        # with one shared loop the second probe would double-sample it
        # under a different shard label — skip it
        if self.telemetry.loop is not self.task_events.loop:
            self.telemetry.start_lag_probe()

    def op_loops(self) -> Dict[str, asyncio.AbstractEventLoop]:
        """The per-op loop routing map RpcServer consults: a frame for
        a shard-owned op dispatches onto the owning shard's loop from
        the reader, never hopping through the head loop's task queue."""
        if not self.sharded:
            return {}
        ev, tel = self.task_events.loop, self.telemetry.loop
        return {"task_events": ev, "trace_spans": ev, "list_tasks": ev,
                "list_traces": ev, "get_trace": ev,
                "heartbeat": tel, "timeseries": tel}

    def stop(self) -> None:
        stopped = set()
        for shard in (self.task_events, self.telemetry):
            if id(shard.loop) not in stopped:
                stopped.add(id(shard.loop))
                shard.stop()
            elif getattr(self, "_shared", None) is not None:
                pass  # shared loop already stopped via the first shard
        shared = getattr(self, "_shared", None)
        if shared is not None:
            shared.stop()


# --------------------------------------------------------------- planes


class TaskEventPlane:
    """Task-event + trace ingest: inbox, merged store, sched-latency
    histogram feed, trace store.  Mutations run on the owning shard's
    loop; the scheduling core and HTTP surfaces read through the
    published ``stats`` snapshot or via ``shard.run_sync`` for the
    heavier record copies (dashboard snapshot, timeline)."""

    def __init__(self, shard: IngestShard):
        from ray_tpu._private.tracing import TraceStore

        self.shard = shard
        self.records: Dict[str, Dict[str, Any]] = {}
        self._inbox: List[List[Dict[str, Any]]] = []
        self._drain_scheduled = False
        self._inbox_high_water = 0
        self._dropped_total = 0
        self._sched_observed: Dict[str, set] = {}
        self.sched_hist = None  # installed by HeadService._start_metrics
        self.trace_store = TraceStore(
            max_traces=int(config.trace_store_max_traces),
            max_spans=int(config.trace_store_max_spans))
        self.finished_total = 0
        self._p99_cache = (0.0, 0.0)  # (computed_at, value)
        # stats snapshot: the scheduling core's lock-free read surface
        # (autoscaler SLO signal, dashboard counts) — one publish per
        # drain tick
        self.stats = VersionedSnapshot(payload=self._stats_payload())
        self._dropped_counter = None
        self._depth_gauge = None

    # ---- ingest (shard loop) -------------------------------------------

    def ingest(self, events: List[Dict[str, Any]]) -> None:
        """Queue one rpc frame's events; the merge runs once per loop
        tick over every frame that landed in the window (head-side half
        of the event batching).  The inbox is bounded: under saturation
        the OLDEST frame drops (newest state wins for an observability
        store) and the loss is counted per shard."""
        max_frames = int(config.head_inbox_max_frames)
        self._inbox.append(events)
        depth = len(self._inbox)
        if depth > self._inbox_high_water:
            self._inbox_high_water = depth
        if max_frames > 0 and depth > max_frames:
            dropped = self._inbox.pop(0)
            self._count_dropped(len(dropped))
        if not self._drain_scheduled:
            self._drain_scheduled = True
            asyncio.get_running_loop().call_soon(self.drain)

    def ingest_spans(self, spans: List[Dict[str, Any]]) -> None:
        self.trace_store.ingest(spans)

    def drain(self) -> None:
        self._drain_scheduled = False
        batches, self._inbox = self._inbox, []
        for events in batches:
            self._apply(events)
        cap = config.task_events_buffer_size
        while len(self.records) > cap:
            oldest = next(iter(self.records))
            self.records.pop(oldest)
            self._sched_observed.pop(oldest, None)
        self._set_depth_gauge()
        self.stats.publish(self._stats_payload())

    def _apply(self, events: List[Dict[str, Any]]) -> None:
        rank = {"SUBMITTED": 0, "LEASED": 1, "RUNNING": 2,
                "FINISHED": 3, "FAILED": 3}
        terminal = ("FINISHED", "FAILED")
        for ev in events:
            tid = ev.get("task_id", "")
            if not tid:
                continue
            rec = self.records.get(tid)
            if rec is None:
                rec = self.records[tid] = {"task_id": tid}
            was_terminal = rec.get("state") in terminal
            for k, v in ev.items():
                if v is None:
                    continue
                if k == "state":
                    # owner (SUBMITTED/LEASED) and executor (RUNNING/...)
                    # flush on independent clocks; a late-arriving earlier
                    # state must not regress the record
                    if rank.get(v, 0) < rank.get(rec.get("state"), -1):
                        continue
                rec[k] = v
            if not was_terminal and rec.get("state") in terminal:
                self.finished_total += 1
            self._observe_sched_latency(rec)

    def _observe_sched_latency(self, rec: Dict[str, Any]) -> None:
        """Once a task record is terminal, decompose its lifetime into
        queued→leased→running→finished phase durations and feed the
        ray_tpu_task_sched_latency_seconds histogram.

        Each phase is observed at most once per task, but independently:
        the executor's RUNNING/FINISHED batch usually lands before the
        owner's SUBMITTED/LEASED batch (the owner holds non-terminal
        events for its periodic flush), so the queued/leased phases only
        become computable on a later merge.  Negative deltas (events
        stamped by different process clocks) clamp to 0."""
        if self.sched_hist is None:
            return
        if rec.get("state") not in ("FINISHED", "FAILED"):
            return
        done = self._sched_observed.setdefault(rec.get("task_id", ""),
                                               set())
        sub = rec.get("submitted_ts")
        leased = rec.get("leased_ts")
        run = rec.get("running_ts")
        end = rec.get("finished_ts") or rec.get("failed_ts")
        h = self.sched_hist
        if "queued" not in done and sub is not None and leased is not None:
            done.add("queued")
            h.observe(max(0.0, leased - sub), tags={"phase": "queued"})
        if "leased" not in done and leased is not None and run is not None:
            done.add("leased")
            h.observe(max(0.0, run - leased), tags={"phase": "leased"})
        if "running" not in done and run is not None and end is not None:
            done.add("running")
            h.observe(max(0.0, end - run), tags={"phase": "running"})

    def _count_dropped(self, n: int) -> None:
        self._dropped_total += n
        if self._dropped_counter is None:
            from ray_tpu._private.metrics import task_events_dropped_counter

            self._dropped_counter = task_events_dropped_counter()
        self._dropped_counter.inc(n, tags={"shard": self.shard.name})

    def _set_depth_gauge(self) -> None:
        hwm, self._inbox_high_water = self._inbox_high_water, 0
        if self._depth_gauge is None:
            from ray_tpu._private.metrics import head_inbox_depth_gauge

            self._depth_gauge = head_inbox_depth_gauge()
        self._depth_gauge.set(hwm, tags={"shard": self.shard.name})

    # ---- published stats (any thread) ----------------------------------

    def _stats_payload(self) -> Dict[str, Any]:
        return {"num_events": len(self.records),
                "num_traces": len(self.trace_store.traces),
                "finished_total": self.finished_total,
                "queued_p99_ms": self._queued_p99_ms(),
                "dropped_total": self._dropped_total}

    def _queued_p99_ms(self, sample: int = 500,
                       max_age_s: float = 0.25) -> float:
        """Queued-phase (submitted->leased) p99 over the most recent
        records — the autoscaler's scheduler-latency SLO signal.
        Cached briefly: recomputing a 500-record walk on every drain
        tick of a burst would cost more than the merge itself."""
        now = time.monotonic()
        at, val = self._p99_cache
        if now - at < max_age_s:
            return val
        recs = list(self.records.values())[-sample:]
        waits = []
        for rec in recs:
            sub, leased = rec.get("submitted_ts"), rec.get("leased_ts")
            if sub is not None and leased is not None:
                waits.append(max(0.0, leased - sub))
        if waits:
            waits.sort()
            val = round(
                waits[min(len(waits) - 1, int(len(waits) * 0.99))] * 1000,
                3)
        else:
            val = 0.0
        self._p99_cache = (now, val)
        return val

    # ---- reads (shard loop; route via rpc op map or shard.run_sync) ----

    def list_tasks(self, state: str = "", name: str = "",
                   limit: int = 1000) -> List[Dict[str, Any]]:
        out = []
        for rec in reversed(list(self.records.values())):
            if state and rec.get("state") != state:
                continue
            if name and rec.get("name") != name:
                continue
            out.append(dict(rec))
            if len(out) >= limit:
                break
        return out

    def recent_records(self, limit: int = 200) -> List[Dict[str, Any]]:
        recent = sorted(self.records.values(),
                        key=lambda r: r.get("running_ts")
                        or r.get("submitted_ts") or 0,
                        reverse=True)[:limit]
        return [dict(r) for r in recent]

    def all_records(self) -> List[Dict[str, Any]]:
        return [dict(r) for r in self.records.values()]

    def summarize_tasks(self) -> Tuple[Dict[str, Dict[str, Any]],
                                       Dict[str, int]]:
        """Per-function aggregates for `rtpu summary`: state counts plus
        queued/running duration samples, and per-method actor-call
        counts.  Runs on the shard loop; the core merges the result with
        its own actor/node state."""
        from ray_tpu._private.task_spec import ACTOR_TASK, NORMAL_TASK

        tasks: Dict[str, Dict[str, Any]] = {}
        methods: Dict[str, int] = {}
        for rec in self.records.values():
            name = rec.get("name") or "?"
            kind = rec.get("kind", NORMAL_TASK)
            row = tasks.get(name)
            if row is None:
                row = tasks[name] = {"kind": kind, "states": {},
                                     "queued_s": [], "running_s": []}
            st = rec.get("state", "?")
            row["states"][st] = row["states"].get(st, 0) + 1
            sub = rec.get("submitted_ts")
            run = rec.get("running_ts")
            end = rec.get("finished_ts") or rec.get("failed_ts")
            lease = rec.get("leased_ts") or run
            if sub is not None and lease is not None:
                row["queued_s"].append(max(0.0, lease - sub))
            if run is not None and end is not None:
                row["running_s"].append(max(0.0, end - run))
            if kind == ACTOR_TASK:
                methods[name] = methods.get(name, 0) + 1
        return tasks, methods


class TelemetryPlane:
    """Heartbeat ingest: object-directory delta application, the
    gauge-summary time-series ring, pressure/chaos bookkeeping, and
    heartbeat reply assembly.

    Reply assembly reads the MEMBERSHIP snapshot the scheduling core
    publishes (addr/labels/totals/draining/chaos/quarantine payloads —
    republished synchronously with every mutation) and this plane's own
    per-node telemetry; the per-node state the core needs back
    (availability, pending demands, liveness) rides ``to_core``, the
    single-producer queue the core drains once per tick.  The ring and
    the chaos-fired table take a small lock: they are read from the
    core loop (autoscaler trend tails, status surfaces) while this loop
    appends — the lock covers microseconds of deque/dict work, never
    reply assembly."""

    def __init__(self, shard: IngestShard, directory: Any,
                 membership: VersionedSnapshot,
                 to_core: CrossShardQueue):
        self.shard = shard
        self.dir = directory
        self.membership = membership
        self.to_core = to_core
        self._ts_lock = threading.Lock()
        self._tseries: Dict[Tuple[str, str], Any] = {}
        # per-node heartbeat-derived telemetry (shard-loop owned)
        self.node_telem: Dict[str, Dict[str, Any]] = {}
        # last full gauge summary per node: heartbeats carry summary
        # DELTAS (unchanged gauges are not re-serialized every beat —
        # the dir_version gossip pattern applied to the metrics echo),
        # so the ring re-records from this cache to keep its cadence
        self._last_metrics: Dict[str, Dict[str, float]] = {}
        self._fired_lock = threading.Lock()
        self._chaos_fired: Dict[str, Dict[str, int]] = {}
        # published cluster view (membership + latest availability):
        # heartbeat replies serve it; the core reads it for spillback
        # pushes without walking this plane's state
        self.cluster = VersionedSnapshot(payload={})

    # ---- heartbeat (shard loop) ----------------------------------------

    def heartbeat(self, node_id: str, available: Dict[str, float],
                  pending: Optional[List[Dict[str, float]]] = None,
                  objects_delta: Optional[Dict[str, Any]] = None,
                  dir_versions: Optional[List[int]] = None,
                  metrics: Optional[Dict[str, float]] = None,
                  memory: Optional[Dict[str, Any]] = None,
                  pressure: Optional[float] = None,
                  seen_chaos_version: int = 0,
                  seen_quarantine_version: int = 0,
                  chaos_fired: Optional[Dict[str, int]] = None
                  ) -> Dict[str, Any]:
        _mv, member = self.membership.read()
        nodes = (member or {}).get("nodes") or {}
        ninfo = nodes.get(node_id)
        if ninfo is None:
            # not in the core's published membership: restarted head
            # that reaped us, or a reap raced this beat — re-register
            return {"unknown_node": True}
        now_mono = time.monotonic()
        telem = self.node_telem.get(node_id)
        if telem is None:
            telem = self.node_telem[node_id] = {}
        telem["available"] = dict(available or {})
        telem["pending"] = pending or []
        telem["last_heartbeat"] = now_mono
        if memory:
            telem["memory"] = memory
        if pressure is not None:
            telem["pressure"] = float(pressure)
        need_metrics = False
        if metrics:
            cached = self._last_metrics.setdefault(node_id, {})
            for k, v in metrics.items():
                if v is None:  # agent retired this gauge
                    cached.pop(str(k), None)
                else:
                    cached[str(k)] = v
        elif node_id not in self._last_metrics:
            # delta-gated summary but no cache (this plane restarted
            # with the head): ask the agent to re-send everything —
            # the DeltaReporter epoch-handshake pattern
            need_metrics = True
        cached = self._last_metrics.get(node_id)
        if cached:
            now = time.time()
            for name, value in cached.items():
                self.ts_record(node_id[:12], name, value, now)
        if objects_delta is not None:
            # delta vs what this agent last acked — applied per shard,
            # bumping only the touched shards' versions.  A delta built
            # against a stale epoch (head restarted underneath the
            # agent) is only safe if it is a full re-send; otherwise the
            # epoch in our reply makes the agent re-send everything.
            # The directory is lock-per-shard (PR 8): safe to write
            # from this thread while the core reads locations.
            if objects_delta.get("full") \
                    or objects_delta.get("epoch") == self.dir.epoch:
                self.dir.apply_delta(
                    node_id, objects_delta.get("add") or (),
                    objects_delta.get("remove") or (),
                    full=bool(objects_delta.get("full")))
        chaos_stale = seen_chaos_version != (member or {}).get(
            "chaos_version", 0)
        if not chaos_stale and chaos_fired:
            # counts only make sense against the CURRENT rule set
            with self._fired_lock:
                self._chaos_fired[node_id] = dict(chaos_fired)
        # forward what the scheduling core owns: entry freshness,
        # availability for placement, pending demand for the autoscaler
        self.to_core.put({"node_id": node_id,
                          "available": dict(available or {}),
                          "pending": pending or [],
                          "memory": memory,
                          "pressure": pressure,
                          "hb_mono": now_mono})
        reply = {"cluster": self._publish_view(member),
                 "version": (member or {}).get("version", 0),
                 "dir_epoch": self.dir.epoch,
                 "dir": self.dir.updates_since(dir_versions),
                 "scalable": (member or {}).get("scalable") or []}
        if need_metrics:
            reply["need_metrics"] = True
        if chaos_stale:
            # catch-up for agents that missed the chaos_rules push (late
            # join, agent restart, dropped connection)
            reply["chaos"] = (member or {}).get("chaos_payload") or {
                "rules": [], "version": 0}
        if seen_quarantine_version != (member or {}).get(
                "quarantine_version", 1):
            reply["quarantine"] = (member or {}).get(
                "quarantine_payload") or {"version": 1, "entries": {}}
        return reply

    def _publish_view(self, member: Optional[Dict[str, Any]]
                      ) -> Dict[str, Any]:
        """Assemble the gossiped cluster view: static membership from
        the core's snapshot, availability/pressure from the freshest
        heartbeat telemetry (falling back to registration-time values
        for nodes that have not beaten yet)."""
        view: Dict[str, Any] = {}
        for nid, ninfo in ((member or {}).get("nodes") or {}).items():
            telem = self.node_telem.get(nid) or {}
            avail = telem.get("available")
            if avail is None:
                avail = ninfo.get("available") or {}
            pressure = telem.get("pressure", ninfo.get("pressure"))
            view[nid] = {"addr": ninfo["addr"],
                         "res": {"total": ninfo.get("total") or {},
                                 "available": avail},
                         "labels": ninfo.get("labels") or {},
                         "xfer": ninfo.get("xfer", 0),
                         **({"draining": True}
                            if ninfo.get("draining") else {}),
                         **({"pressure": pressure}
                            if pressure is not None else {})}
        self.cluster.publish({"view": view,
                              "version": (member or {}).get("version", 0)})
        return view

    def drop_node(self, node_id: str) -> None:
        """Core-loop call on node death: prune this plane's per-node
        state.  Dict pops are GIL-atomic; the ring takes its lock."""
        self.node_telem.pop(node_id, None)
        self._last_metrics.pop(node_id, None)
        with self._fired_lock:
            self._chaos_fired.pop(node_id, None)
        with self._ts_lock:
            for key in [k for k in self._tseries
                        if k[0] == node_id[:12]]:
                self._tseries.pop(key, None)

    # ---- chaos-fired bookkeeping (any thread) --------------------------

    def chaos_fired_counts(self) -> Dict[str, Dict[str, int]]:
        with self._fired_lock:
            return {nid: dict(c) for nid, c in self._chaos_fired.items()}

    def clear_chaos_fired(self) -> None:
        with self._fired_lock:
            self._chaos_fired.clear()

    # ---- time-series ring (any thread; internally locked) --------------

    def ts_record(self, node: str, name: str, value: float,
                  ts: Optional[float] = None) -> None:
        key = (node, name)
        with self._ts_lock:
            dq = self._tseries.get(key)
            if dq is None:
                from collections import deque as _deque

                dq = self._tseries[key] = _deque(
                    maxlen=int(config.timeseries_max_samples))
            try:
                dq.append((ts if ts is not None else time.time(),
                           float(value)))
            except (TypeError, ValueError):
                pass

    def ts_tail(self, metric: str, k: int = 10) -> Dict[str, List[float]]:
        """Last k ring samples of one heartbeat metric per node — the
        autoscaler's trend-smoothing input (PR-6 time-series ring)."""
        out: Dict[str, List[float]] = {}
        with self._ts_lock:
            for (node, name), dq in self._tseries.items():
                if name == metric and dq:
                    out[node] = [v for _ts, v in list(dq)[-k:]]
        return out

    def timeseries_payload(self) -> Dict[str, Any]:
        with self._ts_lock:
            items = [((node, name), list(dq))
                     for (node, name), dq in sorted(self._tseries.items())]
        return {"series": [
            {"node": node, "name": name,
             "points": [[round(ts, 3), v] for ts, v in pts]}
            for (node, name), pts in items]}
