"""Runtime config registry.

Equivalent of the reference's RAY_CONFIG X-macro registry
(reference: src/ray/common/ray_config_def.h — 219 entries, env-overridable
via RAY_<name> and cluster-wide via ray.init(_system_config=...)).

Here: declarative entries overridable per-process via ``RT_<NAME>`` env vars
and cluster-wide via ``ray_tpu.init(_system_config={...})`` (the dict is
serialized and handed to every spawned daemon/worker).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict


_DEFS: Dict[str, Any] = {}


def _def(name: str, default: Any) -> None:
    _DEFS[name] = default


# --- scheduling -------------------------------------------------------------
_def("max_direct_call_object_size", 100 * 1024)  # inline returns/args below this
_def("worker_lease_timeout_ms", 30_000)
_def("worker_pool_prestart_workers", 0)
_def("worker_idle_timeout_ms", 60_000)
_def("scheduler_top_k_fraction", 0.2)  # hybrid policy: top-k random among best
_def("scheduler_top_k_absolute", 5)    # ref: ray_config_def.h scheduler_top_k_absolute
_def("scheduler_spread_threshold", 0.5)
_def("task_retry_delay_ms", 100)
# how long a bundle reservation queues on the node agent for capacity to
# free (e.g. lingering task leases) before the head replans elsewhere
_def("pg_reserve_wait_ms", 2_000)
_def("actor_creation_retries", 3)
# --- object store -----------------------------------------------------------
_def("object_store_memory_bytes", 512 * 1024 * 1024)
_def("object_store_fallback_directory", "/tmp/ray_tpu_spill")
_def("object_spilling_threshold", 0.8)
_def("object_transfer_chunk_bytes", 4 * 1024 * 1024)
# --- bulk object-transfer plane (see _private/object_transfer.py) -----------
_def("object_transfer_enabled", True)   # False: legacy obj_chunk RPC pulls
_def("object_transfer_window", 8)       # in-flight chunk requests per stream
# objects at/above this ride several parallel stripe streams
_def("object_transfer_parallel_threshold", 64 * 1024 * 1024)
_def("object_transfer_max_streams", 2)
_def("object_transfer_sock_buf_bytes", 4 * 1024 * 1024)  # SO_SNDBUF/SO_RCVBUF
# --- locality-aware scheduling ----------------------------------------------
# minimum argument bytes a node must already hold before locality
# overrides the hybrid policy; also the floor for the object directory
# entries piggybacked on heartbeats (0 disables locality scheduling)
_def("locality_min_bytes", 1024 * 1024)
_def("object_directory_max_entries", 128)  # per-node heartbeat summary cap
# head object directory shard count: independent lock+version per
# oid-hash bucket, so heartbeat deltas / lookups / gossip on different
# buckets never serialize on one structure (see object_directory.py)
_def("object_directory_shards", 16)
# --- dispatch batching (see worker.py owner pump) ----------------------------
# max leases one batched request_leases frame may ask an agent for
_def("lease_request_batch_max", 16)
# executor-side result micro-batching: flush a batch_results frame when
# this many are buffered, or this many ms after the first
_def("dispatch_result_batch_max", 32)
_def("dispatch_result_flush_ms", 5)
# how long an agent waits after an owner's connection drops before
# reclaiming its leases — a transiently-dropped owner re-binds them on
# its next lease request within this window
_def("lease_orphan_grace_s", 3.0)
# --- control plane ----------------------------------------------------------
_def("gcs_health_check_period_ms", 3_000)   # ref: ray_config_def.h:841-847
_def("gcs_health_check_failure_threshold", 5)
_def("gcs_persist_interval_ms", 200)        # head table snapshot debounce
_def("gcs_reconnect_grace_s", 15.0)         # client retry window across a
                                            # head restart (ref: NotifyGCSRestart)
_def("pubsub_poll_timeout_ms", 30_000)
_def("rpc_connect_timeout_s", 10.0)
_def("rpc_call_timeout_s", 120.0)
# --- workers ----------------------------------------------------------------
_def("worker_register_timeout_s", 30.0)
_def("worker_startup_parallelism", 4)
# --- memory monitor (reference: memory_monitor.h:52 + ray_config_def.h
# memory_usage_threshold / memory_monitor_refresh_ms) -------------------------
_def("memory_usage_threshold", 0.95)          # node memory fraction
_def("memory_monitor_refresh_ms", 250)        # 0 disables the monitor
_def("memory_monitor_min_kill_interval_ms", 1_000)
_def("memory_monitor_test_usage_file", "")    # test hook: fraction in a file
# virtual node-memory total: > 0 makes the watchdog compute pressure as
# sum(per-worker RSS) / this total instead of reading /proc/meminfo —
# several agents on one host each get an ISOLATED, deterministic memory
# envelope (tests/bench overcommit a 512MB "node" without ever stressing
# the real machine), and it doubles as the node's `memory` resource
# total for bin-packing
_def("memory_monitor_node_total_bytes", 0)
# OOM kills draw from this separate per-task retry budget — never from
# max_retries — with jittered exponential backoff so the retry lands
# after pressure clears instead of immediately back into the same wall
# (-1 = unlimited, mirroring max_retries semantics)
_def("task_oom_retries", 5)
_def("task_oom_retry_max_backoff_ms", 5_000)
# --- poison-task quarantine (head.py) ----------------------------------------
# a task/actor class whose executions OOM-kill or crash workers this
# many CONSECUTIVE times across the cluster is quarantined: further
# submissions fail fast with PoisonedTaskError instead of churning
# workers.  TTL-expiring; `rtpu quarantine clear` lifts it early.
_def("poison_task_threshold", 3)
_def("poison_task_ttl_s", 60.0)
# --- checksummed transfers ---------------------------------------------------
# CRC32 per object computed at seal, carried in directory entries and
# the transfer control protocol, verified on pull: a corrupt copy is
# detected, reported back to its holder (which re-verifies and drops a
# genuinely-bad secondary), and the pull retries from an alternate
# holder.  False skips both the seal-time hash and pull verification.
_def("object_checksums", True)
# --- put() backpressure ------------------------------------------------------
# a put whose shm allocation fails while the arena holds bytes that can
# still free (pinned entries whose pins will release) waits up to this
# long — bounded further by the ambient deadline — for room before
# taking the disk-fallback path; 0 restores immediate fallback
_def("put_backpressure_max_s", 10.0)
# --- head control-plane sharding (see _private/head_shards.py) ---------------
# ingest event-loop threads beside the head's scheduling loop:
#   0 = single-loop compat (planes run on the head loop, no threads)
#   1 = one shared ingest loop for both planes
#   2 = task-event loop + telemetry loop (the default topology)
_def("head_ingest_shards", 2)
# task-event inbox bound, in FRAMES: past this the oldest queued frame
# drops (counted in ray_tpu_task_events_dropped_total{shard=...}) so a
# runaway burst cannot grow head memory without bound; 0 = unbounded
_def("head_inbox_max_frames", 4096)
# --- observability ----------------------------------------------------------
_def("task_events_buffer_size", 10_000)
_def("metrics_report_interval_ms", 5_000)
_def("event_stats", True)
# --- memory/object accounting (rtpu memory / rtpu summary) -------------------
# head-side leak-scan cadence: every interval the head joins the agents'
# store breakdowns with the owners' reference tables, flags leaks, and
# sets ray_tpu_object_leaked_bytes (0 disables the loop; on-demand
# /api/memory views still work).  The scan fans out to every agent and
# registered driver, so the cadence is deliberately lazy relative to
# the TTL — detection latency is bounded by interval + ttl
_def("memory_scan_interval_s", 5.0)
# a borrowed ref still registered past this age, a pinned object with no
# live owner older than this, or a channel slot no live compiled graph
# claims for this long, is flagged in the `leaks` view
_def("object_leak_ttl_s", 30.0)
# bounded aggregation: refs per worker summary (largest first),
# store entries per node payload, and objects in the head's joined
# top-N table.  BOTH caps must sit far above normal working-set sizes:
# truncating either marks the whole view partial, which suspends the
# dead-owner/channel tripwires until the population shrinks (a 10k-ref
# driver is an ordinary workload — see tests/test_scale.py).
_def("memory_summary_max_refs", 20000)
_def("memory_summary_max_objects", 20000)
_def("memory_view_top_n", 50)
# record the user call-site (file:line:function) on put()/.remote()
# minted refs; False drops the ~µs frame walk from the submit hot path
_def("memory_record_call_sites", True)
# --- live introspection (see _private/profiling.py + log_monitor.py) ---------
_def("profiler_default_hz", 99)            # sampling rate when none given
_def("profiler_max_duration_s", 300.0)     # hard cap on one profile run
_def("loop_lag_probe_interval_ms", 500)    # event-loop lag probe cadence
_def("log_monitor_poll_ms", 250)           # agent-side log tail cadence
_def("log_monitor_max_read_bytes", 256 * 1024)  # per file per poll
_def("log_to_driver", True)                # stream worker logs to drivers
_def("timeseries_max_samples", 240)        # head ring depth per series
# --- serve data plane (see serve/http.py) ------------------------------------
_def("serve_max_inflight_requests", 1024)  # proxy-wide gate; 503 beyond
_def("serve_max_header_bytes", 65536)      # request line + headers cap (431)
_def("serve_max_body_bytes", 32 * 1024 * 1024)  # request body cap (413)
_def("serve_pipeline_depth", 32)  # pipelined requests per connection
# --- compiled-DAG channels (see dag/channel.py + dag/execution.py) -----------
_def("dag_channel_buffer_bytes", 1024 * 1024)  # per-version payload capacity
_def("dag_channel_poll_max_s", 0.002)  # backoff cap while polling a channel
_def("dag_monitor_interval_s", 0.2)    # driver loop-ref death-watch cadence;
# bounds how long in-flight CompiledDAGRef.get() calls can hang past an
# actor death before they raise
_def("dag_teardown_timeout_s", 10.0)
# --- chaos fault injection (see _private/fault_injection.py) -----------------
_def("chaos_enabled", True)   # the plane is inert until rules are installed
_def("chaos_seed", 0)         # default seed for rules created without one
# --- fault tolerance ---------------------------------------------------------
# stateful actor restarts (__rt_save__/__rt_restore__ hooks, worker.py):
# snapshot storage root ("" = <session_dir>/actor_state), save cadence in
# completed method calls, and snapshots retained per actor
_def("actor_state_storage_path", "")
_def("actor_state_save_every_n", 1)
_def("actor_state_keep", 2)
# serve: replica health-check budget at deploy time (was a hardcoded
# 600 — one wedged replica constructor stalled deploys for 10 minutes),
# and how many surviving replicas a handle call retries against when the
# one it picked died mid-flight
_def("serve_replica_health_timeout_s", 120.0)
_def("serve_dead_replica_retries", 3)
# --- LLM serving tier (see serve/llm.py) -------------------------------------
_def("llm_page_size", 16)           # KV-cache tokens per page
_def("llm_kv_pages", 0)             # pages per replica; 0 = sized so
# max_batch sequences can run at max_seq_len simultaneously
_def("llm_max_batch_size", 32)      # decode lanes per engine step
_def("llm_prefill_chunk", 64)       # prompt tokens prefetched per step —
# bounds how long one long prompt can stall in-flight decodes
_def("llm_prefill_lanes", 8)        # sequences prefilling one chunk each
# per step (batched prefill: admitting N streams costs N/lanes steps)
_def("llm_stream_flush_tokens", 4)  # tokens coalesced per stream item
# after the first (the first token flushes immediately for TTFT); each
# item costs a stream push + a ref resolution + an SSE chunk, so this
# is the per-token transport amortizer
_def("llm_admission_queue", 256)    # queued sequences before 503 shed
_def("llm_detach_grace_s", 2.0)     # KV pages survive a vanished consumer
# this long (the re-attach window for proxy resume) before recycling
_def("llm_done_seq_ttl_s", 30.0)    # finished sequences replayable (by
# request_id) this long for duplicate/late retries
_def("llm_prefix_sharing", True)    # copy-on-write prefix sharing: admit
# sequences whose page-aligned prompt prefix matches a live sequence's
# onto the SAME physical KV pages (refcounted; recycled at refcount 0),
# prefilling only from the first unshared token
_def("llm_attention_impl", "auto")  # decode attention: "paged" = Pallas
# paged-attention kernel over block tables (cost tracks USED context),
# "dense" = gather-then-dense reference (cost tracks max context),
# "auto" = paged
_def("llm_disagg_min_prompt", 0)    # disaggregated prefill: prompts at
# least this long route their prefill to the dedicated prefill pool
# (when llm_deployment(prefill_replicas=N) created one); shorter
# prompts prefill on the decode replica where queueing costs more than
# the shipped-KV hop saves
# --- elastic autoscaling (see autoscaler/ + head drain state machine) --------
# sustained-demand hysteresis: backlog (demand that FITS existing nodes
# but queues behind busy capacity) must persist for this many
# consecutive autoscaler passes before it launches nodes — one burst
# that drains on its own must not thrash the cluster.  Demand NO
# existing node can ever fit scales up immediately (waiting cannot
# resolve infeasibility).
_def("autoscaler_upscale_consecutive", 3)
# graceful drain budget: past this the drain is abandoned (the node
# keeps running; the autoscaler retries later) rather than force-killed
_def("drain_timeout_s", 60.0)
# how long a drained node's agent gets to finish in-flight leases
# before the remaining (non-migratable) workers are cut loose
_def("drain_lease_grace_s", 20.0)
# scheduler-latency SLO pressure: queued-phase p99 above this for a
# sustained window counts as scale-up pressure even without parked
# infeasible demand (0 disables the signal)
_def("autoscaler_sched_p99_threshold_ms", 0.0)
# --- serve replica autoscaling (num_replicas="auto") -------------------------
# target ongoing requests per replica before another replica is added
_def("serve_autoscale_target_ongoing", 2)
_def("serve_autoscale_min_replicas", 1)
_def("serve_autoscale_max_replicas", 8)
# upscale needs the computed desired above current for this many
# consecutive reconcile rounds; downscale needs it below for this long
_def("serve_autoscale_up_consecutive", 2)
_def("serve_autoscale_down_delay_s", 10.0)
# --- LLM sampling (jit-static decode knobs; see serve/llm.py) ----------------
_def("llm_temperature", 0.0)  # 0 = greedy argmax (the decode-identity tier)
_def("llm_top_k", 0)          # 0 = full vocab; >0 = sample among top-k
# --- end-to-end deadlines (see _private/deadlines.py) ------------------------
# owner-side deadline sweep cadence: how often queued/in-flight tasks
# with deadlines are checked (the sweep only runs while any exist)
_def("deadline_check_interval_ms", 50)
# after the cooperative cancel of a deadline-expired RUNNING task, how
# long before the force path (worker exit) fires if it is still running
_def("deadline_force_cancel_grace_s", 1.0)
# --- serve tail tolerance (see serve/api.py) ---------------------------------
# hedge delay used by hedge_after="p99" until enough latency samples
# exist to compute a real p99 (and its floor thereafter)
_def("serve_hedge_min_delay_s", 0.05)
# per-replica circuit breaker: failure score (time-decayed; errors and
# hedge-slow events each add 1) at which the circuit opens, the decay
# horizon, and how long an open circuit waits before one half-open
# probe is let through
_def("serve_circuit_fail_threshold", 3.0)
_def("serve_circuit_decay_s", 5.0)
_def("serve_circuit_cooldown_s", 1.0)
# --- distributed tracing (see _private/tracing.py) ---------------------------
_def("tracing_enabled", True)
_def("trace_sampling_ratio", 1.0)      # root-span sampling probability
_def("trace_buffer_size", 4096)        # per-process finished-span buffer
_def("trace_store_max_traces", 1000)   # head-side bounded trace store
_def("trace_store_max_spans", 512)     # per-trace span cap at the head


class _Config:
    def __init__(self):
        self._overrides: Dict[str, Any] = {}

    def initialize(self, system_config: Dict[str, Any] | None) -> None:
        if system_config:
            for k, v in system_config.items():
                if k not in _DEFS:
                    raise ValueError(f"Unknown system config key: {k}")
                self._overrides[k] = v

    def serialize(self) -> str:
        return json.dumps(self._overrides)

    @classmethod
    def deserialize_into_env(cls, serialized: str) -> Dict[str, str]:
        """Build the env-var dict to pass to a child process."""
        overrides = json.loads(serialized)
        return {f"RT_{k.upper()}": json.dumps(v) for k, v in overrides.items()}

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in _DEFS:
            raise AttributeError(f"Unknown config: {name}")
        env = os.environ.get(f"RT_{name.upper()}")
        if env is not None:
            try:
                return json.loads(env)
            except json.JSONDecodeError:
                return env
        if name in self._overrides:
            return self._overrides[name]
        return _DEFS[name]


config = _Config()
