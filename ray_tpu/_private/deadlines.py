"""End-to-end request deadlines: context propagation + enforcement glue.

Every robustness layer before this handled crash-stop failures; tail
latency comes from components that are *slow, not dead* — and nothing
slow can be routed around unless requests carry a latency bound.  This
module makes a deadline first-class task metadata, the way trace
context already is (tracing.py):

  - A deadline is an ABSOLUTE wall-clock instant (epoch seconds,
    ``time.time()`` base) so it survives process hops — the gRPC
    deadline model, not a per-hop timeout that resets at every layer.
  - The ACTIVE deadline rides a contextvar.  ``.options(timeout_s=…)``
    stamps ``min(now + timeout_s, ambient)`` into the TaskSpec;
    the executing worker re-activates the spec's deadline, so nested
    ``.remote()`` calls and ``get()`` calls inside the task body
    inherit the caller's remaining budget automatically.
  - Serve's HTTP ingress continues external deadlines from an
    ``X-Request-Deadline-Ms`` header (absolute epoch milliseconds);
    malformed values are ignored, never an error.

Enforcement sites (each increments
``ray_tpu_deadline_exceeded_total{where=…}``):
  queued     owner pump / agent lease queue / worker task queue — the
             task fails fast with DeadlineExceededError WITHOUT running
  running    the owner's deadline sweep resolves an in-flight task and
             cancels it on the worker (cooperative, then force)
  get        ``get()`` spends only the remaining ambient budget
  admission  the LLM engine refuses sequences whose remaining budget
             cannot cover prefill + one decode step
"""

from __future__ import annotations

import contextvars
import time
from typing import Optional

DEADLINE_HEADER = "x-request-deadline-ms"

_current: "contextvars.ContextVar[Optional[float]]" = \
    contextvars.ContextVar("rt_deadline", default=None)

_metric = None


def current_deadline() -> Optional[float]:
    """The active absolute deadline (epoch seconds), or None."""
    return _current.get()


def activate(deadline: Optional[float]):
    """Make `deadline` the active deadline on this thread/coroutine;
    returns a token for `restore`.  None clears (an explicitly
    undeadlined scope inside a deadlined one)."""
    return _current.set(deadline)


def restore(token) -> None:
    _current.reset(token)


def effective_deadline(timeout_s: Optional[float] = None,
                       now: Optional[float] = None) -> Optional[float]:
    """Combine an explicit per-call timeout with the ambient deadline:
    the TIGHTER of the two wins (a callee can shrink its budget, never
    grow past the caller's).  None when neither applies."""
    ambient = _current.get()
    if timeout_s is None:
        return ambient
    now = time.time() if now is None else now
    mine = now + float(timeout_s)
    return mine if ambient is None else min(mine, ambient)


def remaining(deadline: Optional[float] = None,
              now: Optional[float] = None) -> Optional[float]:
    """Seconds left on `deadline` (the ambient one when omitted); never
    negative.  None = unbounded."""
    if deadline is None:
        deadline = _current.get()
    if deadline is None:
        return None
    now = time.time() if now is None else now
    return max(0.0, deadline - now)


def expired(deadline: Optional[float],
            now: Optional[float] = None) -> bool:
    if not deadline:
        return False
    return (time.time() if now is None else now) >= deadline


def from_header(value) -> Optional[float]:
    """Parse an ``X-Request-Deadline-Ms`` header: absolute epoch
    MILLISECONDS.  Malformed or non-positive values return None — the
    request proceeds unbounded, never an error (matching the
    traceparent contract in tracing.py)."""
    if value is None:
        return None
    try:
        ms = float(str(value).strip())
    except (TypeError, ValueError):
        return None
    if ms <= 0:
        return None
    return ms / 1000.0


def count_exceeded(where: str, n: int = 1) -> None:
    """Increment ``ray_tpu_deadline_exceeded_total{where=…}``
    (where = queued | running | get | admission)."""
    global _metric
    if _metric is None:
        from ray_tpu._private.metrics import deadline_metrics

        _metric = deadline_metrics()
    _metric.inc(n, tags={"where": where})
