"""Shared-memory object store (plasma equivalent).

Equivalent role to the reference's plasma store
(reference: src/ray/object_manager/plasma/store.h,
object_lifecycle_manager.h, create_request_queue.h): one store per node,
living inside the node agent's event loop; clients (driver/workers on the
same host) mmap the same arena file and read sealed objects zero-copy.

Differences from the reference, chosen for the TPU build:
- The arena is a plain file in /dev/shm mmap'd MAP_SHARED by name — no
  fd-passing over a Unix socket (reference: plasma/fling.cc) is needed
  because clients can open the file themselves.
- Allocation is a 64-byte-aligned first-fit free list (reference uses a
  dlmalloc arena, plasma/dlmalloc.cc). 64-byte alignment keeps numpy /
  jax host-array frames cache-line aligned for fast host->device DMA.
- Objects that do not fit in the arena fall back to disk files
  (reference: fallback allocation in plasma/plasma_allocator.cc), and the
  store spills cold primaries / evicts secondary copies under pressure
  (reference: eviction_policy.h, local_object_manager.cc).

Client reads stay pinned while any deserialized value still references
the buffer: `Buffer` implements the PEP 688 buffer protocol, so arrays
produced by zero-copy deserialization keep the `Buffer` alive and its
collection releases the pin (reference: PlasmaBuffer in _raylet.pyx).
"""

from __future__ import annotations

import asyncio
import mmap
import os
import sys
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

_ALIGN = 64


class ObjectStoreFull(Exception):
    pass


class ObjectAlreadyExists(Exception):
    pass


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


# Not exposed by every CPython build; the raw Linux value is stable.
# Populates writable PTEs for the CALLING process's mapping without
# touching data — safe concurrently with other processes' writes.
_MADV_POPULATE_WRITE = getattr(mmap, "MADV_POPULATE_WRITE", 23)


class ShmArena:
    """A named, mmap'd shared-memory file that any local process can attach."""

    def __init__(self, path: str, size: int, create: bool):
        self.path = path
        self.size = size
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, size)
            else:
                self.size = os.fstat(fd).st_size
            self._mmap = mmap.mmap(fd, self.size, mmap.MAP_SHARED)
        finally:
            os.close(fd)
        self.view = memoryview(self._mmap)
        # True once this process's page tables cover the whole mapping
        # writable — writers can then skip per-put page touching
        self.populated = False

    @classmethod
    def create(cls, path: str, size: int) -> "ShmArena":
        arena = cls(path, size, create=True)
        arena._prefault()
        return arena

    def _prefault(self) -> None:
        """Touch every page once at creation so client writes never pay
        tmpfs fault+zero costs (measured 4x put-bandwidth difference:
        ~1.3 GB/s faulting vs ~6 GB/s into resident pages)."""
        try:
            self._mmap.madvise(_MADV_POPULATE_WRITE)
            self.populated = True
            return
        except (AttributeError, ValueError, OSError):
            pass
        zeros = b"\0" * (16 * 1024 * 1024)
        view = self.view
        for off in range(0, self.size, len(zeros)):
            chunk = min(len(zeros), self.size - off)
            view[off:off + chunk] = zeros[:chunk]
        self.populated = True

    def populate_async(self) -> None:
        """Install writable PTEs for this process's mapping in the
        background (attachers: drivers/workers).  Data is never touched,
        so this is safe while other processes write objects."""
        import threading

        def run():
            try:
                self._mmap.madvise(_MADV_POPULATE_WRITE)
                self.populated = True
            except Exception:
                pass  # per-put write-touch remains the fallback

        threading.Thread(target=run, name="rt-arena-populate",
                         daemon=True).start()

    @classmethod
    def attach(cls, path: str) -> "ShmArena":
        return cls(path, 0, create=False)

    def close(self, unlink: bool = False) -> None:
        try:
            self.view.release()
        except Exception:
            pass
        try:
            self._mmap.close()
        except Exception:
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class FreeListAllocator:
    """First-fit free-list allocator with coalescing; offsets 64-aligned."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        # sorted list of (offset, size) free blocks
        self._free: List[Tuple[int, int]] = [(0, capacity)]
        self.allocated = 0

    def largest_free(self) -> int:
        return max((blk for _, blk in self._free), default=0)

    def alloc(self, size: int) -> Optional[int]:
        size = _aligned(max(size, 1))
        for i, (off, blk) in enumerate(self._free):
            if blk >= size:
                if blk == size:
                    self._free.pop(i)
                else:
                    self._free[i] = (off + size, blk - size)
                self.allocated += size
                return off
        return None

    def free(self, offset: int, size: int) -> None:
        size = _aligned(max(size, 1))
        self.allocated -= size
        # insert keeping order, then coalesce neighbors
        lo, hi = 0, len(self._free)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._free[mid][0] < offset:
                lo = mid + 1
            else:
                hi = mid
        self._free.insert(lo, (offset, size))
        # coalesce with next
        if lo + 1 < len(self._free):
            off, blk = self._free[lo]
            noff, nblk = self._free[lo + 1]
            if off + blk == noff:
                self._free[lo] = (off, blk + nblk)
                self._free.pop(lo + 1)
        # coalesce with prev
        if lo > 0:
            poff, pblk = self._free[lo - 1]
            off, blk = self._free[lo]
            if poff + pblk == off:
                self._free[lo - 1] = (poff, pblk + blk)
                self._free.pop(lo)


@dataclass
class _Entry:
    size: int
    location: str  # "shm" | "disk"
    offset: int = 0  # shm only
    path: str = ""  # disk only
    sealed: bool = False
    primary: bool = True
    # CRC32 of the payload, fixed at seal (bytes are immutable after)
    # and computed lazily on first export (object_checksums): carried in
    # directory entries and the transfer control protocol, verified by
    # pullers — detects post-seal/in-transit corruption end to end.
    # None for channels (mutable) and when checksums are disabled.
    crc: Optional[int] = None
    # reusable pinned channel slot (compiled-DAG channels): permanently
    # pinned, never spilled/evicted, excluded from the object directory,
    # and writable in place after seal (single-writer ring discipline is
    # enforced by the channel layer, not the store)
    channel: bool = False
    created_at: float = field(default_factory=time.monotonic)
    last_used: float = field(default_factory=time.monotonic)
    pins: Dict[str, int] = field(default_factory=dict)  # client_id -> count

    @property
    def pinned(self) -> bool:
        return any(v > 0 for v in self.pins.values())


class StoreCore:
    """Server-side object store logic; runs inside the node agent's loop.

    Async methods may wait (get blocks until seal); mutation is effectively
    serialized by the single event loop.
    """

    def __init__(self, arena_path: str, capacity: int, spill_dir: str):
        from ray_tpu import _native

        self.arena = ShmArena.create(arena_path, capacity)
        # native C allocator when the toolchain built it; Python fallback
        # is behaviorally identical (reference: plasma/malloc.cc native)
        self.alloc = _native.make_allocator(capacity) \
            or FreeListAllocator(capacity)
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self.objects: Dict[str, _Entry] = {}
        self._seal_events: Dict[str, asyncio.Event] = {}
        self._deleted: Set[str] = set()  # freed oids: get() fails fast
        self.num_spilled = 0
        self.num_evicted = 0
        # put()-backpressure wakeup: set whenever bytes free (pin
        # release, drop, free) so a create waiting for shm room retries
        # event-driven instead of polling (created lazily — __init__
        # may run without a loop)
        self._room_event: Optional[asyncio.Event] = None

    # ---- lifecycle -------------------------------------------------------

    def create(self, oid: str, size: int, primary: bool = True,
               no_disk_fallback: bool = False) -> Dict[str, Any]:
        """Reserve space for oid. Returns {"location","offset"|"path"}.
        ``no_disk_fallback`` raises ObjectStoreFull instead of spilling
        the create to a disk file when shm cannot fit it right now —
        the put-backpressure wait path probes with it."""
        if oid in self.objects:
            raise ObjectAlreadyExists(oid)
        self._deleted.discard(oid)
        if size <= self.arena.size:
            offset = self.alloc.alloc(size)
            if offset is None:
                self._reclaim(size)
                offset = self.alloc.alloc(size)
            if offset is not None:
                self.objects[oid] = _Entry(size=size, location="shm", offset=offset,
                                           primary=primary)
                return {"location": "shm", "offset": offset, "size": size}
        if no_disk_fallback:
            raise ObjectStoreFull(
                f"no shm room for {size} bytes (arena "
                f"{self.alloc.capacity - self.alloc.allocated} free)")
        # fallback to disk (reference: plasma fallback allocation)
        path = os.path.join(self.spill_dir, f"obj-{oid}")
        with open(path, "wb") as f:
            f.truncate(size)
        self.objects[oid] = _Entry(size=size, location="disk", path=path,
                                   primary=primary)
        return {"location": "disk", "path": path, "size": size}

    def _wake_room_waiters(self) -> None:
        if self._room_event is not None:
            self._room_event.set()

    def room_may_free(self, size: int) -> bool:
        """Whether waiting could ever get `size` into shm: the object
        fits the arena at all, and bytes exist that CAN free — pinned
        entries (pins release), unsealed creates (they seal or abort),
        or freed-but-pinned leftovers.  When everything resident is
        unpinned+sealed, _reclaim already did its best and waiting is
        pointless."""
        if size > self.arena.size:
            return False
        for oid, e in self.objects.items():
            if e.location != "shm" or e.channel:
                continue
            if e.pinned or not e.sealed or oid in self._deleted:
                return True
        return False

    async def create_with_backpressure(self, oid: str, size: int,
                                       primary: bool = True,
                                       wait_s: float = 0.0) -> Dict[str, Any]:
        """create(), but a put that would fall to DISK only because the
        arena is transiently full of pinned/unsealed bytes blocks up to
        ``wait_s`` (the client bounds this by its ambient deadline) for
        room to free — backpressure instead of silently flooding the
        slow path.  After the wait (or when nothing can free) the
        normal create semantics apply: disk fallback, and only a truly
        unservable create raises."""
        deadline = time.monotonic() + max(0.0, wait_s)
        while True:
            try:
                return self.create(oid, size, primary=primary,
                                   no_disk_fallback=True)
            except ObjectStoreFull:
                pass
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not self.room_may_free(size):
                return self.create(oid, size, primary=primary)
            if self._room_event is None:
                self._room_event = asyncio.Event()
            self._room_event.clear()
            try:
                await asyncio.wait_for(self._room_event.wait(),
                                       min(remaining, 0.25))
            except asyncio.TimeoutError:
                pass  # re-probe: a pin may have released without a wake

    def create_channel(self, oid: str, size: int) -> Dict[str, Any]:
        """Reserve a reusable pinned shm slot for a compiled-DAG channel
        (writer-node slot or reader-node mirror).  Sealed immediately
        (readers mmap it for the channel's whole life), permanently
        pinned so reclaim can never spill or evict it, and zeroed so
        stale arena bytes cannot masquerade as a published version.
        Channels must live in shm — mirror pushes and zero-copy reads
        write through the arena mapping — so an arena too full to hold
        one raises instead of falling back to disk.  Idempotent per oid
        (a retried compile reuses the slot)."""
        entry = self.objects.get(oid)
        if entry is not None:
            if entry.channel and entry.size == size:
                return {"location": "shm", "offset": entry.offset,
                        "size": entry.size}
            raise ObjectAlreadyExists(oid)
        self._deleted.discard(oid)
        offset = self.alloc.alloc(size)
        if offset is None:
            self._reclaim(size)
            offset = self.alloc.alloc(size)
        if offset is None:
            raise ObjectStoreFull(
                f"cannot allocate a {size}-byte channel slot; channels "
                "require shm (lower max_in_flight / buffer_size_bytes or "
                "grow object_store_memory)")
        entry = _Entry(size=size, location="shm", offset=offset,
                       primary=True, sealed=True, channel=True)
        entry.pins["__channel__"] = 1
        self.objects[oid] = entry
        self.arena.view[offset:offset + size] = b"\0" * size
        return {"location": "shm", "offset": offset, "size": size}

    def destroy_channel(self, oid: str) -> None:
        """Release a channel slot; no-op for unknown/non-channel oids."""
        entry = self.objects.get(oid)
        if entry is None or not entry.channel:
            return
        entry.pins.pop("__channel__", None)
        self._deleted.add(oid)
        if not entry.pinned:
            self._drop(oid, entry)

    def seal(self, oid: str) -> None:
        entry = self.objects.get(oid)
        if entry is None:
            raise KeyError(f"seal of unknown object {oid}")
        entry.sealed = True
        ev = self._seal_events.pop(oid, None)
        if ev is not None:
            ev.set()

    def compute_crc(self, entry: _Entry) -> Optional[int]:
        """CRC32 of an entry's current payload bytes (None when
        checksums are disabled or the bytes are unreadable).  zlib.crc32
        runs ~1 GB/s+ in C; the directory floor (locality_min_bytes)
        keeps the entries that matter largest, and every byte hashed
        here is a byte a pull can verify later."""
        from ray_tpu._private.config import config

        if not config.object_checksums:
            return None
        try:
            if entry.location == "shm":
                return zlib.crc32(
                    self.arena.view[entry.offset:entry.offset + entry.size])
            crc = 0
            with open(entry.path, "rb") as f:
                while True:
                    chunk = f.read(8 * 1024 * 1024)
                    if not chunk:
                        return crc
                    crc = zlib.crc32(chunk, crc)
        except OSError:
            return None

    def verify_crc(self, oid: str) -> Optional[bool]:
        """Re-hash a sealed local copy against its seal-time checksum:
        True = intact, False = CORRUPT, None = unverifiable (no stored
        crc / checksums off / not sealed here).  The corrupt-copy
        quarantine path runs this when a puller reports a mismatch."""
        entry = self.objects.get(oid)
        if entry is None or not entry.sealed or entry.crc is None:
            return None
        current = self.compute_crc(entry)
        if current is None:
            return None
        return current == entry.crc

    def checksum(self, oid: str) -> Optional[int]:
        """The seal-fixed CRC32 of a sealed object, computed lazily on
        first export (obj_info / directory summary) and cached — the
        bytes are immutable from seal, so hashing at first use is
        equivalent to hashing at seal while keeping the local put hot
        path at memcpy speed."""
        entry = self.objects.get(oid)
        if entry is None or not entry.sealed or entry.channel:
            return None
        if entry.crc is None:
            entry.crc = self.compute_crc(entry)
        return entry.crc

    def abort(self, oid: str) -> None:
        """Abort an unsealed create (client died mid-write)."""
        entry = self.objects.get(oid)
        if entry is not None and not entry.sealed:
            self._drop(oid, entry)

    def promote(self, oids: List[str]) -> Tuple[int, List[str]]:
        """Mark sealed copies PRIMARY (eviction-exempt).  The drain
        protocol hands primary-ship to the node that took over a
        draining node's copies — a secondary copy could be evicted
        under pressure the moment the original holder terminates.
        Returns (newly promoted, MISSING oids) — missing means this
        store holds no sealed copy (evicted/freed since the caller
        looked), which the drain must treat as not-handed-off."""
        n = 0
        missing: List[str] = []
        for oid in oids:
            entry = self.objects.get(oid)
            if entry is None or not entry.sealed:
                missing.append(oid)
            elif not entry.primary:
                entry.primary = True
                n += 1
        return n, missing

    async def get(self, oids: List[str], client_id: str,
                  wait_timeout: Optional[float] = None) -> List[Optional[Dict[str, Any]]]:
        """Wait for each oid to be sealed locally; pin and return locations.

        Returns None for objects not local (caller triggers a pull) and
        {"deleted": True} for freed objects.
        """
        deadline = None if wait_timeout is None else time.monotonic() + wait_timeout
        out: List[Optional[Dict[str, Any]]] = []
        for oid in oids:
            if oid in self._deleted:
                out.append({"deleted": True})
                continue
            entry = self.objects.get(oid)
            if entry is not None and not entry.sealed:
                ev = self._seal_events.setdefault(oid, asyncio.Event())
                remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                try:
                    await asyncio.wait_for(ev.wait(), remaining)
                except asyncio.TimeoutError:
                    out.append(None)
                    continue
                entry = self.objects.get(oid)
            if entry is None:
                out.append({"deleted": True} if oid in self._deleted else None)
                continue
            entry.last_used = time.monotonic()
            if entry.location == "disk":
                entry.pins[client_id] = entry.pins.get(client_id, 0) + 1
                out.append({"location": "disk", "path": entry.path, "size": entry.size})
            else:
                entry.pins[client_id] = entry.pins.get(client_id, 0) + 1
                out.append({"location": "shm", "offset": entry.offset, "size": entry.size})
        return out

    def contains(self, oid: str) -> bool:
        if oid in self._deleted:
            return False
        e = self.objects.get(oid)
        return e is not None and e.sealed

    def release(self, oid: str, client_id: str) -> None:
        entry = self.objects.get(oid)
        if entry is None:
            return
        n = entry.pins.get(client_id, 0)
        if n <= 1:
            entry.pins.pop(client_id, None)
            self._wake_room_waiters()  # pinned bytes became reclaimable
        else:
            entry.pins[client_id] = n - 1

    def release_client(self, client_id: str) -> None:
        """Drop all pins held by a disconnected client (worker death)."""
        for entry in self.objects.values():
            entry.pins.pop(client_id, None)
        self._wake_room_waiters()

    def drop_copy(self, oid: str) -> bool:
        """Evict ONE local copy without owner-delete semantics (unlike
        free, which marks the oid deleted so local getters fail with
        "freed"): used for a corrupt local copy — the owner's ref and
        other nodes' copies stay valid, and local getters simply see
        not-local and pull afresh.  Pinned copies are left in place (a
        reader may be mid-access of the bytes)."""
        entry = self.objects.get(oid)
        if entry is None or entry.pinned or entry.channel:
            return False
        self._drop(oid, entry)
        return True

    def free(self, oids: List[str]) -> None:
        """Owner-driven delete. Pinned objects are dropped once unpinned."""
        for oid in oids:
            entry = self.objects.get(oid)
            if entry is None:
                continue
            self._deleted.add(oid)
            if not entry.pinned:
                self._drop(oid, entry)
            # else: dropped lazily by _reclaim once pins go away

    def usage(self) -> Dict[str, Any]:
        return {
            "capacity": self.alloc.capacity,
            "allocated": self.alloc.allocated,
            "num_objects": len(self.objects),
            "num_spilled": self.num_spilled,
            "num_evicted": self.num_evicted,
        }

    def byte_breakdown(self) -> Dict[str, Any]:
        """Who owns this store's bytes — the node half of `rtpu memory`
        (reference role: the `ray memory --stats-only` store stats).

        Buckets are over the ALIGNED footprint for shm entries (what the
        allocator actually charges), so `shm_bytes` reconciles exactly
        with the allocator's own `arena_used` gauge; `object_bytes` is
        the raw payload sum the owner-side reference tables attribute.
        """
        out = {
            "capacity": self.alloc.capacity,
            "arena_used": self.alloc.allocated,
            "arena_free": self.alloc.capacity - self.alloc.allocated,
            "shm_bytes": 0, "object_bytes": 0,
            "pinned_bytes": 0, "pinned_objects": 0,
            "channel_bytes": 0, "channel_slots": 0,
            "spilled_bytes": 0, "spilled_files": 0,
            "unsealed_bytes": 0, "freed_pending_bytes": 0,
            "num_objects": len(self.objects),
            "num_spilled": self.num_spilled,
            "num_evicted": self.num_evicted,
        }
        for oid, e in self.objects.items():
            if e.location == "shm":
                footprint = _aligned(max(e.size, 1))
                out["shm_bytes"] += footprint
            else:
                footprint = e.size
                out["spilled_bytes"] += e.size
                out["spilled_files"] += 1
            out["object_bytes"] += e.size
            if e.channel:
                out["channel_bytes"] += footprint
                out["channel_slots"] += 1
            elif e.pinned:
                out["pinned_bytes"] += footprint
                out["pinned_objects"] += 1
            if not e.sealed:
                out["unsealed_bytes"] += footprint
            if oid in self._deleted:
                out["freed_pending_bytes"] += footprint
        return out

    def object_summary(self, min_bytes: int, limit: int) -> List[List[Any]]:
        """[oid, size] pairs for sealed objects at/above min_bytes —
        piggybacked on heartbeats to feed the head's object directory
        (locality-aware spillback + multi-source pull retry).  Largest
        first, so the cap drops the entries that matter least.
        min_bytes <= 0 means locality is disabled: report nothing
        rather than every tiny object.  Entries carry the seal-fixed
        CRC32 when ALREADY computed (a pull/obj_info hashed it) — the
        directory picks checksums up opportunistically rather than this
        heartbeat-path walk hashing gigabytes on the agent loop; pull
        verification itself always gets a fresh crc from the holder's
        obj_info handshake, where the hash cost amortizes into the
        transfer."""
        if min_bytes <= 0:
            return []
        out = [[oid, e.size, e.crc]
               for oid, e in self.objects.items()
               if e.sealed and e.size >= min_bytes and not e.channel
               and oid not in self._deleted]
        if len(out) > limit:
            out.sort(key=lambda p: -p[1])
            del out[limit:]
        return out

    def list_objects(self, limit: int = 1000) -> List[Dict[str, Any]]:
        """Object summaries for the state API (reference:
        GetObjectsInfo in node_manager.proto:405)."""
        now = time.monotonic()
        out = []
        for oid, e in self.objects.items():
            out.append({"object_id": oid, "size": e.size,
                        "location": e.location, "sealed": e.sealed,
                        "primary": e.primary, "pins": sum(e.pins.values()),
                        "channel": e.channel, "freed": oid in self._deleted,
                        "age_s": round(now - e.created_at, 3)})
            if len(out) >= limit:
                break
        return out

    # ---- memory pressure -------------------------------------------------

    def _drop(self, oid: str, entry: _Entry) -> None:
        self.objects.pop(oid, None)
        # wake any getters blocked on the seal event; they re-check and see
        # the object is gone (deleted/None) instead of waiting out the timeout
        ev = self._seal_events.pop(oid, None)
        if ev is not None:
            ev.set()
        if entry.location == "shm":
            self.alloc.free(entry.offset, entry.size)
            self._wake_room_waiters()
        else:
            try:
                os.unlink(entry.path)
            except OSError:
                pass

    def _reclaim(self, needed: int) -> None:
        """Evict/spill until `needed` bytes could plausibly be allocated.

        Order: freed-but-pinned leftovers, secondary copies (LRU), then
        spill cold primaries to disk (reference: eviction_policy.h +
        local_object_manager.cc spilling).
        """
        # 1. deleted objects whose pins have since been released
        for oid in [o for o in self._deleted if o in self.objects]:
            e = self.objects[oid]
            if not e.pinned:
                self._drop(oid, e)
        if self._headroom() >= needed:
            return
        # 2. evict unpinned sealed secondary copies, LRU first
        candidates = sorted(
            ((oid, e) for oid, e in self.objects.items()
             if e.location == "shm" and e.sealed and not e.pinned and not e.primary),
            key=lambda kv: kv[1].last_used,
        )
        for oid, e in candidates:
            self._drop(oid, e)
            self.num_evicted += 1
            if self._headroom() >= needed:
                return
        # 3. spill unpinned sealed primaries to disk, LRU first
        candidates = sorted(
            ((oid, e) for oid, e in self.objects.items()
             if e.location == "shm" and e.sealed and not e.pinned and e.primary),
            key=lambda kv: kv[1].last_used,
        )
        for oid, e in candidates:
            self._spill(oid, e)
            if self._headroom() >= needed:
                return

    def _headroom(self) -> int:
        return self.alloc.largest_free()

    def _spill(self, oid: str, entry: _Entry) -> None:
        path = os.path.join(self.spill_dir, f"obj-{oid}")
        with open(path, "wb") as f:
            f.write(self.arena.view[entry.offset:entry.offset + entry.size])
        self.alloc.free(entry.offset, entry.size)
        entry.location = "disk"
        entry.path = path
        entry.offset = 0
        self.num_spilled += 1

    def close(self, unlink: bool = True) -> None:
        self.arena.close(unlink=unlink)


class Buffer:
    """A pinned read view; collection of the last view releases the pin.

    Implements the PEP 688 buffer protocol so zero-copy consumers (numpy,
    pickle5 out-of-band loads) hold a reference to *this* object, not just
    the underlying mmap — guaranteeing the store cannot recycle the bytes
    while any deserialized value is alive.
    """

    def __init__(self, mv: memoryview, on_release: Optional[Callable[[], None]] = None):
        self._mv = mv
        self._on_release = on_release

    def __buffer__(self, flags: int) -> memoryview:
        return self._mv

    def __len__(self) -> int:
        return self._mv.nbytes

    def __del__(self):
        cb, self._on_release = self._on_release, None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass


class _SharedRelease:
    """Calls `fn` once, after `count` participants have all released."""

    def __init__(self, count: int, fn: Callable[[], None]):
        self._count = count
        self._fn = fn

    def __call__(self):
        self._count -= 1
        if self._count == 0 and self._fn is not None:
            fn, self._fn = self._fn, None
            fn()


# Buffer's __buffer__ hook (PEP 688) only reaches the C buffer protocol
# on Python 3.12+; earlier interpreters see a plain object and every
# out-of-band consumer (numpy, pyarrow) rejects it with "a bytes-like
# object is required"
_PEP688 = sys.version_info >= (3, 12)


def deserialize_pinned(data: memoryview, on_release: Optional[Callable[[], None]]) -> Any:
    """Zero-copy deserialize; the pin is released when the value (all of its
    out-of-band-backed parts) is garbage collected, or immediately if the
    value embeds no out-of-band buffers.

    On interpreters without PEP 688 the out-of-band frames are copied
    instead (one memcpy per frame) and the pin releases immediately —
    correctness over zero-copy; the wrapper path resumes on 3.12+."""
    from ray_tpu._private import serialization

    frames = serialization.unpack_frames(data)
    if len(frames) == 1 or on_release is None:
        import pickle

        value = pickle.loads(frames[0])
        if on_release is not None:
            on_release()
        return value
    import pickle

    if not _PEP688:
        try:
            return pickle.loads(frames[0],
                                buffers=[f.tobytes() for f in frames[1:]])
        finally:
            on_release()
    shared = _SharedRelease(len(frames) - 1, on_release)
    buffers = [Buffer(f, shared) for f in frames[1:]]
    return pickle.loads(frames[0], buffers=buffers)


class PlasmaClient:
    """Client-side store access: mmap attach + agent RPC for control.

    `rpc` is a SyncRpcClient to the node agent, whose RpcHost exposes
    store_create/store_seal/store_get/store_release/store_free/
    store_contains (see node_agent.py).
    """

    def __init__(self, arena_path: str, rpc, client_id: str):
        from ray_tpu import _native

        self.arena = ShmArena.attach(arena_path)
        self.arena.populate_async()  # writable PTEs off the put path
        self.rpc = rpc
        self.client_id = client_id
        _native.warm_up()  # compile off the put path

    @staticmethod
    def _touch(view) -> None:
        """WRITE-fault one byte per page before packing.

        A fresh attach has no PTEs for the (already-resident) tmpfs
        pages; taking the faults inside the copy throttles it to
        ~2 GB/s.  A read-touch maps pages read-only and still pays a
        write-protect upgrade fault per page during the copy — writing
        one byte per page instead installs writable PTEs in a single
        pass (safe: this region is exclusively ours until seal).
        Parallelized in C when available."""
        from ray_tpu import _native

        _native.touch_pages_write(view)

    def put_serialized(self, oid: str, frames, total_size: int,
                       primary: bool = True, wait_s: float = 0.0) -> None:
        from ray_tpu._private import serialization

        loc = self.rpc.call("store_create", oid=oid, size=total_size,
                            primary=primary,
                            **({"wait_s": wait_s, "timeout": wait_s + 60.0}
                               if wait_s > 0 else {}))
        try:
            if loc["location"] == "shm":
                out = self.arena.view[loc["offset"]:loc["offset"] + total_size]
                if not self.arena.populated:
                    self._touch(out)
                serialization.pack_into(frames, out)
            else:
                buf = bytearray(total_size)
                serialization.pack_into(frames, memoryview(buf))
                with open(loc["path"], "r+b") as f:
                    f.write(buf)
        except BaseException:
            self._abort(oid)
            raise
        self.rpc.call("store_seal", oid=oid)

    def put_raw(self, oid: str, data: bytes, primary: bool = True,
                wait_s: float = 0.0) -> None:
        loc = self.rpc.call("store_create", oid=oid, size=len(data),
                            primary=primary,
                            **({"wait_s": wait_s, "timeout": wait_s + 60.0}
                               if wait_s > 0 else {}))
        try:
            if loc["location"] == "shm":
                from ray_tpu import _native

                out = self.arena.view[loc["offset"]:loc["offset"] + len(data)]
                if not self.arena.populated:
                    self._touch(out)
                _native.copy_into(out, data)
            else:
                with open(loc["path"], "r+b") as f:
                    f.write(data)
        except BaseException:
            self._abort(oid)
            raise
        self.rpc.call("store_seal", oid=oid)

    def _abort(self, oid: str) -> None:
        try:
            self.rpc.call("store_abort", oid=oid)
        except Exception:
            pass

    def get_locations(self, oids: List[str],
                      timeout: Optional[float] = None) -> Dict[str, Dict[str, Any]]:
        """Resolve (and pin) locations for oids, waiting for seals.

        Missing/timed-out objects are absent from the result; freed objects
        map to {"deleted": True}. Each *found* object is pinned exactly once
        even across retries.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        found: Dict[str, Dict[str, Any]] = {}
        pending = list(oids)
        while pending:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            round_wait = 10.0 if remaining is None else min(10.0, remaining)
            locs = self.rpc.call(
                "store_get", oids=pending, client_id=self.client_id,
                wait_timeout=round_wait,
                timeout=round_wait * max(1, len(pending)) + 30.0,
            )
            still = []
            for oid, loc in zip(pending, locs):
                if loc is None:
                    still.append(oid)
                else:
                    found[oid] = loc
            pending = still
            if pending and deadline is not None and time.monotonic() >= deadline:
                break
        return found

    def get_values(self, oids: List[str], timeout: Optional[float] = None) -> List[Any]:
        """Fetch + deserialize; raises KeyError on timeout/missing/freed."""
        found = self.get_locations(oids, timeout=timeout)
        missing = [oid for oid in oids
                   if found.get(oid) is None or found[oid].get("deleted")]
        if missing:
            # release pins taken on the objects we did find before bailing
            for oid, loc in found.items():
                if not loc.get("deleted"):
                    try:
                        self.rpc.oneway("store_release", oid=oid,
                                        client_id=self.client_id)
                    except Exception:
                        pass
            loc = found.get(missing[0])
            freed = loc is not None and loc.get("deleted")
            raise KeyError(f"object {missing[0]} not available"
                           + (" (freed)" if freed else ""))
        return [self._load(oid, found[oid]) for oid in oids]

    def _load(self, oid: str, loc: Dict[str, Any]) -> Any:
        if loc["location"] == "shm":
            mv = self.arena.view[loc["offset"]:loc["offset"] + loc["size"]]
            release = self._make_release(oid)
            return deserialize_pinned(mv, release)
        # disk object: mmap the file for zero-copy reads
        with open(loc["path"], "rb") as f:
            mapped = mmap.mmap(f.fileno(), loc["size"], mmap.MAP_SHARED, mmap.PROT_READ)
        mv = memoryview(mapped)
        release = self._make_release(oid)
        return deserialize_pinned(mv, release)

    def _make_release(self, oid: str):
        rpc, client_id = self.rpc, self.client_id

        def release():
            try:
                rpc.oneway("store_release", oid=oid, client_id=client_id)
            except Exception:
                pass

        return release

    def contains(self, oid: str) -> bool:
        return bool(self.rpc.call("store_contains", oid=oid))

    def free(self, oids: List[str]) -> None:
        self.rpc.call("store_free", oids=oids)

    def close(self):
        self.arena.close(unlink=False)


class RpcPlasmaClient(PlasmaClient):
    """Store access for drivers with NO local arena (client mode): data
    rides the control-plane RPC in chunks instead of shared memory.

    Equivalent of the reference's Ray Client data path
    (reference: python/ray/util/client/server/server.py — a remote
    driver's puts/gets proxy through the cluster).  Slower than mmap by
    design; correct from any machine that can reach the node agent.
    """

    _CHUNK = 4 * 1024 * 1024

    def __init__(self, rpc, client_id: str):
        self.arena = None  # no mmap: all data moves over RPC
        self.rpc = rpc
        self.client_id = client_id

    def put_serialized(self, oid: str, frames, total_size: int,
                       primary: bool = True, wait_s: float = 0.0) -> None:
        from ray_tpu._private import serialization

        buf = bytearray(total_size)
        serialization.pack_into(frames, memoryview(buf))
        self.put_raw(oid, buf, primary=primary, wait_s=wait_s)

    def put_raw(self, oid: str, data, primary: bool = True,
                wait_s: float = 0.0) -> None:
        # memoryview slices: no per-chunk copies (msgpack serializes any
        # buffer-protocol object directly)
        view = memoryview(data)
        self.rpc.call("store_create", oid=oid, size=view.nbytes,
                      primary=primary,
                      **({"wait_s": wait_s, "timeout": wait_s + 60.0}
                         if wait_s > 0 else {}))
        try:
            for pos in range(0, view.nbytes, self._CHUNK):
                reply = self.rpc.call(
                    "store_write", oid=oid, offset=pos,
                    data=view[pos:pos + self._CHUNK])
                if not reply.get("ok"):
                    raise RuntimeError(reply.get("error", "write failed"))
        except BaseException:
            self._abort(oid)
            raise
        self.rpc.call("store_seal", oid=oid)

    def _load(self, oid: str, loc: Dict[str, Any]) -> Any:
        from ray_tpu._private import serialization

        size = loc["size"]
        data = bytearray(size)
        try:
            for pos in range(0, size, self._CHUNK):
                n = min(self._CHUNK, size - pos)
                r = self.rpc.call("obj_chunk", oid=oid, offset=pos, length=n)
                if not r.get("found"):
                    raise KeyError(f"object {oid} vanished mid-read")
                data[pos:pos + len(r["data"])] = r["data"]
        finally:
            # the bytes are ours now: drop the pin immediately
            self._make_release(oid)()
        return serialization.deserialize(memoryview(data))

    def close(self):
        pass
