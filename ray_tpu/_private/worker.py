"""CoreWorker: the in-process runtime of every driver and worker.

Equivalent of the reference's CoreWorker
(reference: src/ray/core_worker/core_worker.h:291 — SubmitTask :910,
SubmitActorTask :986, Put :584, Get :739; transport in
src/ray/core_worker/transport/direct_task_transport.h:79 and
direct_actor_task_submitter.h).

Threading model (reference: core_worker_process.h io_service):
  - the user's thread calls the public API (submit/get/put/wait)
  - all RPC (one server + pooled clients) runs on one EventLoopThread
  - worker mode executes tasks on the process main thread, fed by a
    thread-safe queue from the RPC loop

Task path (reference call stack SURVEY §3.2): submit → owner-side
dependency resolution (inline promotion) → worker lease from the node
agent (hybrid policy, spillback) → direct push_task RPC to the leased
worker → returns inlined in the reply (< max_direct_call_object_size)
or sealed into the worker-node's shared-memory store.

Ownership (reference: reference_count.h): the submitter owns task
returns and its own puts.  Borrows are registered race-free by
piggybacking on the task reply ("borrows": arg refs the worker kept;
"nested": refs embedded in returns, acked before the worker drops its
pins).
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import os
import queue
import random
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import cloudpickle
from concurrent.futures import CancelledError as _futures_cancelled

from ray_tpu._private import deadlines, serialization
from ray_tpu._private.config import config
from ray_tpu._private.errors import (TaskCancelledError,
                                     ActorDiedError, DeadlineExceededError,
                                     GetTimeoutError,
                                     ObjectFreedError, ObjectLostError,
                                     OutOfMemoryError, PoisonedTaskError,
                                     RayTaskError, RayWorkerError,
                                     RuntimeEnvSetupError, SchedulingError)
from ray_tpu._private.function_manager import FunctionManager
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.memory_store import MemoryStore
from ray_tpu._private.object_ref import ObjectRef, SerializationContext
from ray_tpu._private.object_store import PlasmaClient
from ray_tpu._private.profiling import IntrospectionRpcMixin, loop_lag_probe
from ray_tpu._private.reference_count import ReferenceCounter
from ray_tpu._private.streaming import (STREAMING, ObjectRefGenerator,
                                        StreamState)
from ray_tpu._private import tracing
from ray_tpu._private.rpc import (ConnectionLost, EventLoopThread, RpcClient,
                                  RpcError, RpcHost, RpcServer, SyncRpcClient,
                                  is_loopback)
from ray_tpu._private.task_spec import (ACTOR_CREATION_TASK, ACTOR_TASK,
                                        NORMAL_TASK, TaskSpec, WireArg)

MODE_DRIVER = "driver"
MODE_WORKER = "worker"

# owner-local poison-quarantine cache window: fail-fast verdicts learned
# from kill reports / refused leases are honored at most this long
# before the next submission re-validates through the lease layer — a
# `rtpu quarantine clear` becomes effective cluster-wide within one
# window + a heartbeat, while the fail-fast still never churns workers
# (the lease refusal is a cheap RPC, not a spawn)
_POISON_CACHE_S = 5.0

# MPMD pipeline-stage system methods (train/pipeline.py): named with the
# "__rt_dag_" prefix so they ride the compiled-DAG dispatch branch in
# _execute_inner (pinned exec loop, exempt from per-method state autosave,
# never shadowed by ActorHandle attribute lookup)
PIPELINE_EXEC_METHOD = "__rt_dag_pipeline_loop__"
PIPELINE_CTL_METHOD = "__rt_dag_pipeline_ctl__"
# LLM serving decode loop (serve/llm.py): same pinned-loop contract —
# the serve controller installs one per llm_deployment replica
LLM_EXEC_METHOD = "__rt_dag_llm_loop__"

_TASK_PUSH_TIMEOUT = 7 * 86400.0  # tasks may legitimately run for days
_WARM_LEASE_TTL_S = 0.2  # idle leases stay pooled this long before return
_LOCALITY_DEFER_S = 1.0  # max time the pump holds a task back waiting
# for a lease on the node that already holds its argument bytes
_PIPELINE_DEPTH_MAX = 24  # cap on tasks in flight per leased worker
_PIPELINE_BUDGET_S = 0.024  # per-lease pipeline covers this much work:
# depth = budget / measured per-task EXECUTION time, so sub-ms tasks
# pipeline at _PIPELINE_DEPTH_MAX while 24ms+ tasks dispatch one at a
# time (spread across workers) — a continuous curve, not a cliff
_SERVICE_WINDOW_S = 2.0  # service-time samples decay on this horizon
_MAX_RECONSTRUCTION_ROUNDS = 10  # get() retry rounds across object losses
_MAX_LEASES_PER_CLASS = 16
_MAX_ACTOR_INFLIGHT = 1000

_global_worker: Optional["CoreWorker"] = None
_global_lock = threading.Lock()

# root of the ray_tpu package: frames under it are framework internals,
# the first frame OUTSIDE it is the user call-site recorded per ref
# (trailing separator so a sibling dir sharing the prefix doesn't match)
_PKG_DIR = os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))) + os.sep


def _user_call_site() -> str:
    """file:line:function of the first non-framework frame on this
    thread's stack — the `rtpu memory` attribution for a put()/.remote()
    minted ref.  A bounded frame walk (~1µs), gated by
    memory_record_call_sites for hot paths that can't spare it."""
    if not config.memory_record_call_sites:
        return ""
    try:
        f = sys._getframe(2)
        for _ in range(32):
            if f is None:
                return ""
            fn = f.f_code.co_filename
            if not fn.startswith(_PKG_DIR):
                return (f"{os.path.basename(fn)}:{f.f_lineno}:"
                        f"{f.f_code.co_name}")
            f = f.f_back
    except Exception:
        pass
    return ""


def _live_channel_oids() -> List[str]:
    """Channel-slot oids claimed by live compiled graphs in THIS process
    (empty when the dag subsystem was never imported) — reported in the
    memory summary so the head's channel-leak tripwire knows which store
    slots are still legitimately owned."""
    mod = sys.modules.get("ray_tpu.dag.execution")
    if mod is None:
        return []
    try:
        return list(mod.live_channel_oids())
    except Exception:
        return []


def global_worker_or_none() -> Optional["CoreWorker"]:
    return _global_worker


def set_global_worker(w: Optional["CoreWorker"]) -> None:
    global _global_worker
    with _global_lock:
        _global_worker = w


class _ExecState(threading.local):
    task_id: str = ""
    job_id: str = ""
    num_returns: int = 0


class _ExecShadow:
    """Per-coroutine snapshot of _ExecState: async task bodies run on
    the shared loop thread where the exec thread's threading.local is
    invisible; a contextvar carries this shadow instead (isolated per
    asyncio.Task, so interleaved coroutines can't see each other's)."""

    __slots__ = ("task_id", "job_id", "num_returns")

    def __init__(self, src: "_ExecState"):
        self.task_id = src.task_id
        self.job_id = src.job_id
        self.num_returns = src.num_returns


_exec_ctx = contextvars.ContextVar("rt_exec_shadow", default=None)


class _TaskState:
    __slots__ = ("spec", "contained_refs", "retries_left", "sched_key",
                 "return_oids", "deps_ready", "cancelled", "defer_deadline",
                 "oom_retries_left", "oom_attempt", "oom_delay")

    def __init__(self, spec: TaskSpec, contained_refs: List[ObjectRef]):
        self.spec = spec
        self.contained_refs = contained_refs
        self.retries_left = spec.max_retries
        # watchdog OOM kills draw from their own budget — they must
        # never silently consume max_retries (the kill was the system's
        # choice, not the task's fault), and the jittered exponential
        # backoff below gives pressure time to clear between attempts
        self.oom_retries_left = int(config.task_oom_retries)
        self.oom_attempt = 0
        self.oom_delay = 0.0  # next requeue delay, consumed by the pusher
        self.sched_key = spec.scheduling_class()
        self.deps_ready = True
        self.cancelled = False  # ray_tpu.cancel hit it mid-resolution
        # locality dispatch: how long the pump may hold this task back
        # waiting for a lease on its argument-holding node (0 = not yet
        # deferred; set on first deferral, cleared never — bounded wait)
        self.defer_deadline = 0.0
        self.return_oids = [
            ObjectID.from_index(TaskID.from_hex(spec.task_id), i + 1).hex()
            for i in range(spec.num_returns)
        ]


class _LineageEntry:
    __slots__ = ("spec", "live", "attempts_left", "arg_pins")

    def __init__(self, spec: TaskSpec, arg_pins: List[ObjectRef]):
        self.spec = spec
        self.live: Set[str] = set()   # plasma return oids with live refs
        # reconstruction budget rides the task's retry budget (-1 = infinite,
        # matching _push's retries_left semantics)
        self.attempts_left = spec.max_retries
        self.arg_pins = arg_pins      # holding the refs pins the arg values


class _Lease:
    __slots__ = ("lease_id", "worker_id", "addr", "agent_addr", "inflight",
                 "dead", "failed_head", "tpu_chips", "in_bundle",
                 "pool_key", "resources", "warm_since")

    def __init__(self, lease_id: str, worker_id: str, addr: Tuple[str, int],
                 agent_addr: Tuple[str, int],
                 tpu_chips: Optional[List[int]] = None,
                 in_bundle: bool = False, pool_key: tuple = (),
                 resources: Optional[Dict[str, float]] = None):
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.addr = addr
        self.agent_addr = agent_addr
        # concrete chip indices the lease's node agent assigned; exported
        # to the executing worker as TPU_VISIBLE_CHIPS
        self.tpu_chips = tpu_chips or []
        # tasks pushed but not yet replied, in push order (the worker
        # executes FIFO, so inflight[0] is the one actually running);
        # pipelining > 1 deep hides the push RPC round-trip (reference:
        # direct_task_transport.h pipelines lease requests + pushes)
        self.inflight: deque = deque()
        self.dead = False
        # snapshotted at death: the one task that was actually executing
        self.failed_head: Optional[_TaskState] = None
        # granted out of a PG bundle's reserved capacity: returning it
        # frees bundle-internal capacity only, so node-pool reclaim
        # pushes must not evict it
        self.in_bundle = in_bundle
        # warm-pool identity: (resource shape, pg/bundle, env, strategy)
        # — everything in the scheduling class EXCEPT the function, so an
        # idle lease outlives its class and any same-shape class adopts
        # it without an agent round trip (see CoreWorker._park_lease)
        self.pool_key = pool_key
        self.resources = resources or {}
        self.warm_since = 0.0


class _ServiceStats:
    """Windowed, time-decayed estimate of a scheduling class's per-task
    *execution* time, used to pick the pipeline depth for its leases.

    Samples are the worker-reported execution wall time carried in every
    result frame ("exec_s"), NOT the owner-observed push round-trip: a
    sync burst's round trip includes the caller's blocking get and the
    whole owner-side turnaround, and an estimator trained on that can
    serialize dispatch for a class whose tasks are actually sub-ms
    (round-5 verdict: 2000 sync tasks collapsed subsequent async
    throughput ~3x).  Execution time is burst-shape-independent.

    Decay is time-based (two rotating windows of _SERVICE_WINDOW_S), so
    a historical burst stops influencing depth within ~2 windows even
    with no new samples — the estimator can never be "stuck" by history.
    """

    __slots__ = ("cur_sum", "cur_n", "prev_mean", "prev_n", "rotated_at")

    def __init__(self):
        self.cur_sum = 0.0
        self.cur_n = 0
        self.prev_mean = 0.0
        self.prev_n = 0
        self.rotated_at = time.monotonic()

    def _rotate(self, now: float) -> None:
        age = now - self.rotated_at
        if age < _SERVICE_WINDOW_S:
            return
        if age < 2 * _SERVICE_WINDOW_S and self.cur_n:
            self.prev_mean = self.cur_sum / self.cur_n
            self.prev_n = self.cur_n
        else:  # idle ≥ 2 windows: everything measured is stale
            self.prev_mean = 0.0
            self.prev_n = 0
        self.cur_sum = 0.0
        self.cur_n = 0
        self.rotated_at = now

    def observe(self, exec_s: float, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._rotate(now)
        self.cur_sum += max(0.0, exec_s)
        self.cur_n += 1

    def samples(self, now: Optional[float] = None) -> int:
        now = time.monotonic() if now is None else now
        self._rotate(now)
        return self.cur_n + self.prev_n

    def mean(self, now: Optional[float] = None) -> Optional[float]:
        now = time.monotonic() if now is None else now
        self._rotate(now)
        # the previous window contributes at most as much weight as a
        # window's worth of fresh samples, so a regime change (fast →
        # slow tasks under one function) wins within one window
        prev_n = min(self.prev_n, max(self.cur_n, 8))
        n = self.cur_n + prev_n
        if n == 0:
            return None
        return (self.cur_sum + self.prev_mean * prev_n) / n

    def depth(self, now: Optional[float] = None) -> int:
        """Continuous pipeline depth: enough tasks in flight per lease to
        cover _PIPELINE_BUDGET_S of work at the measured service time.
        Unmeasured classes spread depth-1 across workers (probe first)."""
        svc = self.mean(now)
        if svc is None:
            return 1
        if svc <= _PIPELINE_BUDGET_S / _PIPELINE_DEPTH_MAX:
            return _PIPELINE_DEPTH_MAX
        return max(1, min(_PIPELINE_DEPTH_MAX, int(_PIPELINE_BUDGET_S / svc)))


class _SchedState:
    __slots__ = ("key", "pending", "staged", "lock", "leases",
                 "inflight_requests", "stats", "request_agents",
                 "req_counter", "pump_queued", "defer_timer", "req_rr",
                 "has_deadlines")

    def __init__(self, key: tuple = ()):
        self.key = key
        self.pending: deque = deque()
        # cross-thread submission staging: the caller thread appends
        # here under this class's OWN lock (not a process-global one),
        # so submitters of different scheduling classes, the reply path,
        # and the event-flush path never contend on one lock.  The pump
        # drains staged -> pending in one pass on the IO loop.
        self.staged: deque = deque()
        self.lock = threading.Lock()
        self.leases: List[_Lease] = []
        self.inflight_requests = 0
        # True while a deferred-locality re-pump timer is scheduled
        self.defer_timer = False
        # rotates which pending task's spec rides the next lease request
        self.req_rr = 0
        # windowed execution-time stats driving the pipeline depth curve
        self.stats = _ServiceStats()
        # outstanding lease requests: req_id -> agent addr currently asked.
        # When pending drains, the owner cancels these so stale queued
        # requests don't hold the agent's FIFO — each would otherwise be
        # granted, linger idle, and stall queued demand behind it
        # (reference: CancelWorkerLease in node_manager.proto)
        self.request_agents: Dict[str, Tuple[str, int]] = {}
        self.req_counter = 0
        # True while a coalesced pump wakeup is queued on the loop:
        # rapid-fire submissions accumulate in staged and get assigned
        # in ONE pump (forming real push_tasks batches) instead of one
        # pump per submission; guarded by `lock`
        self.pump_queued = False
        # sticky: this class has seen a deadlined task, so the pump
        # pays the pre-dispatch expiry scan (undeadlined classes never
        # do — the scan would be O(pending) on the burst hot path)
        self.has_deadlines = False


class _ActorState:
    __slots__ = ("actor_id", "addr", "instance", "pending", "inflight",
                 "pumping", "recovering", "dead", "death_cause", "seq",
                 "resolving", "pump_queued")

    def __init__(self, actor_id: str):
        self.actor_id = actor_id
        self.addr: Optional[Tuple[str, int]] = None
        self.instance = -1
        self.pending: deque = deque()
        self.inflight: Dict[int, _TaskState] = {}
        self.pumping = False
        self.recovering = False
        self.dead = False
        self.death_cause = ""
        self.seq = 0
        self.resolving = None  # in-flight resolve future (coalesced)
        self.pump_queued = False  # coalesced-pump callback scheduled


class CoreWorker(IntrospectionRpcMixin, RpcHost):
    def __init__(self, mode: str, head_addr: Tuple[str, int],
                 agent_addr: Tuple[str, int], arena_path: str,
                 node_id: str, worker_id: str = "", job_id: str = "",
                 log_to_driver: Optional[bool] = None):
        self.mode = mode
        self.node_id = node_id
        self.worker_id = worker_id or WorkerID.from_random().hex()
        self.head_addr = head_addr
        self.agent_addr = tuple(agent_addr)
        self._io = EventLoopThread(name=f"rt-io-{mode}")
        # pooled workers always co-locate with their agent, so loopback
        # is the right bind for them — but a DRIVER under a REMOTE head
        # must be dialable back (the head's memory aggregator joins its
        # reference table, and borrowers dial owner_addr), so advertise
        # the interface this machine routes to the head through
        bind_host = "127.0.0.1"
        if mode == MODE_DRIVER and not is_loopback(head_addr[0]):
            import socket as _socket

            try:
                probe = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
                try:
                    probe.connect((head_addr[0], head_addr[1] or 1))
                    bind_host = probe.getsockname()[0]
                finally:
                    probe.close()
            except OSError:
                pass  # loopback + the head-side gap handling backstop
        self._server = RpcServer(self, bind_host, 0)
        port = self._io.run(self._server.start())
        self.address: Tuple[str, int] = (bind_host, port)
        self.head = SyncRpcClient(head_addr[0], head_addr[1], self._io,
                                  label="head",
                                  retry_lost_s=config.gcs_reconnect_grace_s)
        self.agent = SyncRpcClient(agent_addr[0], agent_addr[1], self._io, label="agent")
        if not job_id:
            # driver_addr lets the head's memory aggregator call back
            # into this driver's reference table (rtpu memory)
            job_id = self.head.call(
                "register_job", driver_addr=list(self.address))["job_id"]
        self.job_id = job_id
        if arena_path:
            self.plasma = PlasmaClient(arena_path, self.agent,
                                       client_id=self.worker_id)
        else:
            # client mode: no local arena mmap — data rides the RPC
            # (reference: ray client, util/client/)
            from ray_tpu._private.object_store import RpcPlasmaClient

            self.plasma = RpcPlasmaClient(self.agent, client_id=self.worker_id)
        self.memory = MemoryStore()
        self.rc = ReferenceCounter(self._free_object)
        self.functions = FunctionManager(self.head)
        self.job_runtime_env: Dict[str, Any] = {}  # init(runtime_env=...)
        self._locations: Dict[str, Tuple[str, int]] = {}  # owned oid -> node
        # owned oid -> plasma size: with _locations this is the owner's
        # reference table half of locality scheduling — submissions stamp
        # (loc, size) hints onto WireArgs so pick_node can score nodes by
        # argument bytes already local and agents can prefetch
        self._obj_sizes: Dict[str, int] = {}
        self._containers: Dict[str, List[ObjectRef]] = {}  # outer -> inner pins
        # lineage reconstruction (reference: object_recovery_manager.cc +
        # task_manager.h resubmit): while a plasma-stored return of an owned
        # normal task has live refs, keep its TaskSpec (and pin its arg
        # refs) so a lost primary copy can be recomputed
        self._lineage_lock = threading.Lock()
        self._lineage: Dict[str, _LineageEntry] = {}      # task_id -> entry
        self._lineage_by_oid: Dict[str, str] = {}         # oid -> task_id
        self._reconstructing: Set[str] = set()            # task_ids in flight
        self._sched: Dict[tuple, _SchedState] = {}
        # warm-lease pool (replaces per-lease linger timers): idle leases
        # parked here by pool_key, adopted by ANY scheduling class of the
        # same shape, swept back to their agents after _WARM_LEASE_TTL_S
        # by one pool-level timer, and returned early when an agent
        # reports queued demand (reclaim_idle_leases push)
        self._warm_leases: Dict[tuple, List[_Lease]] = {}
        self._warm_sweep_handle = None
        self._warm_adopted = 0   # observability/tests: pool hits
        self._warm_returned = 0  # leases returned by TTL sweep/reclaim
        self._pg_cache: Dict[str, Any] = {}
        self._actors: Dict[str, _ActorState] = {}
        self._agent_clients: Dict[Tuple[str, int], RpcClient] = {}
        self._worker_clients: Dict[Tuple[str, int], RpcClient] = {}
        self._exec_tls = _ExecState()
        self._exec.job_id = job_id
        self._exec.task_id = TaskID.for_driver(JobID.from_hex(job_id)).hex()
        self._put_counter = 0
        self._put_lock = threading.Lock()
        self._block_depth = 0  # nested blocking gets (see _notify_blocked)
        self._block_lock = threading.Lock()
        self._shutdown = False
        # observability: task-event buffer flushed to the head in batches
        # (reference: task_event_buffer.h:206) + process metrics pushed
        # to the node agent for re-export on its Prometheus endpoint
        self._task_events: List[Dict[str, Any]] = []
        self._task_events_lock = threading.Lock()
        self._flush_soon = False  # completion-flush scheduled (under lock)
        self._ev_dropped_counter = None  # lazy overflow counter
        self._metrics_collector = None  # set by _observability_loop
        self._io.spawn(self._observability_loop())
        # live introspection: loop-lag health probe on the IO loop, and
        # (drivers) worker-log streaming — every agent's log monitor
        # pushes its workers' stdout/stderr lines here, printed with
        # (pid=..., node=...) prefixes (reference: log_to_driver)
        self._io.spawn(loop_lag_probe(
            "driver" if mode == MODE_DRIVER else "worker"))
        if log_to_driver is None:
            log_to_driver = bool(config.log_to_driver)
        # agent addrs with an active log subscription: _aclient_agent
        # re-subscribes on any replacement connection (subscriptions are
        # per-connection server-side, so a silent drop would otherwise
        # end streaming for the driver's whole lifetime)
        self._log_subscribed: Set[Tuple[str, int]] = set()
        if mode == MODE_DRIVER and log_to_driver:
            self._io.spawn(self._subscribe_worker_logs())
        # streaming generator tasks we own: task_id -> StreamState
        # (reference: _raylet.pyx ObjectRefGenerator machinery)
        self._streams: Dict[str, StreamState] = {}
        # executor-side per-connection stream-item coalescing: many
        # concurrent generator tasks (the LLM serving tier runs 64+
        # token streams per replica) push items over ONE owner
        # connection — batching them into one "stream_items" frame per
        # flush tick replaces an RPC frame per token item (PR-8's
        # frame-batching philosophy applied to the streaming path)
        self._stream_out_lock = threading.Lock()
        self._stream_out_bufs: Dict[int, Tuple[Any, List[Dict]]] = {}
        # in-flight batched pushes awaiting per-task "batch_result"
        # pushes: task_id -> completion context (loop-confined; popped
        # synchronously in the push handler so the batch's failure path
        # can tell processed from unprocessed tasks)
        self._batch_pending: Dict[str, tuple] = {}
        # normal tasks whose ref args are still resolving (not yet in any
        # pending queue) — cancellable through here
        self._resolving_tasks: Dict[str, _TaskState] = {}
        # memory-pressure resilience (memory_monitor.py + head.py
        # quarantine): watchdog kill receipts pushed by agents keyed by
        # the killed worker_id (consulted when the worker connection's
        # death surfaces — a receipt turns a generic RayWorkerError into
        # a typed, separately-budgeted OutOfMemoryError); the local
        # poison-quarantine cache (fid -> (until, detail, history))
        # learned from kill-report replies / poisoned lease refusals;
        # and the fids this owner has reported kills for (their first
        # later success sends the ok-report that resets the head's
        # consecutive-kill count)
        self._oom_receipts: Dict[str, Dict[str, Any]] = {}
        self._quarantined: Dict[str, tuple] = {}
        self._kill_history: Set[str] = set()
        # end-to-end deadlines (_private/deadlines.py): the sweep timer
        # runs only while deadlined tasks exist (armed at submit, self-
        # re-arming while it finds any); _deadline_resolved marks tasks
        # the sweep already failed owner-side so the worker's eventual
        # reply (value, or the cancel-induced error) is discarded
        # instead of overwriting the DeadlineExceededError — and so the
        # next sweep tick doesn't re-fail/re-cancel them
        self._deadline_sweep_handle = None
        self._deadline_resolved: Set[str] = set()
        # cancellation (reference: core_worker CancelTask):
        # owner side — task_ids we force-cancelled (their worker death
        # must surface TaskCancelledError, never a retry)
        self._cancelled_tasks: Set[str] = set()
        # executor side — cancel-before-start marks, and live execution
        # handles so a cancel RPC can interrupt the running body
        self._cancelled_exec: Set[str] = set()
        # task_ids accepted by rpc_push_task and not yet finished — a
        # cancel for anything else is a no-op (keeps the mark set from
        # accumulating entries for already-finished tasks)
        self._exec_pending: Set[str] = set()
        self._sync_running: Dict[str, int] = {}   # task_id -> thread ident
        self._async_running: Dict[str, Any] = {}  # task_id -> conc. future
        # executor-side coalescing buffer for batched-push results:
        # id(conn) -> (conn, [result items]) flushed once per loop tick
        self._result_bufs: Dict[int, Tuple[Any, List[Dict[str, Any]]]] = {}
        # coalesced cross-thread posts to the IO loop (see _post_to_loop)
        self._post_lock = threading.Lock()
        self._post_buf: deque = deque()
        self._post_scheduled = False
        # worker-mode execution state
        self._task_queue: "queue.Queue" = queue.Queue()
        self._actor_instance: Any = None
        self._actor_creation_spec: Optional[TaskSpec] = None
        # stateful actor restarts (__rt_save__/__rt_restore__ hooks):
        # snapshot store handle + save cadence, guarded by a lock because
        # max_concurrency > 1 actors finish methods on several exec
        # threads (see _maybe_save_actor_state)
        self._actor_state_ckpt: Any = None
        self._actor_state_lock = threading.Lock()       # cadence counter
        self._actor_state_save_lock = threading.Lock()  # pickle + write
        self._actor_calls_since_save = 0
        self._pending_acks: Dict[str, Any] = {}  # task_id -> held values
        self._exec_threads: List[threading.Thread] = []

    @property
    def _exec(self):
        """Execution context: the per-coroutine shadow when running an
        async task body on the shared loop thread, else the exec
        thread's threading.local."""
        shadow = _exec_ctx.get()
        return shadow if shadow is not None else self._exec_tls

    # ------------------------------------------------------- observability

    def record_task_event(self, task_id: str, state: str,
                          _executor: Optional[bool] = None,
                          **fields) -> None:
        """Buffer a task state transition; flushed to the head in batches
        (reference: task_event_buffer.h FlushEvents).

        `_executor` overrides the by-state attribution guess — the
        owner records FAILED too (see _fail_task), and must not claim
        the record's worker/node with its own identity."""
        ev = {"task_id": task_id, "state": state,
              f"{state.lower()}_ts": time.time()}
        if _executor is None:
            _executor = state in ("RUNNING", "FINISHED", "FAILED")
        if _executor:
            # executor-side states claim the record's worker/node; the
            # submitter's identity rides dedicated caller_* keys so a
            # late-flushed owner event can't clobber the executor
            # attribution (timeline tracks key off worker_id/node_id)
            ev["worker_id"] = self.worker_id
            ev["node_id"] = self.node_id
        else:
            ev["caller_worker_id"] = self.worker_id
            ev["caller_node_id"] = self.node_id
        sub = os.environ.get("RT_JOB_ID")
        if sub:
            # correlate this driver's tasks with its job submission id
            ev["submission_id"] = sub
        ev.update(fields)
        dropped = 0
        with self._task_events_lock:
            self._task_events.append(ev)
            if len(self._task_events) > config.task_events_buffer_size:
                dropped = len(self._task_events) // 2
                del self._task_events[:dropped]
            schedule = (state in ("FINISHED", "FAILED")
                        and not self._flush_soon and not self._shutdown)
            if schedule:
                self._flush_soon = True
        if dropped:
            # overflow is deliberate (events must never backpressure the
            # submit hot path) but no longer silent:
            # ray_tpu_task_events_dropped_total counts the loss
            if self._ev_dropped_counter is None:
                from ray_tpu._private.metrics import \
                    task_events_dropped_counter

                self._ev_dropped_counter = task_events_dropped_counter()
            self._ev_dropped_counter.inc(dropped, tags={"shard": "owner"})
        if schedule:
            # completion events flush on a short coalescing delay instead
            # of waiting out the periodic interval: a snapshot taken right
            # after get() returns must already see the task FINISHED, and
            # the delay batches a burst's events into one frame
            try:
                self._loop().call_soon_threadsafe(self._schedule_event_flush)
            except RuntimeError:
                with self._task_events_lock:
                    self._flush_soon = False

    def _schedule_event_flush(self) -> None:
        self._loop().call_later(
            0.005, lambda: self._spawn(self._flush_task_events()))

    async def _flush_task_events(self):
        with self._task_events_lock:
            self._flush_soon = False
            batch, self._task_events = self._task_events, []
        if batch:
            try:
                await self.head.aio.oneway("task_events", events=batch)
            except Exception:
                pass
        # trace spans ride the same flush cadence (worker → head)
        spans = tracing.drain()
        if spans:
            for s in spans:
                s.setdefault("worker_id", self.worker_id)
                s.setdefault("node_id", self.node_id)
            try:
                await self.head.aio.oneway("trace_spans", spans=spans)
                tracing.count_flush()
            except Exception:
                tracing.count_dropped(len(spans))

    async def _observability_loop(self):
        import asyncio

        from ray_tpu._private.metrics import (default_registry,
                                              dispatch_pump_depth_gauge)

        default_registry.default_tags.setdefault(
            "worker_id", self.worker_id[:12])
        pump_depth = dispatch_pump_depth_gauge()

        def collect():
            # owner-side queued work not yet pushed to a lease: the
            # "is dispatch the bottleneck" gauge (sampled at render,
            # zero hot-path cost; dict snapshots tolerate cross-thread
            # mutation)
            depth = sum(len(s.pending) for s in list(self._sched.values()))
            depth += sum(len(a.pending) for a in list(self._actors.values()))
            pump_depth.set(depth)

        self._metrics_collector = collect  # removed again in shutdown()
        default_registry.add_collector(collect)
        interval = max(0.2, config.metrics_report_interval_ms / 1000.0 / 5)
        while not self._shutdown:
            await asyncio.sleep(interval)
            await self._flush_task_events()
            try:
                # push whenever this process has registered any metric —
                # user metrics in a driver count too
                if default_registry.has_samples():
                    text = default_registry.render()
                    await (await self._aclient_agent(self.agent_addr)).oneway(
                        "report_metrics", source=self.worker_id,
                        text=text.encode())
            except Exception:
                pass

    # ------------------------------------------------------------------ utils

    def _loop(self):
        return self._io.loop

    def _post_to_loop(self, fn, *args) -> None:
        """call_soon_threadsafe with wakeup coalescing.  Every
        call_soon_threadsafe writes a byte to the loop's self-pipe — a
        SYSCALL per call, ~1 ms on syscall-throttled boxes, paid on the
        submission hot path (one per .remote(), one per exec reply).
        Here the wakeup is written only on the buffer's empty→nonempty
        edge; a burst of N submissions pays ONE syscall and the drain
        callback runs them FIFO (submission order — the actor seqno
        contract — is preserved).  Raises RuntimeError like
        call_soon_threadsafe when the loop is shut down."""
        with self._post_lock:
            self._post_buf.append((fn, args))
            if self._post_scheduled:
                return
            self._post_scheduled = True
        try:
            self._loop().call_soon_threadsafe(self._drain_posts)
        except RuntimeError:
            with self._post_lock:
                self._post_scheduled = False
            raise

    def _drain_posts(self) -> None:
        while True:
            with self._post_lock:
                if not self._post_buf:
                    self._post_scheduled = False
                    return
                items = list(self._post_buf)
                self._post_buf.clear()
            for fn, args in items:
                try:
                    fn(*args)
                except Exception:
                    # one bad callback must not drop the rest, but keep
                    # the diagnostics call_soon_threadsafe used to give
                    import sys

                    print(f"[ray_tpu] exception in posted callback "
                          f"{getattr(fn, '__name__', fn)!r}:",
                          file=sys.stderr)
                    traceback.print_exc()

    def _spawn(self, coro):
        """Fire-and-forget a coroutine on the IO loop from any thread."""
        if self._shutdown:
            coro.close()
            return
        try:
            self._io.spawn(coro)
        except RuntimeError:
            coro.close()

    async def _aclient_worker(self, addr: Tuple[str, int]) -> RpcClient:
        addr = (addr[0], addr[1])
        c = self._worker_clients.get(addr)
        if c is None or c.dead:
            c = RpcClient(addr[0], addr[1], label=f"worker-{addr[1]}",
                          on_push=self._on_exec_worker_push)
            self._worker_clients[addr] = c
        return c

    def _on_exec_worker_push(self, method: str, payload: Dict[str, Any]):
        """Oneway pushes from a worker executing our task (IO loop).

        "stream_item": one yielded value of a streaming generator task
        (reference: core_worker.proto ReportGeneratorItemReturns).  The
        item lands exactly like a completed return value — inline bytes
        in the memory store or a recorded plasma location — so the
        consumer-facing ObjectRef resolves through the normal get path.
        """
        if method == "batch_results":
            # pop registrations AND remove from inflight synchronously:
            # the batch failure path snapshots failed_head from
            # inflight[0], which must never point at a task whose result
            # already arrived.  Then process the whole frame in ONE
            # coroutine — a Task per result would dominate small-task
            # throughput.
            work = []
            for item in payload.get("items") or []:
                entry = self._batch_pending.pop(item.get("tid", ""), None)
                if entry is None:
                    continue
                if entry[0] == "task":
                    lease, task = entry[2], entry[3]
                    try:
                        lease.inflight.remove(task)
                    except ValueError:
                        pass
                else:
                    entry[1].inflight.pop(entry[2].spec.seqno, None)
                work.append((entry, item.get("reply")))
            if work:
                asyncio.ensure_future(self._finish_batch_items(work))
            return
        if method == "stream_items":
            # coalesced frame: many items, possibly for many streams;
            # apply all, then wake each touched stream once
            touched = set()
            for one in payload.get("items") or []:
                s = self._apply_stream_item(one)
                if s is not None:
                    touched.add(s)
            for s in touched:
                s.wake()
            return
        if method != "stream_item":
            return
        s = self._apply_stream_item(payload)
        if s is not None:
            s.wake()

    def _apply_stream_item(self, payload) -> Optional[StreamState]:
        tid = payload["task_id"]
        s = self._streams.get(tid)
        if s is None:
            return None  # generator abandoned; drop late items
        idx = payload["index"]
        oid = ObjectID.from_index(TaskID.from_hex(tid), idx + 1).hex()
        item = payload["item"]
        if "v" in item:
            self.memory.set_raw(oid, item["v"])
        elif "stored" in item:
            node = tuple(item["stored"]["node"])
            self._locations[oid] = node
            if item["stored"].get("size"):
                self._obj_sizes[oid] = item["stored"]["size"]
            self.memory.set_in_plasma(oid, node)
        else:
            return None  # malformed item
        s.arrived = max(s.arrived, idx + 1)
        return s

    async def _aclient_agent(self, addr: Tuple[str, int]) -> RpcClient:
        addr = (addr[0], addr[1])
        c = self._agent_clients.get(addr)
        if c is None or c.dead:
            resubscribe = c is not None and addr in self._log_subscribed
            c = RpcClient(addr[0], addr[1], label=f"agent-{addr[1]}",
                          on_push=self._on_agent_push)
            self._agent_clients[addr] = c
            if resubscribe:
                # the old connection carried our log subscription (per-
                # connection server-side): renew it on the replacement
                # so streaming survives agent reconnects
                async def _resub(client=c):
                    try:
                        await client.call("subscribe_logs", tail=0)
                    except Exception:
                        pass

                self._spawn(_resub())
        return c

    async def _subscribe_worker_logs(self):
        """Driver mode: subscribe to every node agent's log monitor so
        worker stdout/stderr streams to this driver's console
        (reference: _private/log_monitor.py + worker.py print_logs).
        Agents joining later are not auto-subscribed — `rtpu logs
        --follow` covers operator use on growing clusters."""
        try:
            table = await self.head.aio.call("node_table")
        except Exception:
            table = {self.node_id: {"addr": list(self.agent_addr)}}
        for entry in table.values():
            addr = entry.get("addr")
            if not addr:
                continue
            try:
                client = await self._aclient_agent((addr[0], addr[1]))
                await client.call("subscribe_logs", tail=0)
                self._log_subscribed.add((addr[0], addr[1]))
            except Exception:
                pass  # an unreachable agent must not fail driver init

    def _print_log_lines(self, payload: Dict[str, Any]) -> None:
        """Render a log_lines push: one prefixed line per worker line,
        mirroring the reference's `(pid=..., ip=...)` driver output."""
        import sys

        node = (payload.get("node_id") or "")[:12]
        out = []
        for ent in payload.get("batch") or []:
            prefix = f"(pid={ent.get('pid')}, node={node}) "
            out.extend(prefix + line for line in ent.get("lines") or [])
        if out:
            print("\n".join(out), file=sys.stdout, flush=True)

    def _on_agent_push(self, method: str, payload: Dict[str, Any]):
        """Oneway pushes from a node agent (runs on the IO loop)."""
        if method == "log_lines":
            self._print_log_lines(payload)
            return
        if method == "oom_kill":
            # watchdog kill receipt, sent just BEFORE the SIGKILL: when
            # the worker connection's death surfaces in the push path,
            # the receipt reclassifies it as an OOM kill (typed error,
            # separate retry budget).  Bounded: receipts are consumed on
            # the death they explain; prune oldest if one never is
            # (owner_conn raced a reconnect and the death was seen by a
            # different owner object)
            wid = payload.get("worker_id", "")
            if wid:
                self._oom_receipts[wid] = payload
                while len(self._oom_receipts) > 256:
                    self._oom_receipts.pop(next(iter(self._oom_receipts)))
            return
        if method == "reclaim_idle_leases":
            # demand queued behind our leases on THAT agent: hand back
            # warm-pool leases NOW instead of after the TTL sweep.  The
            # push carries the agent's aggregate queued demand ("need"),
            # so we return only enough capacity to cover it and keep the
            # rest of the pool warm — a lease we just assigned work to
            # has inflight tasks and is skipped (no correctness race).
            agent = tuple(payload.get("agent") or ())
            need: Dict[str, float] = dict(payload.get("need") or {})

            def covered() -> bool:
                return bool(need) and all(v <= 0 for v in need.values())

            def consume(res: Dict[str, float]) -> None:
                for k, v in res.items():
                    if k in need:
                        need[k] -= v

            for pool in list(self._warm_leases.values()):
                for lease in list(pool):
                    if covered():
                        return
                    if lease.dead or lease.in_bundle:
                        continue
                    if agent and tuple(lease.agent_addr) != agent:
                        continue
                    pool.remove(lease)
                    consume(lease.resources)
                    self._warm_returned += 1
                    self._spawn(self._return_pooled(lease))
            # leases momentarily idle inside a class (between a reply and
            # its pump) are fair game too once the pool is exhausted.
            # list(): caller threads insert new classes concurrently
            # (_sched_state via staged submission)
            for state in list(self._sched.values()):
                for lease in list(state.leases):
                    if covered():
                        return
                    if lease.inflight or lease.dead or lease.in_bundle:
                        continue
                    if agent and tuple(lease.agent_addr) != agent:
                        continue
                    consume(lease.resources)
                    self._spawn(self._return_lease(state, lease))

    def shutdown(self):
        # deregister our pump-depth collector from the process-singleton
        # registry: a leaked closure would pin this whole worker graph
        # across init/shutdown cycles (and keep sampling dead state)
        if self._metrics_collector is not None:
            from ray_tpu._private.metrics import default_registry

            default_registry.remove_collector(self._metrics_collector)
            self._metrics_collector = None
        # flush buffered task events before tearing the IO plane down —
        # a short-lived driver's SUBMITTED events live in the last
        # interval of the observability loop
        with self._task_events_lock:
            batch, self._task_events = self._task_events, []
        if batch:
            try:
                self._io.run(
                    self.head.aio.oneway("task_events", events=batch),
                    timeout=2.0)
            except Exception:
                pass
        spans = tracing.drain()
        if spans:
            for s in spans:
                s.setdefault("worker_id", self.worker_id)
                s.setdefault("node_id", self.node_id)
            try:
                self._io.run(
                    self.head.aio.oneway("trace_spans", spans=spans),
                    timeout=2.0)
            except Exception:
                pass
        self._shutdown = True
        # wake every blocked waiter (gets, dep-resolution executor
        # threads): their objects can no longer arrive, and a thread
        # parked on an entry event would hang interpreter exit
        try:
            self.memory.fail_pending(RayWorkerError("ray_tpu.shutdown()"))
        except Exception:
            pass
        try:
            self.plasma.close()
        except Exception:
            pass
        for c in (self.head, self.agent):
            try:
                c.close()
            except Exception:
                pass

        async def _close_all():
            for c in list(self._agent_clients.values()) + list(self._worker_clients.values()):
                await c.close()
            await self._server.stop()

        try:
            self._io.run(_close_all(), timeout=5)
        except Exception:
            pass
        self._io.stop()

    # ---------------------------------------------------------- ref plumbing

    def register_local_ref(self, ref: ObjectRef) -> None:
        if self._shutdown:
            return
        owned = ref.owner_addr is None or tuple(ref.owner_addr) == self.address
        self.rc.add_local(ref.oid, owned)

    def unregister_local_ref(self, ref: ObjectRef) -> None:
        if self._shutdown:
            return
        borrowed_done = self.rc.remove_local(ref.oid)
        if borrowed_done and ref.owner_addr is not None:
            # drop the cached inline value too: borrowed entries are only
            # evicted here (owner-side eviction runs in _free_object), so
            # keeping them would leak every borrowed small object
            self.memory.evict(ref.oid)
            self._spawn(self._send_remove_borrow(tuple(ref.owner_addr), ref.oid))

    async def _send_remove_borrow(self, owner: Tuple[str, int], oid: str):
        try:
            c = await self._aclient_worker(owner)
            await c.oneway("remove_borrow", oid=oid, borrower=list(self.address))
        except Exception:
            pass

    def _free_object(self, oid: str) -> None:
        """Owned object's refcount hit zero: drop the value everywhere."""
        if self._shutdown:
            return
        self._drop_lineage(oid)
        self.memory.evict(oid)
        self._containers.pop(oid, None)  # releases nested pins via GC
        self._obj_sizes.pop(oid, None)
        loc = self._locations.pop(oid, None)
        if loc is not None:
            self._spawn(self._send_free(loc, oid))

    async def _send_free(self, node: Tuple[str, int], oid: str):
        try:
            c = await self._aclient_agent(node)
            await c.call("store_free", oids=[oid])
        except Exception:
            # recorded holder unreachable — the copy may have migrated
            # off a drained node; free wherever the head's directory
            # says it lives now, so a scale-down can't strand bytes
            try:
                r = await self.head.aio.call("object_locations",
                                             oids=[oid])
                for host, port in r.get("locations", {}).get(oid, []):
                    try:
                        c = await self._aclient_agent((host, port))
                        await c.call("store_free", oids=[oid])
                    except Exception:
                        pass
            except Exception:
                pass

    # ---- borrower/owner RPCs ----

    async def rpc_add_borrow(self, oid: str, borrower: List):
        self.rc.add_borrower(oid, (borrower[0], borrower[1]))
        return {"ok": True}

    async def rpc_remove_borrow(self, oid: str, borrower: List):
        self.rc.remove_borrower(oid, (borrower[0], borrower[1]))

    async def rpc_fetch_object(self, oid: str, wait: float = 0.0,
                               lost_at=None):
        """Owner-side object resolution for borrowers
        (reference: ownership-based object directory).

        `lost_at` is a borrower's report that the node we pointed it at
        could not serve the object; if it matches our recorded location,
        drop it and kick lineage reconstruction."""
        if lost_at is not None:
            loc = self._locations.get(oid)
            ent = self.memory.peek(oid)
            cur = loc or (ent.node_addr if ent is not None and ent.in_plasma
                          else None)
            if cur is not None and tuple(lost_at) == tuple(cur):
                # _maybe_reconstruct clears locations + resolutions for
                # every return of the producing task before resubmitting
                if not self._maybe_reconstruct(oid):
                    return {"unknown": True}
                return {"pending": True}
        entry = self.memory.peek(oid)
        if entry is None and wait > 0 and self.memory.known(oid):
            # event-driven long-poll: a memory-store waiter wakes this
            # coroutine on resolution — no executor thread parked per
            # in-flight poll (a borrower fleet would exhaust the pool)
            loop = self._loop()
            fut = loop.create_future()

            def _wake():
                loop.call_soon_threadsafe(
                    lambda: fut.done() or fut.set_result(None))

            token = self.memory.add_waiter(oid, _wake)
            if token is not None:
                try:
                    await asyncio.wait_for(fut, timeout=min(wait, 10.0))
                except asyncio.TimeoutError:
                    pass
                finally:
                    self.memory.remove_waiter(oid, token)
            entry = self.memory.peek(oid)
        if entry is not None:
            if entry.error is not None:
                return {"error": cloudpickle.dumps(entry.error)}
            if entry.in_plasma:
                return {"plasma": list(entry.node_addr)}
            if entry.raw is not None:
                return {"inline": entry.raw}
            return {"inline": serialization.serialize_to_bytes(entry.value)}
        loc = self._locations.get(oid)
        if loc is not None:
            return {"plasma": list(loc)}
        if self.rc.is_freed(oid):
            return {"freed": True}
        if self.memory.known(oid):
            return {"pending": True}
        return {"unknown": True}

    async def rpc_fetch_objects(self, oids: List[str], wait: float = 0.0):
        """Vectorized owner-side resolution: one frame resolves a whole
        batch of this owner's objects (concurrent long-polls share the
        wall-clock wait).  Per-oid results keyed by oid, each shaped
        exactly like a fetch_object reply."""
        results = await asyncio.gather(
            *[self.rpc_fetch_object(oid, wait=wait) for oid in oids])
        return {"results": dict(zip(oids, results))}

    def memory_summary(self, limit: int = 0) -> Dict[str, Any]:
        """This process's half of the cluster memory view: every live
        owned/borrowed ref with pin state, borrower count, size, store
        location, and creation call-site (reference: the per-worker
        `GetCoreWorkerStats` dump behind `ray memory`).  Bounded: owned
        refs sort largest-first and both lists cap at `limit`."""
        limit = int(limit) or int(config.memory_summary_max_refs)
        owned: List[Dict[str, Any]] = []
        borrowed: List[Dict[str, Any]] = []
        for r in self.rc.summary():
            oid = r["oid"]
            size = self._obj_sizes.get(oid, 0)
            if oid in self._locations:
                store = "plasma"
            else:
                e = self.memory.peek(oid)
                if e is not None:
                    if e.in_plasma:
                        store = "plasma"
                    elif e.error is not None:
                        store = "error"
                    else:
                        store = "inline"
                        if not size and e.raw is not None:
                            size = len(e.raw)
                elif self.memory.known(oid):
                    store = "pending"
                else:
                    store = "remote"
            r["size"] = size
            r["store"] = store
            (owned if r.pop("owned") else borrowed).append(r)
        owned.sort(key=lambda x: -x["size"])
        return {
            "worker_id": self.worker_id, "node_id": self.node_id,
            "kind": self.mode, "addr": list(self.address),
            "num_owned": len(owned), "num_borrowed": len(borrowed),
            "owned_bytes": sum(x["size"] for x in owned),
            "truncated": max(0, len(owned) - limit)
            + max(0, len(borrowed) - limit),
            "owned": owned[:limit], "borrowed": borrowed[:limit],
            "channels": _live_channel_oids(),
        }

    async def rpc_memory_summary(self, limit: int = 0):
        return self.memory_summary(limit)

    async def rpc_task_ack(self, task_id: str):
        self._pending_acks.pop(task_id, None)

    async def rpc_ping(self):
        return {"pong": True, "mode": self.mode}

    # ---- host-collective plane (ray_tpu.util.collective) ----

    async def rpc_coll_push(self, group: str, seq: int, src: int,
                            payload: bytes, chan: str = "op"):
        from ray_tpu.util import collective

        collective._deliver_push(group, chan, seq, src, payload)

    async def _acoll_send(self, addr, group: str, chan: str, seq: int,
                          src: int, payload: bytes):
        try:
            c = await self._aclient_worker(tuple(addr))
            await c.oneway("coll_push", group=group, chan=chan, seq=seq,
                           src=src, payload=payload)
        except Exception as e:
            import sys

            print(f"[ray_tpu.collective] send {group}/{chan}#{seq} "
                  f"rank {src} -> {addr} failed: {e}", file=sys.stderr)

    # ------------------------------------------------------------------- put

    def _next_put_oid(self) -> str:
        with self._put_lock:
            self._put_counter += 1
            # put indices live in the top half of the 32-bit index space;
            # return indices (including unbounded streaming-generator
            # items, which count up from 1) own the bottom half — a fixed
            # partition, because both counters are unbounded and any
            # additive offset scheme could collide
            idx = 0x8000_0000 + self._put_counter
        tid = TaskID.from_hex(self._exec.task_id or
                              TaskID.for_driver(JobID.from_hex(self.job_id)).hex())
        return ObjectID.from_index(tid, idx).hex()

    def put(self, value: Any) -> ObjectRef:
        oid = self._next_put_oid()
        with SerializationContext() as ctx:
            frames, size = serialization.serialize(value)
        if size <= config.max_direct_call_object_size:
            # small values stay in the owner's in-process store, skipping
            # two plasma RPC round-trips (reference: memory_store.cc —
            # ray.put below the direct-call threshold avoids plasma).
            # Borrowers resolve inline via fetch_object; task args inline
            # through _resolve_deps; the existing machinery covers both.
            buf = bytearray(size)
            serialization.pack_into(frames, memoryview(buf))
            self.memory.set_raw(oid, bytes(buf))
            node_addr = None
        else:
            # backpressure: a put the arena cannot take right now blocks
            # (bounded by the ambient deadline and put_backpressure_max_s)
            # for pinned bytes to release instead of silently flooding
            # the disk-fallback path; a truly unspillable arena still
            # falls through to the store's normal create semantics
            wait_s = float(config.put_backpressure_max_s)
            remaining = deadlines.remaining(deadlines.current_deadline())
            if remaining is not None:
                wait_s = min(wait_s, max(0.0, remaining))
            self.plasma.put_serialized(oid, frames, size, primary=True,
                                       wait_s=wait_s)
            self._locations[oid] = self.agent_addr
            self._obj_sizes[oid] = size
            node_addr = self.agent_addr
        if ctx.refs:
            # the stored value embeds refs: pin them for the outer's lifetime
            self._containers[oid] = list(ctx.refs)
        ref = ObjectRef(oid, owner_addr=self.address, node_addr=node_addr)
        self.rc.set_meta(oid, call_site=_user_call_site(), name="put")
        return ref

    # ------------------------------------------------------------------- get

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        # a worker blocking inside a task donates its lease's resources
        # so nested tasks can schedule (reference: HandleWorkerBlocked) —
        # without this, task nesting deeper than the node's CPU count
        # deadlocks.  Fast path (everything already resolved) skips the
        # agent round-trip entirely.
        # the ambient request deadline caps the budget: a get() inside a
        # deadlined task (or a Serve request) spends only what remains,
        # and its expiry surfaces as the typed DeadlineExceededError
        ambient = deadlines.remaining()
        deadline_bound = ambient is not None and (timeout is None
                                                  or ambient < timeout)
        if deadline_bound:
            timeout = ambient
        # the deadline starts NOW — the blocked-notification RPC below
        # must not eat into the caller's budget
        deadline = None if timeout is None else time.monotonic() + timeout
        # NOTE: plasma-stored objects (even locally present ones) also
        # trigger the notification — the worker has no local index of
        # plasma contents, and a blocking get's latency dwarfs the
        # round-trip anyway
        notify = (self.mode == MODE_WORKER and self._exec.task_id
                  and not all(self.memory.ready(r.oid) for r in refs))
        if notify:
            self._notify_blocked(True)
        try:
            return self._get_inner(refs, deadline)
        except GetTimeoutError as e:
            if deadline_bound:
                deadlines.count_exceeded("get")
                raise DeadlineExceededError(
                    f"request deadline expired while waiting: {e}",
                    where="get") from e
            raise
        finally:
            if notify:
                self._notify_blocked(False)

    def _notify_blocked(self, blocked: bool) -> None:
        # the RPC stays INSIDE the lock: edge detection and delivery must
        # serialize, or two exec threads crossing (one leaving get as
        # another enters) could deliver blocked/unblocked inverted and
        # wedge the lease's donation state
        with self._block_lock:
            self._block_depth += 1 if blocked else -1
            edge = (self._block_depth == 1) if blocked \
                else (self._block_depth == 0)
            if not edge:
                return
            try:
                self.agent.call(
                    "worker_blocked" if blocked else "worker_unblocked",
                    worker_id=self.worker_id, timeout=2.0)
            except Exception:
                pass  # agent briefly unreachable: accounting-only feature

    def _reconstruction_outcome(self, oids, ok: bool) -> None:
        """Count lineage-reconstruction outcomes
        (ray_tpu_object_reconstructions_total{outcome=ok|failed})."""
        if not oids:
            return
        from ray_tpu._private.metrics import fault_tolerance_metrics

        fault_tolerance_metrics()[1].inc(
            len(oids), tags={"outcome": "ok" if ok else "failed"})

    def _lost_detail(self, refs: Sequence[ObjectRef]) -> str:
        """Human-actionable loss report: each unrecoverable object id
        WITH the task that produced it, so operators can tell what was
        lost instead of just that something was."""
        with self._lineage_lock:
            parts = [
                f"{ref.oid[:16]} (produced by task "
                f"{(self._lineage_by_oid.get(ref.oid) or 'unknown')[:16]})"
                for ref in refs[:8]]
        more = f" … and {len(refs) - 8} more" if len(refs) > 8 else ""
        return ", ".join(parts) + more

    def _get_inner(self, refs: Sequence[ObjectRef],
                   deadline: Optional[float] = None) -> List[Any]:
        out: List[Any] = [None] * len(refs)
        pending: List[Tuple[int, ObjectRef]] = list(enumerate(refs))
        reconstructed: Set[str] = set()  # oids routed through lineage replay
        for _round in range(_MAX_RECONSTRUCTION_ROUNDS):
            plasma_fetch: List[Tuple[int, ObjectRef, Tuple[str, int]]] = []
            carry: List[Tuple[int, ObjectRef]] = []  # raced-clear retries
            # borrowed refs whose location the owner must resolve,
            # grouped so each owner gets ONE fetch_objects frame per
            # wait round instead of one serial RPC per ref (10k small
            # refs -> O(owners) round trips, not O(refs))
            by_owner: Dict[Tuple[str, int],
                           List[Tuple[int, ObjectRef]]] = {}
            for i, ref in pending:
                oid = ref.oid
                if self.memory.known(oid):
                    remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                    entry = self.memory.wait_ready(oid, remaining)
                    if entry is None:
                        raise GetTimeoutError(f"timed out waiting for {oid[:16]}")
                    if entry.error is not None:
                        raise entry.error
                    if entry.in_plasma:
                        plasma_fetch.append((i, ref, entry.node_addr))
                    else:
                        # snapshot: clear_resolution may race this read
                        value, raw = entry.value, entry.raw
                        if value is None and raw is None:
                            # raced clear (reconstruction started between
                            # wait_ready and this read): go around
                            carry.append((i, ref))
                            continue
                        if value is None:
                            with SerializationContext() as dctx:
                                value = serialization.deserialize(raw)
                                entry.value = value
                            # nested refs inside an inline value are live
                            # borrows — register them with their owners,
                            # exactly as the plasma fetch path does
                            self._register_foreign_refs(dctx.refs)
                        out[i] = value
                elif self.rc.is_freed(oid):
                    raise ObjectFreedError(f"object {oid[:16]} was freed by its owner")
                else:
                    node = ref.node_addr if _round == 0 else None
                    if node is None and ref.owner_addr is not None \
                            and tuple(ref.owner_addr) != self.address:
                        by_owner.setdefault(
                            tuple(ref.owner_addr), []).append((i, ref))
                        continue
                    if node is None:
                        node = self._locations.get(oid, self.agent_addr)
                    plasma_fetch.append((i, ref, node))
            for owner, items in by_owner.items():
                resolved_carry, resolved_plasma = \
                    self._resolve_owner_batch(owner, items, deadline)
                # inline values landed in the MEMORY STORE; revisit next
                # round to read them into out (the memory.known branch)
                carry.extend(resolved_carry)
                plasma_fetch.extend(resolved_plasma)
            if not plasma_fetch:
                if not carry:
                    self._reconstruction_outcome(reconstructed, ok=True)
                    return out
                pending = carry
                continue
            failures = self._fetch_plasma(plasma_fetch, out, deadline)
            if not failures and not carry:
                self._reconstruction_outcome(reconstructed, ok=True)
                return out
            # some plasma primaries are gone: reconstruct what we own,
            # report borrower-visible losses to their owners, retry
            pending = carry
            for i, ref, node, err in failures:
                if self._maybe_reconstruct(ref.oid):
                    reconstructed.add(ref.oid)
                    pending.append((i, ref))
                elif ref.owner_addr is not None \
                        and tuple(ref.owner_addr) != self.address \
                        and self._report_lost_to_owner(ref, node, deadline):
                    pending.append((i, ref))
                else:
                    self._reconstruction_outcome({ref.oid}, ok=False)
                    raise ObjectLostError(
                        f"object {self._lost_detail([ref])} was lost "
                        f"({err}) and cannot be reconstructed")
        lost_refs = [ref for _i, ref in pending]
        self._reconstruction_outcome({r.oid for r in lost_refs}, ok=False)
        raise ObjectLostError(
            f"gave up reconstructing after {_MAX_RECONSTRUCTION_ROUNDS} "
            f"rounds; unrecoverable objects: {self._lost_detail(lost_refs)}")

    def _resolve_owner_batch(self, owner: Tuple[str, int],
                             items: List[Tuple[int, ObjectRef]], deadline
                             ) -> Tuple[List[Tuple[int, ObjectRef]],
                                        List[Tuple[int, ObjectRef,
                                                   Tuple[str, int]]]]:
        """Resolve a group of refs against their common owner: one
        fetch_objects frame per long-poll round carries EVERY still-
        pending oid (round-5 verdict: resolving many small borrowed refs
        did one RPC round per ref).  Returns (carry, plasma): carry refs
        resolved inline into the memory store (read next round), plasma
        refs with the node address to pull from."""
        pending = items
        carry: List[Tuple[int, ObjectRef]] = []
        plasma: List[Tuple[int, ObjectRef, Tuple[str, int]]] = []
        while pending:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError(
                    f"timed out resolving {pending[0][1].oid[:16]} "
                    f"(+{len(pending) - 1} more)")
            wait = 10.0 if remaining is None else min(10.0, remaining)
            try:
                results = self._io.run(
                    self._afetch_many_from_owner(
                        owner, [ref.oid for _i, ref in pending], wait),
                    timeout=wait + 30.0)
            except ConnectionLost:
                raise ObjectLostError(
                    f"owner of {pending[0][1].oid[:16]} at {owner} "
                    f"is unreachable")
            nxt: List[Tuple[int, ObjectRef]] = []
            for i, ref in pending:
                r = results.get(ref.oid) or {"unknown": True}
                if r.get("pending"):
                    nxt.append((i, ref))
                elif r.get("freed"):
                    raise ObjectFreedError(
                        f"object {ref.oid[:16]} was freed by its owner")
                elif r.get("unknown"):
                    raise ObjectLostError(
                        f"owner does not know object {ref.oid[:16]}")
                elif "error" in r:
                    raise cloudpickle.loads(r["error"])
                elif "inline" in r:
                    self.memory.set_raw(ref.oid, r["inline"])
                    carry.append((i, ref))
                else:
                    plasma.append((i, ref, (r["plasma"][0], r["plasma"][1])))
            pending = nxt
        return carry, plasma

    async def _afetch_many_from_owner(self, owner, oids: List[str],
                                      wait: float) -> Dict[str, Any]:
        c = await self._aclient_worker(owner)
        r = await c.call("fetch_objects", oids=oids, wait=wait,
                         timeout=wait + 20.0)
        return r.get("results") or {}

    async def _afetch_from_owner(self, owner, oid: str, wait: float,
                                 lost_at=None):
        c = await self._aclient_worker(owner)
        return await c.call("fetch_object", oid=oid, wait=wait,
                            lost_at=list(lost_at) if lost_at else None,
                            timeout=wait + 20.0)

    def _report_lost_to_owner(self, ref: ObjectRef, node, deadline) -> bool:
        """Tell the owner its recorded location failed to serve the object.
        Returns True if the owner is handling it (reconstruction underway
        or a different location exists) — the caller then re-resolves."""
        owner = tuple(ref.owner_addr)
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
            raise GetTimeoutError(
                f"timed out while recovering {ref.oid[:16]}")
        budget = 30.0 if remaining is None else min(30.0, remaining)
        try:
            r = self._io.run(
                self._afetch_from_owner(owner, ref.oid, 0.0, lost_at=node),
                timeout=budget)
        except Exception:
            return False
        return not (r.get("unknown") or r.get("freed") or "error" in r)

    def _fetch_plasma(self, items, out: List[Any], deadline) -> list:
        """Localize + read plasma objects; fills `out` for successes and
        returns [(i, ref, node, err)] for objects that could not be
        localized (lost primaries — reconstruction candidates)."""
        # 1. make everything local: ONE ensure_local_batch frame to our
        # agent carries every (oid, source) pair — the agent pulls them
        # concurrently (deduped against in-flight pulls) and replies
        # per-oid, so localizing N objects costs one RPC round, not N
        async def _ensure_all():
            r = await self.agent.aio.call(
                "ensure_local_batch",
                items=[[ref.oid, list(node) if node else None]
                       for _i, ref, node in items],
                timeout=config.rpc_call_timeout_s)
            return r.get("results") or []

        try:
            replies = self._io.run(_ensure_all(),
                                   timeout=config.rpc_call_timeout_s + 30)
        except Exception as e:
            # transient transport trouble with our own agent is NOT
            # evidence the primaries are lost — don't trigger duplicate
            # re-executions for it
            raise ObjectLostError(
                f"could not localize {items[0][1].oid[:16]} "
                f"(+{len(items) - 1} more): {e}") from e
        failures: List[Tuple[int, ObjectRef, Tuple[str, int], str]] = []
        localized = []
        for (i, ref, node), r in zip(
                items, list(replies) + [{"ok": False, "error": "no reply"}]
                * max(0, len(items) - len(replies))):
            if not r.get("ok"):
                failures.append((i, ref, node, str(r.get("error"))))
            else:
                localized.append((i, ref))
        if not localized:
            return failures
        # 2. read them zero-copy from the local store
        remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
        oids = [ref.oid for _, ref in localized]
        with SerializationContext() as ctx:
            try:
                values = self.plasma.get_values(oids, timeout=remaining)
            except KeyError as e:
                if "freed" in str(e):
                    raise ObjectFreedError(str(e)) from e
                raise ObjectLostError(str(e)) from e
        self._register_foreign_refs(ctx.refs)
        for (i, _), v in zip(localized, values):
            out[i] = v
        return failures

    def _register_foreign_refs(self, refs: List[ObjectRef]) -> None:
        """Register borrows for refs materialized out of fetched values."""
        seen: Set[str] = set()
        for r in refs:
            if r.owner_addr is not None and tuple(r.owner_addr) != self.address \
                    and r.oid not in seen:
                seen.add(r.oid)
                self._spawn(self._send_add_borrow(tuple(r.owner_addr), r.oid))

    async def _send_add_borrow(self, owner: Tuple[str, int], oid: str):
        try:
            c = await self._aclient_worker(owner)
            await c.call("add_borrow", oid=oid, borrower=list(self.address))
        except Exception:
            pass

    # ------------------------------------------------------------- get_async

    async def get_async(self, refs: Sequence[ObjectRef],
                        timeout: Optional[float] = None) -> List[Any]:
        """Awaitable get: completion futures on the CALLING event loop,
        fed by memory-store waiters — a caller can await thousands of
        in-flight refs without parking a thread per ref (the async Serve
        ingress rides this).  Loop-agnostic: usable from any event loop,
        not just the worker's IO loop.

        Hot path (owned refs resolving to inline values — every serve
        reply under max_direct_call_object_size) completes entirely on
        the loop.  Plasma-stored or borrowed values fall back to one
        executor-thread blocking get for just those refs — the slow path
        is already dominated by the transfer, and reconstruction/
        recovery semantics stay identical to get()."""
        loop = asyncio.get_running_loop()
        deadline = None if timeout is None else time.monotonic() + timeout
        waits: List[Any] = []
        cleanups: List[Tuple[str, int]] = []

        def _waker(fut):
            return lambda: loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(None))

        for ref in refs:
            oid = ref.oid
            if not self.memory.known(oid):
                # no memory entry to await (a local plasma put, or a
                # borrowed ref whose owner lives elsewhere): resolved by
                # the blocking fallback below, which long-polls/fetches
                # with the same deadline
                continue
            if self.memory.ready(oid):
                continue
            fut = loop.create_future()
            token = self.memory.add_waiter(oid, _waker(fut))
            if token is not None:
                waits.append(fut)
                cleanups.append((oid, token))
        try:
            if waits:
                remaining = (None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
                try:
                    await asyncio.wait_for(
                        asyncio.gather(*waits), timeout=remaining)
                except asyncio.TimeoutError:
                    raise GetTimeoutError(
                        f"timed out awaiting {len(waits)} of "
                        f"{len(refs)} objects") from None
        finally:
            for oid, token in cleanups:
                self.memory.remove_waiter(oid, token)
        out: List[Any] = [None] * len(refs)
        slow: List[Tuple[int, ObjectRef]] = []
        for i, ref in enumerate(refs):
            entry = self.memory.peek(ref.oid)
            if entry is None or entry.in_plasma:
                slow.append((i, ref))
                continue
            if entry.error is not None:
                raise entry.error
            value, raw = entry.value, entry.raw
            if value is None and raw is None:
                # raced clear (reconstruction): take the blocking path
                slow.append((i, ref))
                continue
            if value is None:
                with SerializationContext() as dctx:
                    value = serialization.deserialize(raw)
                    entry.value = value
                self._register_foreign_refs(dctx.refs)
            out[i] = value
        if slow:
            # the ABSOLUTE deadline rides into the executor job: deriving
            # it at job start would let executor queue wait silently
            # extend the caller's timeout
            slow_refs = [r for _, r in slow]
            values = await loop.run_in_executor(
                None, lambda: self._get_inner(slow_refs, deadline))
            for (i, _), v in zip(slow, values):
                out[i] = v
        return out

    # ------------------------------------------------------------------ wait

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        """Event-driven wait (no polling; reference: src/ray/raylet/
        wait_manager.h).  Locally-owned refs register memory-store waiter
        callbacks fired by the IO thread on resolution; borrowed refs run
        ONE long-poll probe each against their owner (the owner blocks
        server-side until the object resolves), instead of a 5 ms
        check-everything loop with a sync RPC per ref per iteration."""
        deadline = None if timeout is None else time.monotonic() + timeout
        cond = threading.Condition()
        ready_idx: Set[int] = set()
        removals: List[Tuple[str, int]] = []  # (oid, token) to clean up
        probes: List[Any] = []  # concurrent futures wrapping probe tasks

        def mark(idx: int) -> None:
            with cond:
                ready_idx.add(idx)
                cond.notify_all()

        for idx, ref in enumerate(refs):
            oid = ref.oid
            if self.memory.ready(oid):
                ready_idx.add(idx)
            elif self.memory.known(oid):
                token = self.memory.add_waiter(oid, lambda i=idx: mark(i))
                if token is None:  # resolved between the two checks
                    ready_idx.add(idx)
                else:
                    removals.append((oid, token))
            else:
                coro = self._aprobe_ready(ref, idx, mark, deadline)
                if self._shutdown:
                    coro.close()
                    continue
                try:
                    probes.append(self._io.spawn(coro))
                except RuntimeError:
                    coro.close()

        try:
            with cond:
                while len(ready_idx) < min(num_returns, len(refs)):
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        break
                    cond.wait(remaining)
        finally:
            # cancel probes NOW — a probe parked in a 10 s owner-side
            # long-poll must not outlive the wait that spawned it (the
            # poll-loop pattern `while pending: ray.wait(pending, 0.5)`
            # would otherwise pile up ~N*(10s/timeout) live probes)
            for f in probes:
                f.cancel()
            for oid, token in removals:
                self.memory.remove_waiter(oid, token)
        with cond:
            snapshot = set(ready_idx)
        ready = [r for i, r in enumerate(refs) if i in snapshot]
        pending = [r for i, r in enumerate(refs) if i not in snapshot]
        return ready, pending

    async def _aprobe_ready(self, ref: ObjectRef, idx: int, mark,
                            deadline) -> None:
        """Readiness probe for refs this process doesn't own: the local
        plasma store first, then a server-side long-poll on the owner
        (covers values inlined in the owner's memory store, which never
        touch plasma).  Ended by cancellation from wait()'s finally."""
        import asyncio

        while True:
            try:
                if self.plasma.contains(ref.oid):
                    mark(idx)
                    return
            except Exception:
                pass
            owner = ref.owner_addr
            if owner is None or tuple(owner) == self.address:
                return  # nothing that could ever resolve it
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                return
            poll = 10.0 if remaining is None else min(10.0, remaining)
            t0 = time.monotonic()
            try:
                r = await self._afetch_from_owner(tuple(owner), ref.oid, poll)
            except Exception:
                await asyncio.sleep(0.2)
                continue
            if any(k in r for k in ("inline", "plasma", "error", "freed")):
                mark(idx)
                return
            if time.monotonic() - t0 < 0.5:
                # the owner answered without long-polling (e.g. "unknown"
                # for an evicted entry): pace the loop or it spins RPCs
                # at round-trip rate until the wait deadline
                await asyncio.sleep(0.5)

    # ---------------------------------------------------------- task submit

    def _serialize_args(self, args: tuple, kwargs: dict) -> Tuple[List[WireArg], List[ObjectRef]]:
        wire: List[WireArg] = []
        contained: List[ObjectRef] = []
        items = [(None, a) for a in args] + list(kwargs.items())
        for kw, a in items:
            if isinstance(a, ObjectRef):
                contained.append(a)
                wire.append(WireArg(object_id=a.oid,
                                    owner_addr=a.owner_addr or self.address,
                                    kw=kw, **self._arg_hints(a)))
                continue
            with SerializationContext() as ctx:
                blob = serialization.serialize_to_bytes(a)
            contained.extend(ctx.refs)
            if len(blob) > config.max_direct_call_object_size:
                # big literal arg: put once, pass by ref
                ref = self.put(a)
                contained.append(ref)
                wire.append(WireArg(object_id=ref.oid, owner_addr=self.address,
                                    kw=kw, **self._arg_hints(ref)))
            else:
                wire.append(WireArg(value=blob, kw=kw))
        return wire, contained

    def _arg_hints(self, ref: ObjectRef) -> Dict[str, Any]:
        """Locality hints for a ref argument: (holder node addr, size)
        from the owner's reference table, falling back to the ref's own
        recorded plasma location for borrowed refs.  pick_node scores
        nodes by these bytes; the granting agent prefetches them."""
        loc = self._locations.get(ref.oid) \
            or (tuple(ref.node_addr) if ref.node_addr else None)
        if loc is None:
            return {}
        return {"loc": loc, "size": self._obj_sizes.get(ref.oid, 0)}

    def submit_task(self, function_id: str, args: tuple, kwargs: dict,
                    num_returns: int = 1, resources: Optional[Dict[str, float]] = None,
                    max_retries: int = 3, name: str = "",
                    runtime_env: Optional[Dict[str, Any]] = None,
                    scheduling_strategy: Optional[Dict[str, Any]] = None,
                    placement_group_id: str = "",
                    bundle_index: int = -1,
                    timeout_s: Optional[float] = None) -> List[ObjectRef]:
        from ray_tpu._private.runtime_env import merge as _renv_merge

        if num_returns == "streaming":
            num_returns = STREAMING
        tid = TaskID.for_normal_task(JobID.from_hex(self.job_id))
        wire_args, contained = self._serialize_args(args, kwargs)
        spec = TaskSpec(
            task_id=tid.hex(), job_id=self.job_id, kind=NORMAL_TASK,
            function_id=function_id, args=wire_args, num_returns=num_returns,
            resources=resources or {"CPU": 1}, max_retries=max_retries,
            name=name, owner_addr=self.address, caller_id=self.worker_id,
            runtime_env=_renv_merge(self.job_runtime_env, runtime_env or {}),
            scheduling_strategy=scheduling_strategy or {},
            placement_group_id=placement_group_id,
            bundle_index=max(bundle_index, 0) if placement_group_id else -1,
            deadline=deadlines.effective_deadline(timeout_s) or 0.0)
        task = _TaskState(spec, contained)
        # submit span: child of whatever span this thread/coroutine is
        # running under (an executing task's span for nested submits, a
        # Serve ingress span, …) or a fresh sampled root.  The worker's
        # execute span parents to it via spec.trace_ctx; an unsampled
        # decision propagates too so the subtree doesn't re-roll.
        span, spec.trace_ctx = tracing.begin_submit(
            "submit " + (name or function_id[:8]))
        if span is not None:
            span.set_attribute("task_id", spec.task_id)
        refs: List[Any] = []
        if num_returns == STREAMING:
            # yields arrive incrementally; no automatic retries (a
            # consumed prefix cannot be replayed) — see streaming.py
            task.retries_left = 0
            self._streams[spec.task_id] = StreamState()
            refs.append(ObjectRefGenerator(self, spec.task_id))
        call_site = _user_call_site()
        for oid in task.return_oids:
            self.memory.ensure(oid)
            refs.append(ObjectRef(oid, owner_addr=self.address))
            self.rc.set_meta(oid, call_site=call_site,
                             name=name or function_id[:8])
        self.record_task_event(
            spec.task_id, "SUBMITTED",
            name=name or function_id[:8], kind=NORMAL_TASK,
            job_id=self.job_id)
        if any(a.object_id is not None for a in spec.args):
            self._spawn(self._submit(task))
        else:
            # no ref args: nothing to resolve — stage straight into the
            # class's partitioned queue.  The caller thread takes only
            # this class's lock and pays ONE loop wakeup per burst (the
            # pump_queued edge); the coalesced pump forms real
            # push_tasks batches out of whatever accumulated.
            self._stage_ready(task)
        if spec.deadline:
            # AFTER the enqueue: arming first would let a concurrent
            # sweep tick scan-and-disarm in the gap and never see this
            # task (the arm's racy handle read would then skip re-arming)
            self._arm_deadline_sweep()
        if span is not None:
            span.end()
        return refs

    def _sched_state(self, key: tuple) -> _SchedState:
        # called from caller threads too (staged submission): setdefault
        # keeps concurrent first-submissions of one class to one state
        state = self._sched.get(key)
        if state is None:
            state = self._sched.setdefault(key, _SchedState(key))
        return state

    def _stage_ready(self, task: _TaskState) -> None:
        state = self._sched_state(task.sched_key)
        with state.lock:
            if task.spec.deadline:
                state.has_deadlines = True
            state.staged.append(task)
            if state.pump_queued:
                return
            state.pump_queued = True
        try:
            self._loop().call_soon_threadsafe(self._coalesced_pump, state)
        except RuntimeError:
            with state.lock:
                state.pump_queued = False  # loop shut down

    def _coalesced_pump(self, state: _SchedState) -> None:
        with state.lock:
            state.pump_queued = False
        self._pump(state)

    def _fail_poisoned(self, state: _SchedState, spec: TaskSpec,
                       reply: Dict[str, Any]) -> None:
        """An agent refused this class's lease because the head
        quarantined it as poison: cache the verdict locally (later
        submissions fail before any RPC) and fail every pending task in
        the class fast with the typed error + kill history."""
        detail = reply.get("error_str", "task class is quarantined")
        history = list(reply.get("history", []))
        until = time.time() + _POISON_CACHE_S
        if spec.function_id:
            self._quarantined[spec.function_id] = (until, detail, history)
        err = PoisonedTaskError(detail, key=spec.function_id,
                                history=history)
        while state.pending:
            self._fail_task(state.pending.popleft(), err)

    async def _submit(self, task: _TaskState):
        q = self._fid_quarantined(task.spec.function_id)
        if q is not None:
            # fail fast at submission: the class is quarantined as
            # poison (this owner learned it from a kill report or a
            # refused lease); dispatching would only be refused again
            self._fail_task(task, PoisonedTaskError(
                q[1], key=task.spec.function_id, history=q[2]))
            return
        # owner-side dependency resolution (reference: dependency_resolver.h)
        # — registered so ray_tpu.cancel can reach a task whose args are
        # still resolving (it is in no pending queue yet)
        self._resolving_tasks[task.spec.task_id] = task
        try:
            ok = await self._resolve_deps(task)
        finally:
            self._resolving_tasks.pop(task.spec.task_id, None)
        if not ok or task.cancelled:
            return
        state = self._sched_state(task.sched_key)
        if task.spec.deadline:
            state.has_deadlines = True
        state.pending.append(task)
        self._pump(state)

    async def _resolve_deps(self, task: _TaskState) -> bool:
        for arg in task.spec.args:
            if arg.object_id is None:
                continue
            oid = arg.object_id
            if not self.memory.known(oid):
                continue  # plasma object or foreign ref: worker will fetch
            e = self.memory._entry(oid)
            if not e.event.is_set():
                await self._loop().run_in_executor(None, e.event.wait)
            if e.error is not None:
                self._fail_task(task, e.error)
                return False
            if e.in_plasma:
                arg.owner_addr = self.address
            elif e.raw is not None:
                arg.value = e.raw
                arg.object_id = None
            else:
                arg.value = serialization.serialize_to_bytes(e.value)
                arg.object_id = None
        for arg in task.spec.args:
            # refs that were still pending when _serialize_args stamped
            # hints have resolved locations now: fill them in so the
            # lease request can score locality / prefetch
            if arg.object_id is not None and arg.loc is None:
                loc = self._locations.get(arg.object_id)
                if loc is not None:
                    arg.loc = loc
                    arg.size = self._obj_sizes.get(arg.object_id, 0)
        return True

    # ---------------------------------------------------------- cancellation

    def cancel(self, target, force: bool = False) -> None:
        """Cancel a task by any of its return refs or its generator
        (reference: python/ray/_private/worker.py:2942 ray.cancel).
        No-op if the task already finished."""
        if isinstance(target, ObjectRefGenerator):
            task_id = target.task_id
        else:
            task_id = ObjectID(bytes.fromhex(target.oid)).task_id().hex()
        self._io.run(self._cancel_async(task_id, force), timeout=30.0)

    async def _cancel_async(self, task_id: str, force: bool):
        err = TaskCancelledError(f"task {task_id[:12]} was cancelled")
        # 0. args still resolving (not yet in any queue): fail it now and
        # tell _submit to drop it when resolution finishes
        task = self._resolving_tasks.get(task_id)
        if task is not None:
            task.cancelled = True
            self._fail_task(task, err)
            return
        # 1. still pending owner-side (never pushed): fail it locally.
        # list(): caller threads insert new classes concurrently
        for state in list(self._sched.values()):
            # staged = submitted but not yet drained by a pump pass
            with state.lock:
                staged_hit = next((t for t in state.staged
                                   if t.spec.task_id == task_id), None)
                if staged_hit is not None:
                    state.staged.remove(staged_hit)
            if staged_hit is not None:
                self._fail_task(staged_hit, err)
                return
            for task in list(state.pending):
                if task.spec.task_id == task_id:
                    state.pending.remove(task)
                    self._fail_task(task, err)
                    return
            # 2. pushed to a leased worker: interrupt it there
            for lease in state.leases:
                for task in list(lease.inflight):
                    if task.spec.task_id == task_id:
                        await self._cancel_on_worker(
                            task, lease.addr, force)
                        return
        for astate in list(self._actors.values()):
            for task in list(astate.pending):
                if task.spec.task_id == task_id:
                    astate.pending.remove(task)
                    self._fail_task(task, err)
                    return
            for task in list(astate.inflight.values()):
                if task.spec.task_id != task_id:
                    continue
                if astate.addr:
                    await self._cancel_on_worker(task, astate.addr, force)
                else:
                    # actor mid-recovery: no live worker to interrupt.
                    # Mark the task so the recovery requeue resolves it
                    # with TaskCancelledError instead of silently
                    # re-running it on the restarted actor.
                    task.retries_left = 0
                    task.cancelled = True
                    self._cancelled_tasks.add(task_id)
                return
        # already finished (or unknown): no-op, like the reference

    def _take_cancelled(self, task: _TaskState) -> bool:
        """If this task was force-cancelled, consume the mark and resolve
        it as cancelled.  Used by the connection-failure handlers: the
        worker's death IS the cancellation outcome, never a retryable
        fault."""
        if task.spec.task_id in self._deadline_resolved:
            # the deadline sweep already resolved this task with
            # DeadlineExceededError — consume every mark and report
            # "handled" so no path overwrites or retries it
            self._deadline_resolved.discard(task.spec.task_id)
            self._cancelled_tasks.discard(task.spec.task_id)
            return True
        if task.spec.task_id not in self._cancelled_tasks:
            return False
        self._cancelled_tasks.discard(task.spec.task_id)
        self._fail_task(task, TaskCancelledError(
            f"task {task.spec.task_id[:12]} was cancelled (force=True)"))
        return True

    async def _cancel_on_worker(self, task: _TaskState,
                                addr: Tuple[str, int], force: bool):
        task.retries_left = 0
        if force:
            # the worker will exit; the push failure must read as
            # cancellation, not a worker fault to retry
            self._cancelled_tasks.add(task.spec.task_id)
        try:
            c = await self._aclient_worker(addr)
            await c.call("cancel_task", task_id=task.spec.task_id,
                         force=force, timeout=10.0)
        except Exception:
            pass  # worker already gone: the push path resolves the task

    def _fail_task(self, task: _TaskState, error: BaseException):
        # owner-side failures (cancelled while queued, worker death with
        # no retries left, scheduling errors) never reach an executor —
        # record FAILED here or the task-event store would show the task
        # SUBMITTED forever and the timeline would silently drop it.
        # _executor=False: if the task DID run (worker died mid-task),
        # the executor's RUNNING event already attributed the record and
        # this event must not re-stamp it with the owner's identity
        self.record_task_event(task.spec.task_id, "FAILED",
                               _executor=False, error=str(error)[:200])
        for oid in task.return_oids:
            self.memory.set_error(oid, error)
        if task.spec.num_returns == STREAMING:
            s = self._streams.get(task.spec.task_id)
            if s is not None and s.error is None:
                s.error = error
                s.wake()
        with self._lineage_lock:
            self._reconstructing.discard(task.spec.task_id)
        task.contained_refs = []

    # ------------------------------------------------------ deadline sweep

    def _arm_deadline_sweep(self) -> None:
        """Called from submit paths (any thread) when a deadlined task
        enters the system: make sure the owner-side sweep timer is
        running.  The sweep self-re-arms while any deadlined work
        exists and dies when none does, so undeadlined workloads never
        pay for it."""
        if self._deadline_sweep_handle is not None or self._shutdown:
            return  # racy read is fine: the loop-side ensure re-checks
        try:
            self._loop().call_soon_threadsafe(self._ensure_deadline_sweep)
        except RuntimeError:
            pass  # loop shut down

    def _ensure_deadline_sweep(self) -> None:
        if self._deadline_sweep_handle is None and not self._shutdown:
            self._deadline_sweep_handle = self._loop().call_later(
                config.deadline_check_interval_ms / 1000.0,
                self._deadline_sweep_tick)

    def _fail_deadline(self, task: _TaskState, where: str) -> None:
        """Resolve a task as deadline-exceeded owner-side.  For tasks
        still queued this IS fail-fast (never dispatched — no reply
        will ever come, so nothing to track); for running tasks the
        caller additionally fires the cancel path and the late worker
        reply is discarded via _deadline_resolved (tracking queued
        expiries there would grow the set forever)."""
        task.retries_left = 0
        task.cancelled = True
        if where == "running":
            self._deadline_resolved.add(task.spec.task_id)
        deadlines.count_exceeded(where)
        self._fail_task(task, DeadlineExceededError(
            f"task {task.spec.name or task.spec.method_name or task.spec.task_id[:12]} "
            f"exceeded its deadline while {where}", where=where))

    def _deadline_sweep_tick(self) -> None:
        """One sweep over every owner-side queue and in-flight set:
        expired queued tasks fail fast without dispatching; expired
        running tasks are resolved NOW (the caller's get() unblocks at
        the deadline, not at cancel completion) and cancelled on their
        worker — cooperative first, the existing force path after
        deadline_force_cancel_grace_s."""
        self._deadline_sweep_handle = None
        now = time.time()
        live = False
        resolved = self._deadline_resolved
        # 0. args still resolving (in no queue yet)
        for task in list(self._resolving_tasks.values()):
            dl = task.spec.deadline
            if not dl or task.spec.task_id in resolved:
                continue
            if now >= dl:
                self._fail_deadline(task, "queued")
            else:
                live = True
        # 1. normal-task classes: staged, pending, leased-and-inflight
        for state in list(self._sched.values()):
            expired: List[_TaskState] = []
            with state.lock:
                for t in list(state.staged):
                    if t.spec.deadline and now >= t.spec.deadline:
                        state.staged.remove(t)
                        expired.append(t)
                    elif t.spec.deadline:
                        live = True
            for t in list(state.pending):
                if t.spec.deadline and now >= t.spec.deadline:
                    state.pending.remove(t)
                    expired.append(t)
                elif t.spec.deadline:
                    live = True
            for t in expired:
                self._fail_deadline(t, "queued")
            for lease in list(state.leases):
                for t in list(lease.inflight):
                    dl = t.spec.deadline
                    if not dl or t.spec.task_id in resolved:
                        continue
                    if now >= dl:
                        self._fail_deadline(t, "running")
                        self._spawn(self._deadline_cancel(t, lease.addr))
                    else:
                        live = True
        # 2. actor calls: pending + inflight
        for astate in list(self._actors.values()):
            for t in list(astate.pending):
                if t.spec.deadline and now >= t.spec.deadline:
                    try:
                        astate.pending.remove(t)
                    except ValueError:
                        continue
                    self._fail_deadline(t, "queued")
                elif t.spec.deadline:
                    live = True
            for t in list(astate.inflight.values()):
                dl = t.spec.deadline
                if not dl or t.spec.task_id in resolved:
                    continue
                if now >= dl:
                    self._fail_deadline(t, "running")
                    if astate.addr:
                        self._spawn(self._deadline_cancel(t, astate.addr))
                else:
                    live = True
        if live:
            self._ensure_deadline_sweep()

    async def _deadline_cancel(self, task: _TaskState,
                               addr: Tuple[str, int]):
        """Cancel a deadline-expired RUNNING task on its worker: the
        cooperative interrupt first (async-exc / coroutine cancel at
        the next bytecode), then — if it is STILL running after the
        grace — the existing force path (worker exit; queued tasks
        behind it requeue for free via _account_push_death)."""
        tid = task.spec.task_id
        try:
            c = await self._aclient_worker(addr)
            await c.call("cancel_task", task_id=tid, force=False,
                         timeout=10.0)
        except ConnectionLost:
            return  # worker gone: the push failure path resolves it
        except Exception:
            # a TIMEOUT here is the gray case the force path exists
            # for (a worker wedged in native code / chaos-stalled never
            # answers the cooperative RPC) — fall through to force
            pass
        grace = float(config.deadline_force_cancel_grace_s)
        if grace > 0:
            await self._sleep(grace)
        self._cancelled_tasks.add(tid)
        try:
            c = await self._aclient_worker(addr)
            r = await c.call("cancel_task", task_id=tid, force=True,
                             timeout=10.0)
            if not r.get("ok"):
                self._cancelled_tasks.discard(tid)  # already finished
        except Exception:
            self._cancelled_tasks.discard(tid)

    @staticmethod
    def _pool_key_of(sched_key: tuple) -> tuple:
        # scheduling_class() = (resources, kind, function_id, pg_id,
        # bundle_index, env_key, strategy): the pool key drops kind and
        # function_id — any function of the same shape can reuse the
        # leased worker, which is what makes throughput independent of
        # WHICH function a previous burst ran
        return sched_key[:1] + sched_key[3:]

    def _park_lease(self, state: _SchedState, lease: _Lease) -> None:
        """Idle lease → warm pool (replaces the per-lease linger timer).
        Parked leases keep their agent-side grant; the pool-level sweep
        returns them after _WARM_LEASE_TTL_S of disuse."""
        if lease.dead:
            return
        if lease in state.leases:
            state.leases.remove(lease)
        lease.warm_since = time.monotonic()
        self._warm_leases.setdefault(lease.pool_key, []).append(lease)
        self._ensure_warm_sweep()

    def _adopt_warm_lease(self, state: _SchedState) -> Optional[_Lease]:
        pool = self._warm_leases.get(self._pool_key_of(state.key))
        while pool:
            lease = pool.pop()  # LIFO: hottest worker first
            if lease.dead:
                continue
            self._warm_adopted += 1
            state.leases.append(lease)
            return lease
        return None

    def _ensure_warm_sweep(self) -> None:
        if self._warm_sweep_handle is None and not self._shutdown:
            self._warm_sweep_handle = self._loop().call_later(
                _WARM_LEASE_TTL_S / 2, self._sweep_warm_leases)

    def _sweep_warm_leases(self) -> None:
        self._warm_sweep_handle = None
        now = time.monotonic()
        any_left = False
        for key, pool in list(self._warm_leases.items()):
            keep = []
            for lease in pool:
                if lease.dead:
                    continue
                if now - lease.warm_since >= _WARM_LEASE_TTL_S:
                    self._warm_returned += 1
                    self._spawn(self._return_pooled(lease))
                else:
                    keep.append(lease)
            if keep:
                self._warm_leases[key] = keep
                any_left = True
            else:
                self._warm_leases.pop(key, None)
        if any_left:
            self._ensure_warm_sweep()

    async def _return_pooled(self, lease: _Lease, kill: bool = False):
        if lease.dead:
            return
        lease.dead = True
        await self._notify_drop(lease, kill)

    @staticmethod
    def _locality_pref_addr(spec: TaskSpec) -> Optional[Tuple[str, int]]:
        """Agent addr holding this task's biggest hinted argument (past
        the locality threshold), or None.  The pump prefers a lease on
        that node so class-sharing pipelines don't undo the cluster
        policy's locality routing."""
        totals: Dict[Tuple[str, int], int] = {}
        for a in spec.args:
            if a.object_id is not None and a.loc and a.size:
                key = (a.loc[0], a.loc[1])
                totals[key] = totals.get(key, 0) + a.size
        if not totals:
            return None  # common case: config never consulted
        # sum per node, mirroring pick_node's arg_bytes_by_node scoring
        # (a node holding two medium args beats one holding a single
        # larger arg); stable tie-break on the addr
        best, best_size = max(totals.items(), key=lambda kv: (kv[1], kv[0]))
        min_bytes = int(config.locality_min_bytes)
        if min_bytes <= 0 or best_size < min_bytes:
            return None
        return best

    def _pump(self, state: _SchedState):
        # drain the cross-thread staged queue first: one pass moves a
        # whole submission burst into pending (partitioned handoff —
        # only this class's lock, never a process-global one)
        if state.staged:
            with state.lock:
                state.pending.extend(state.staged)
                state.staged.clear()
        if state.has_deadlines and state.pending:
            # fail-fast BEFORE dispatch: an expired task must never
            # consume a lease slot (the sweep covers idle periods; this
            # covers the moment of assignment)
            now_w = time.time()
            doomed = [t for t in state.pending
                      if t.spec.deadline and now_w >= t.spec.deadline
                      and t.spec.task_id not in self._deadline_resolved]
            for t in doomed:
                state.pending.remove(t)
                self._fail_deadline(t, "queued")
        # hand pending tasks to leases at the depth the service-time
        # curve allows; adopt warm-pool leases before breaking — a
        # pooled worker beats both a deeper pipeline and a fresh lease
        # request
        live = [l for l in state.leases if not l.dead]
        depth = state.stats.depth()
        # group this tick's assignments per lease, filling each chosen
        # lease's pipeline with a CHUNK of consecutive tasks: N tasks to
        # one worker ride ONE push_tasks frame instead of N push RPCs.
        # Assigning one task at a time to the min-inflight lease (the
        # old policy) fragmented a burst into batches of 1-2 spread
        # round-robin across leases — frames, not payload bytes, are
        # what cap small-task throughput, so the fragmentation was the
        # tasks/s ceiling (round-6 profile: 340 single-task frames for
        # a 1000-task burst).
        batches: Dict[int, Tuple[_Lease, List[_TaskState]]] = {}
        deferred: List[_TaskState] = []
        now = time.monotonic()
        while state.pending:
            candidates = [l for l in live if len(l.inflight) < depth]
            if not candidates:
                adopted = (self._adopt_warm_lease(state)
                           if len(state.leases) < _MAX_LEASES_PER_CLASS
                           else None)
                if adopted is None:
                    break  # every lease at depth, nothing warm to adopt
                live.append(adopted)
                continue
            head = state.pending[0]
            # a lease on the node already holding the task's argument
            # bytes beats the shallowest pipeline: the task skips the
            # transfer entirely (cluster-level locality routing decided
            # node choice; this is its per-task dispatch counterpart)
            lease = None
            pref = self._locality_pref_addr(head.spec)
            if pref is not None:
                for cand in candidates:
                    if tuple(cand.agent_addr) == pref:
                        lease = cand
                        break
                if lease is None:
                    # no lease on the holder: hold the task back rather
                    # than binding it to the wrong node.  First
                    # encounter defers unconditionally — requeueing
                    # makes the deficit loop below fire a lease request
                    # whose locality routing targets the holder (an
                    # existing warm lease elsewhere must not swallow
                    # the task before pick_node ever sees it).  After
                    # that, keep deferring only while requests are in
                    # flight, within the deadline — bounded, so a
                    # saturated holder can only delay it, never strand
                    # it
                    state.pending.popleft()
                    first = head.defer_deadline == 0.0
                    if first:
                        head.defer_deadline = now + _LOCALITY_DEFER_S
                    if now < head.defer_deadline \
                            and (first or state.inflight_requests > 0):
                        deferred.append(head)
                        continue
                    # deferral bound passed: dispatch off-holder rather
                    # than strand the task
                    lease = min(candidates, key=lambda l: len(l.inflight))
                    lease.inflight.append(head)
                    batches.setdefault(id(lease), (lease, []))[1].append(head)
                    continue
            if lease is None:
                lease = min(candidates, key=lambda l: len(l.inflight))
            # fill the chosen lease's pipeline with consecutive
            # compatible tasks — a task whose locality pref names a
            # DIFFERENT node breaks the chunk and gets its own pass
            chunk = batches.setdefault(id(lease), (lease, []))[1]
            lease_addr = tuple(lease.agent_addr)
            while len(lease.inflight) < depth and state.pending:
                nxt = state.pending[0]
                npref = (pref if nxt is head
                         else self._locality_pref_addr(nxt.spec))
                if npref is not None and lease_addr != npref:
                    break
                state.pending.popleft()
                lease.inflight.append(nxt)
                chunk.append(nxt)
        if deferred:
            state.pending.extendleft(reversed(deferred))
            if not state.defer_timer:
                # deadline-driven re-pump: without it a request queued
                # 30s at a busy holder would strand deferred tasks past
                # their bound until the next unrelated pump event
                state.defer_timer = True
                wake = min(t.defer_deadline for t in deferred)

                def _expire():
                    state.defer_timer = False
                    self._pump(state)

                self._loop().call_later(max(0.0, wake - now) + 0.01, _expire)
        for lease, tasks in batches.values():
            if not tasks:
                continue
            self._observe_batch_size(len(tasks))
            if len(tasks) == 1:
                self._spawn(self._push(state, lease, tasks[0]))
            else:
                self._spawn(self._push_batch(state, lease, tasks))
        if not state.pending:
            # no demand: cancel outstanding lease requests — a stale
            # queued request would be granted later, sit idle, and
            # stall demand queued behind it on the agent (reference:
            # CancelWorkerLease on lease_policy mismatch/drain)
            if state.request_agents:
                cancels, state.request_agents = state.request_agents, {}
                for rid, addr in cancels.items():
                    self._spawn(self._cancel_lease_request(rid, addr))
            # park every idle lease in the warm pool (a lease granted
            # after the queue drained would otherwise pin resources
            # forever, and the NEXT burst — any function — adopts it)
            for lease in list(state.leases):
                if not lease.inflight and not lease.dead:
                    self._park_lease(state, lease)
            return
        # request more leases if there is unmet demand.  The ask is
        # sized to the pipeline capacity still uncovered — pending /
        # depth workers — not to raw pending count (the old policy
        # over-requested 16 leases for a sub-ms burst one worker could
        # drain, churning worker spawns + queued-request cancels).
        # every live lease is already pipeline-saturated here (the
        # assignment loop only leaves pending tasks when no lease is
        # below depth), so the uncovered demand is pending alone —
        # subtracting live leases again would starve small bursts that
        # spill just past one lease's depth
        need = -(-len(state.pending) // max(1, depth))  # ceil
        deficit = need - state.inflight_requests
        capacity = (_MAX_LEASES_PER_CLASS - len(state.leases)
                    - state.inflight_requests)
        want = max(0, min(deficit, capacity,
                          int(config.lease_request_batch_max)))
        if want <= 0:
            return
        head_spec = state.pending[0].spec
        if deferred or head_spec.placement_group_id:
            # locality-deferred tasks (or bundle-targeted specs) need
            # each request to carry a DISTINCT pending task's spec so
            # hints route leases to each task's holder — keep the
            # per-spec single-request path for them
            for _ in range(want):
                state.inflight_requests += 1
                spec = state.pending[state.req_rr % len(state.pending)].spec
                state.req_rr += 1
                self._spawn(self._request_lease(state, spec))
        else:
            # homogeneous demand: ONE request_leases frame asks the
            # agent for every missing lease at once — a 2k-task burst
            # costs O(1) lease RPC rounds, not O(missing leases)
            state.inflight_requests += want
            self._spawn(self._request_leases(state, head_spec, want))

    _batch_hist = None

    def _observe_batch_size(self, n: int) -> None:
        if self._batch_hist is None:
            from ray_tpu._private.metrics import dispatch_batch_size_histogram

            self._batch_hist = dispatch_batch_size_histogram()
        self._batch_hist.observe(n)

    async def _cancel_lease_request(self, rid: str, addr: Tuple[str, int]):
        try:
            c = await self._aclient_agent(addr)
            await c.oneway("cancel_lease_request", req_id=rid)
        except Exception:
            pass

    async def _pg_bundle_addr(self, pg_id: str, bundle_index: int,
                              refresh: bool = False):
        """Resolve (and cache) the agent address hosting a PG bundle.

        Returns (status, addr): status in {"ok", "pending", "gone"}.
        """
        info = None if refresh else self._pg_cache.get(pg_id)
        if info is None or info.get("state") != "CREATED":
            info = await self.head.aio.call(
                "get_placement_group", pg_id=pg_id, wait=True,
                timeout=config.pubsub_poll_timeout_ms / 1000.0 + 10.0)
            self._pg_cache[pg_id] = info
        placements = info.get("placements") or []
        if info.get("state") == "PENDING":
            return "pending", None
        if info.get("state") != "CREATED" or bundle_index >= len(placements):
            return "gone", None
        p = placements[bundle_index]
        if p is None:
            return "pending", None  # bundle being re-reserved after node death
        return "ok", (p["addr"][0], p["addr"][1])

    async def _request_lease(self, state: _SchedState, spec: TaskSpec):
        rid = ""
        try:
            if spec.placement_group_id:
                await self._request_pg_lease(state, spec)
                return
            state.req_counter += 1
            rid = f"{self.worker_id[:12]}-{state.req_counter}"
            agent_addr = self.agent_addr
            for _hop in range(8):
                state.request_agents[rid] = agent_addr
                try:
                    c = await self._aclient_agent(agent_addr)
                    reply = await c.call(
                        "request_lease", spec=spec.to_wire(), req_id=rid,
                        timeout=config.worker_lease_timeout_ms / 1000.0 + 10.0)
                except (ConnectionLost, RpcError):
                    if agent_addr == self.agent_addr:
                        raise
                    agent_addr = self.agent_addr  # spillback target died: retry home
                    continue
                if "spillback" in reply:
                    agent_addr = tuple(reply["spillback"]["addr"])
                    continue
                if "granted" in reply:
                    g = reply["granted"]
                    lease = _Lease(g["lease_id"], g["worker_id"],
                                   (g["addr"][0], g["addr"][1]), agent_addr,
                                   tpu_chips=g.get("tpu_chips"),
                                   pool_key=self._pool_key_of(state.key),
                                   resources=dict(spec.resources))
                    state.leases.append(lease)
                    return
                if reply.get("error") == "infeasible":
                    err = SchedulingError(reply.get("error_str", "infeasible"))
                    while state.pending:
                        self._fail_task(state.pending.popleft(), err)
                    return
                if reply.get("error") == "poisoned":
                    self._fail_poisoned(state, spec, reply)
                    return
                if reply.get("error") == "runtime env setup failed":
                    err = RuntimeEnvSetupError(
                        reply.get("error_str", "runtime env setup failed"))
                    while state.pending:
                        self._fail_task(state.pending.popleft(), err)
                    return
                if reply.get("error") == "canceled":
                    return  # we canceled it: demand drained
                if reply.get("error") == "deadline exceeded":
                    # the agent dropped our queued lease request because
                    # the spec's deadline passed: the finally's pump
                    # fails the expired tasks fast and re-requests for
                    # whatever demand remains
                    return
                # lease timeout: retry while there is still demand
                if not state.pending:
                    return
        finally:
            if rid:
                state.request_agents.pop(rid, None)
            state.inflight_requests -= 1
            self._pump(state)

    async def _request_leases(self, state: _SchedState, spec: TaskSpec,
                              count: int):
        """Batched lease acquisition: ONE request_leases frame asks an
        agent for up to `count` workers of this spec's shape; the agent
        grants what fits now in one reply (node_agent.rpc_request_leases).
        A partial grant returns immediately — the post-reply pump
        recomputes the deficit and re-asks, which converges in at most
        one extra frame while never camping on a saturated agent's FIFO
        with a multi-lease request."""
        rid = ""
        try:
            state.req_counter += 1
            rid = f"{self.worker_id[:12]}-{state.req_counter}"
            agent_addr = self.agent_addr
            for _hop in range(8):
                state.request_agents[rid] = agent_addr
                try:
                    c = await self._aclient_agent(agent_addr)
                    reply = await c.call(
                        "request_leases", spec=spec.to_wire(), count=count,
                        req_id=rid,
                        timeout=config.worker_lease_timeout_ms / 1000.0 + 10.0)
                except (ConnectionLost, RpcError):
                    if agent_addr == self.agent_addr:
                        raise
                    agent_addr = self.agent_addr  # spillback target died
                    continue
                if "spillback" in reply:
                    agent_addr = tuple(reply["spillback"]["addr"])
                    continue
                grants = reply.get("granted_list") or ()
                for g in grants:
                    state.leases.append(_Lease(
                        g["lease_id"], g["worker_id"],
                        (g["addr"][0], g["addr"][1]), agent_addr,
                        tpu_chips=g.get("tpu_chips"),
                        pool_key=self._pool_key_of(state.key),
                        resources=dict(spec.resources)))
                if grants:
                    return
                if reply.get("error") == "infeasible":
                    err = SchedulingError(reply.get("error_str", "infeasible"))
                    while state.pending:
                        self._fail_task(state.pending.popleft(), err)
                    return
                if reply.get("error") == "poisoned":
                    self._fail_poisoned(state, spec, reply)
                    return
                if reply.get("error") == "runtime env setup failed":
                    err = RuntimeEnvSetupError(
                        reply.get("error_str", "runtime env setup failed"))
                    while state.pending:
                        self._fail_task(state.pending.popleft(), err)
                    return
                if reply.get("error") == "canceled":
                    return  # we canceled it: demand drained
                if reply.get("error") == "deadline exceeded":
                    return  # expired spec: the finally's pump fails it
                if not state.pending:
                    return  # lease timeout with no demand left
        finally:
            if rid:
                state.request_agents.pop(rid, None)
            state.inflight_requests -= count
            self._pump(state)

    async def _request_pg_lease(self, state: _SchedState, spec: TaskSpec):
        """Leases for bundle-targeted tasks go straight to the node that
        reserved the bundle (no hybrid policy / spillback)."""
        idx = max(spec.bundle_index, 0)
        attempt = 0
        while True:
            status, addr = await self._pg_bundle_addr(
                spec.placement_group_id, idx, refresh=attempt > 0)
            if status == "pending":
                # the group (or this bundle) isn't placed yet: the head
                # keeps scheduling it; waiting must not consume attempts
                attempt = max(attempt, 1)
                continue
            if status == "gone" or attempt >= 4:
                err = SchedulingError(
                    f"placement group {spec.placement_group_id[:12]} bundle "
                    f"{idx} is not available")
                while state.pending:
                    self._fail_task(state.pending.popleft(), err)
                return
            attempt += 1
            try:
                c = await self._aclient_agent(addr)
                reply = await c.call(
                    "request_lease", spec=spec.to_wire(),
                    timeout=config.worker_lease_timeout_ms / 1000.0 + 10.0)
            except (ConnectionLost, RpcError):
                continue  # bundle node died: refresh placement and retry
            if "granted" in reply:
                g = reply["granted"]
                lease = _Lease(g["lease_id"], g["worker_id"],
                               (g["addr"][0], g["addr"][1]), addr,
                               tpu_chips=g.get("tpu_chips"), in_bundle=True,
                               pool_key=self._pool_key_of(state.key),
                               resources=dict(spec.resources))
                state.leases.append(lease)
                return
            if reply.get("error") == "bundle not reserved":
                continue  # rescheduled elsewhere: refresh and retry
            if reply.get("error") == "infeasible":
                err = SchedulingError(reply.get("error_str", "infeasible"))
                while state.pending:
                    self._fail_task(state.pending.popleft(), err)
                return
            if reply.get("error") == "poisoned":
                self._fail_poisoned(state, spec, reply)
                return
            if not state.pending:
                return

    def _observe_exec(self, state: _SchedState, reply: Dict[str, Any]) -> None:
        """Feed the worker-reported execution time from a result frame
        into the class's windowed service estimator."""
        exec_s = reply.get("exec_s")
        if isinstance(exec_s, (int, float)):
            state.stats.observe(float(exec_s))

    def _reply_disposition(self, task: _TaskState,
                           reply: Dict[str, Any]) -> str:
        """How to resolve a completed push: "resolve" (normal reply
        processing), "retry" (worker flagged a retryable fault, e.g. a
        stale cancellation interrupt hit the wrong task — requeue without
        surfacing the error), or "cancelled" (already resolved here)."""
        if not reply.get("retryable"):
            return "resolve"
        if self._take_cancelled(task):
            return "cancelled"
        if task.retries_left == 0:
            return "resolve"  # out of retries: surface the reply's error
        if task.retries_left > 0:
            task.retries_left -= 1
        return "retry"

    async def _push(self, state: _SchedState, lease: _Lease, task: _TaskState):
        # LEASED marks dispatch to a leased worker; the head derives the
        # queued (submitted→leased) and leased (leased→running) phases of
        # ray_tpu_task_sched_latency_seconds from it
        self.record_task_event(task.spec.task_id, "LEASED")
        try:
            c = await self._aclient_worker(lease.addr)
            reply = await c.call("push_task", spec=task.spec.to_wire(),
                                 tpu_chips=lease.tpu_chips,
                                 timeout=_TASK_PUSH_TIMEOUT)
        except (ConnectionLost, RpcError, Exception) as e:
            self._drop_lease(state, lease, kill=True)
            # a watchdog kill's receipt rides the agent connection, the
            # death itself the worker connection: one beat lets an
            # in-flight receipt land before the death is classified
            await self._sleep(0.05)
            if self._account_push_death(lease, task, e):
                await self._sleep(self._death_retry_delay([task]))
                state.pending.appendleft(task)
            self._pump(state)
            return
        self._observe_exec(state, reply)
        try:
            lease.inflight.remove(task)
        except ValueError:
            pass
        d = self._reply_disposition(task, reply)
        if d == "retry":
            state.pending.appendleft(task)
        elif d == "resolve":
            await self._process_reply(task, reply, lease.addr)
        self._pump(state)

    def _account_push_death(self, lease: _Lease, task: _TaskState,
                            error: Exception) -> bool:
        """Worker-death policy for one pushed task (shared by single and
        batched pushes): only the task actually running (oldest in the
        worker's FIFO when it died) is charged a retry; tasks merely
        queued behind it were never started and requeue for free.
        A watchdog OOM receipt for the dead worker reroutes the charge
        to the separate OOM budget (typed error when exhausted).
        Returns True if the task should be requeued, False if it was
        resolved (cancelled or failed)."""
        started = lease.failed_head is task
        try:
            lease.inflight.remove(task)
        except ValueError:
            pass
        if self._take_cancelled(task):
            return False
        if started:
            receipt = self._oom_receipts.pop(lease.worker_id, None)
            if receipt is not None:
                return self._account_oom_death(task, receipt)
        if not started or task.retries_left != 0:
            if started and task.retries_left > 0:
                task.retries_left -= 1
            return True
        # TERMINAL crash (whole retry budget burned on worker deaths):
        # feed the head's poison accounting — classes that reliably
        # crash workers quarantine like OOM loops do.  Deliberately NOT
        # counted per-kill: one dead NODE takes every same-class lease
        # on it at once, and a class that recovers on retry elsewhere
        # must never read as poison
        self._report_task_kill(task.spec, "crash")
        self._fail_task(task, RayWorkerError(
            f"worker {lease.worker_id[:8]} died running "
            f"{task.spec.name or task.spec.function_id[:8]}: {error}"))
        return False

    def _account_oom_death(self, task: _TaskState,
                           receipt: Dict[str, Any]) -> bool:
        """Charge one watchdog kill against the task's OOM budget.
        Never touches max_retries.  Exhausted budget (or an already-
        quarantined class) resolves the task with the typed error built
        from the receipt; otherwise the task requeues after a jittered
        exponential backoff (the rpc.backoff_delays shape) bounded by
        the spec's remaining deadline."""
        from ray_tpu._private.memory_monitor import is_self_poisoning

        spec = task.spec
        if is_self_poisoning(int(receipt.get("rss", 0)),
                             int(receipt.get("limit", 0))):
            self._report_task_kill(spec, "oom")
        q = self._fid_quarantined(spec.function_id)
        if q is not None:
            self._fail_task(task, PoisonedTaskError(
                q[1], key=spec.function_id, history=q[2]))
            return False
        if task.oom_retries_left == 0:
            self._fail_task(task, self._oom_error(spec, receipt))
            return False
        if task.oom_retries_left > 0:
            task.oom_retries_left -= 1
        task.oom_attempt += 1
        base = config.task_retry_delay_ms / 1000.0
        cap = config.task_oom_retry_max_backoff_ms / 1000.0
        ceiling = min(max(base, 1e-3) * (2.0 ** task.oom_attempt), cap)
        delay = random.uniform(ceiling / 2.0, ceiling)
        if spec.deadline:
            remaining = spec.deadline - time.time()
            if remaining <= 0:
                self._fail_deadline(task, "queued")
                return False
            delay = min(delay, remaining)
        task.oom_delay = delay
        return True

    @staticmethod
    def _oom_error(spec: TaskSpec, receipt: Dict[str, Any]) -> Exception:
        name = spec.name or spec.method_name or spec.function_id[:8]
        return OutOfMemoryError(
            f"task {name!r} was OOM-killed by the memory watchdog on "
            f"node {receipt.get('node_id', '')[:12]} (worker RSS "
            f"{int(receipt.get('rss', 0)) >> 20} MiB, node usage "
            f"{receipt.get('usage', 0.0):.0%} >= threshold "
            f"{receipt.get('threshold', 0.0):.0%}) and its "
            f"task_oom_retries budget is exhausted",
            rss_bytes=int(receipt.get("rss", 0)),
            node_usage=float(receipt.get("usage", 0.0)),
            node_id=receipt.get("node_id", ""),
            worker_id=receipt.get("worker_id", ""),
            breakdown=receipt.get("breakdown") or {})

    def _fid_quarantined(self, fid: str) -> Optional[tuple]:
        """The live local-quarantine record for fid, TTL-pruned."""
        q = self._quarantined.get(fid)
        if q is None:
            return None
        if q[0] and time.time() >= q[0]:
            self._quarantined.pop(fid, None)
            return None
        return q

    def _report_task_kill(self, spec: TaskSpec, kind: str) -> None:
        """Tell the head this class's execution killed a worker (fire-
        and-forget from the IO loop); the reply carries the class's
        quarantine verdict, cached locally so the NEXT submission fails
        fast without waiting for lease-layer gossip."""
        fid = spec.function_id
        if not fid:
            return
        self._kill_history.add(fid)
        name = spec.name or spec.method_name or fid[:8]

        async def _report():
            try:
                r = await self.head.aio.call(
                    "task_kill_report", key=fid, kind=kind, name=name,
                    node_id=self.node_id)
            except Exception:
                return
            if r.get("quarantined"):
                until = min(float(r.get("until", 0.0)) or
                            (time.time() + _POISON_CACHE_S),
                            time.time() + _POISON_CACHE_S)
                self._quarantined[fid] = (
                    until,
                    r.get("detail", f"task class {name!r} is quarantined"),
                    list(r.get("history", [])))

        self._spawn(_report())

    def _report_task_ok(self, spec: TaskSpec) -> None:
        """First success of a class with local kill history: reset the
        head's consecutive-kill count (fire-and-forget)."""
        fid = spec.function_id
        if fid not in self._kill_history:
            return
        self._kill_history.discard(fid)

        async def _report():
            try:
                await self.head.aio.call("task_ok_report", key=fid)
            except Exception:
                pass

        self._spawn(_report())

    @staticmethod
    def _death_retry_delay(tasks: List[_TaskState]) -> float:
        """The pre-requeue sleep for a batch of death-requeued tasks:
        the plain worker-death delay, or the longest OOM backoff any of
        them was charged (consumed so a later, non-OOM requeue of the
        same task sleeps normally)."""
        delay = config.task_retry_delay_ms / 1000.0
        for t in tasks:
            if t.oom_delay > 0:
                delay = max(delay, t.oom_delay)
                t.oom_delay = 0.0
        return delay

    async def _push_batch(self, state: _SchedState, lease: _Lease,
                          tasks: List[_TaskState]):
        """One push_tasks frame carrying N specs (this tick's assignments
        to one lease).  The worker executes FIFO and pushes each result
        back the moment it completes ("batch_result" — handled in
        _on_exec_worker_push, which removes the task from inflight), so
        failure semantics stay identical to per-task _push: on worker
        death, results that arrived were already processed, the task at
        inflight[0] is the one actually running, and only it is charged
        a retry."""
        for task in tasks:
            self._batch_pending[task.spec.task_id] = (
                "task", state, lease, task)
            self.record_task_event(task.spec.task_id, "LEASED")
        try:
            c = await self._aclient_worker(lease.addr)
            await c.call(
                "push_tasks", specs=[t.spec.to_wire() for t in tasks],
                tpu_chips=lease.tpu_chips, timeout=_TASK_PUSH_TIMEOUT)
        except (ConnectionLost, RpcError, Exception) as e:
            self._drop_lease(state, lease, kill=True)
            await self._sleep(0.05)  # let an in-flight OOM receipt land
            requeue = [task for task in tasks
                       if self._batch_pending.pop(task.spec.task_id, None)
                       is not None  # else: result arrived before death
                       and self._account_push_death(lease, task, e)]
            if requeue:
                await self._sleep(self._death_retry_delay(requeue))
                state.pending.extendleft(reversed(requeue))
            self._pump(state)
            return
        # ordered connection: every batch_result was dispatched (and its
        # registration popped) before this reply resolved — nothing to do
        self._pump(state)

    async def _finish_batch_items(self, work: List[tuple]):
        """Process a frame's worth of batched-push results (inflight
        bookkeeping already done synchronously in the push handler);
        pump each touched scheduling state / actor once at the end."""
        states = {}
        astates = {}
        for entry, reply in work:
            if entry[0] == "task":
                _, state, lease, task = entry
                self._observe_exec(state, reply)
                d = self._reply_disposition(task, reply)
                if d == "retry":
                    state.pending.appendleft(task)
                elif d == "resolve":
                    await self._process_reply(task, reply, lease.addr)
                states[id(state)] = state
            else:  # actor
                _, astate, task, addr = entry
                d = self._reply_disposition(task, reply)
                if d == "retry":
                    self._actor_requeue(astate, task)
                elif d == "resolve":
                    await self._process_reply(task, reply, addr)
                astates[id(astate)] = astate
        for state in states.values():
            self._pump(state)
        for astate in astates.values():
            await self._actor_pump(astate)

    async def _sleep(self, s: float):
        import asyncio
        await asyncio.sleep(s)

    async def _return_lease(self, state: _SchedState, lease: _Lease, kill=False):
        if lease.inflight or lease.dead:
            return
        lease.dead = True
        if lease in state.leases:
            state.leases.remove(lease)
        try:
            c = await self._aclient_agent(lease.agent_addr)
            await c.call("return_lease", lease_id=lease.lease_id, kill_worker=kill)
        except Exception:
            pass

    def _drop_lease(self, state: _SchedState, lease: _Lease, kill: bool):
        if lease.dead:
            return  # several pipelined pushes may fail on the same lease
        lease.dead = True
        # snapshot which task was executing when the worker died — each
        # failing _push compares against this, not the shifting deque head
        lease.failed_head = lease.inflight[0] if lease.inflight else None
        if lease in state.leases:
            state.leases.remove(lease)
        self._spawn(self._notify_drop(lease, kill))

    async def _notify_drop(self, lease: _Lease, kill: bool):
        try:
            c = await self._aclient_agent(lease.agent_addr)
            await c.call("return_lease", lease_id=lease.lease_id, kill_worker=kill)
        except Exception:
            pass

    async def _process_reply(self, task: _TaskState, reply: Dict[str, Any],
                             worker_addr: Tuple[str, int]):
        if task.spec.task_id in self._deadline_resolved:
            # the deadline sweep resolved this task while it ran; the
            # late reply (a value, or the cancel-induced error) must
            # not overwrite the DeadlineExceededError the caller saw.
            # Still ack held values so the worker's pin set drains.
            self._deadline_resolved.discard(task.spec.task_id)
            self._cancelled_tasks.discard(task.spec.task_id)
            if reply.get("needs_ack"):
                try:
                    c = await self._aclient_worker(worker_addr)
                    await c.oneway("task_ack", task_id=task.spec.task_id)
                except Exception:
                    pass
            with self._lineage_lock:
                self._reconstructing.discard(task.spec.task_id)
            task.contained_refs = []
            return
        if task.spec.num_returns == STREAMING:
            # every stream_item push was dispatched before this reply
            # (same ordered connection), so arrived is final here
            s = self._streams.get(task.spec.task_id)
            if s is not None:
                if reply.get("error"):
                    results = reply.get("results") or []
                    try:
                        s.error = cloudpickle.loads(results[0]["err"])
                    except Exception:
                        s.error = RayTaskError(
                            task.spec.name or "stream",
                            reply.get("error_str", "<unpicklable error>"))
                else:
                    s.total = int(reply.get("stream_len", s.arrived))
                s.wake()
        results = reply.get("results", [])
        nested_all: Dict[str, List] = reply.get("nested") or {}
        for i, oid in enumerate(task.return_oids):
            r = results[i] if i < len(results) else {"err": cloudpickle.dumps(
                RayWorkerError("missing return value"))}
            nested = nested_all.get(oid) or []
            if nested:
                inner_refs = []
                for n_oid, n_owner, n_node in nested:
                    ref = ObjectRef(n_oid,
                                    owner_addr=tuple(n_owner) if n_owner else None,
                                    node_addr=tuple(n_node) if n_node else None)
                    inner_refs.append(ref)
                    if ref.owner_addr is not None and tuple(ref.owner_addr) != self.address:
                        await self._send_add_borrow(tuple(ref.owner_addr), n_oid)
                self._containers[oid] = inner_refs
            if "err" in r:
                try:
                    exc = cloudpickle.loads(r["err"])
                except Exception:
                    exc = RayTaskError(task.spec.name or "task", "<unpicklable error>")
                self.memory.set_error(oid, exc)
            elif "v" in r:
                self.memory.set_raw(oid, r["v"])
            elif "stored" in r:
                node = tuple(r["stored"]["node"])
                self._locations[oid] = node
                if r["stored"].get("size"):
                    self._obj_sizes[oid] = r["stored"]["size"]
                if task.spec.kind == NORMAL_TASK:
                    self._record_lineage(task, oid)
                self.memory.set_in_plasma(oid, node)
        # the worker replied normally (e.g. a force-cancel caught the task
        # still queued): the force-death mapping entry is no longer needed
        self._cancelled_tasks.discard(task.spec.task_id)
        if not reply.get("error"):
            # a real completion of a class this owner reported kills
            # for: reset the head's consecutive-kill count (the poison
            # quarantine counts CONSECUTIVE kills by design)
            self._report_task_ok(task.spec)
        for b_oid in reply.get("borrows") or []:
            self.rc.add_borrower(b_oid, worker_addr)
        if reply.get("needs_ack"):
            try:
                c = await self._aclient_worker(worker_addr)
                await c.oneway("task_ack", task_id=task.spec.task_id)
            except Exception:
                pass
        with self._lineage_lock:
            self._reconstructing.discard(task.spec.task_id)
        task.contained_refs = []  # release submission pins

    # ------------------------------------------------- lineage reconstruction

    def _record_lineage(self, task: _TaskState, oid: str) -> None:
        with self._lineage_lock:
            entry = self._lineage.get(task.spec.task_id)
            if entry is None:
                entry = _LineageEntry(task.spec, list(task.contained_refs))
                self._lineage[task.spec.task_id] = entry
            entry.live.add(oid)
            self._lineage_by_oid[oid] = task.spec.task_id

    def _drop_lineage(self, oid: str) -> None:
        with self._lineage_lock:
            tid = self._lineage_by_oid.pop(oid, None)
            if tid is None:
                return
            entry = self._lineage.get(tid)
            if entry is not None:
                entry.live.discard(oid)
                if not entry.live:
                    self._lineage.pop(tid, None)  # arg pins released via GC

    def _maybe_reconstruct(self, oid: str) -> bool:
        """Resubmit the task that produced a lost plasma return.

        Returns True if a reconstruction is (already) underway — callers
        then re-wait on the object.  Reference:
        src/ray/core_worker/object_recovery_manager.cc (recover via
        TaskManager resubmit, bounded by the retry budget).
        """
        with self._lineage_lock:
            tid = self._lineage_by_oid.get(oid)
            if tid is None:
                return False
            entry = self._lineage.get(tid)
            if entry is None:
                return False
            if tid in self._reconstructing:
                return True
            if entry.attempts_left == 0:
                return False
            if entry.attempts_left > 0:
                entry.attempts_left -= 1
            self._reconstructing.add(tid)
            spec = entry.spec
        task = _TaskState(spec, list(entry.arg_pins))
        for roid in task.return_oids:
            self._locations.pop(roid, None)
            self.memory.clear_resolution(roid)
        self._spawn(self._submit(task))
        return True

    # ---------------------------------------------------------- actor submit

    def create_actor(self, class_id: str, args: tuple, kwargs: dict,
                     resources: Optional[Dict[str, float]] = None,
                     max_restarts: int = 0, max_task_retries: int = 0,
                     max_concurrency: int = 1, name: str = "",
                     runtime_env: Optional[Dict[str, Any]] = None,
                     scheduling_strategy: Optional[Dict[str, Any]] = None,
                     placement_group_id: str = "",
                     bundle_index: int = -1,
                     method_num_returns: Optional[Dict[str, Any]] = None
                     ) -> str:
        from ray_tpu._private.runtime_env import merge as _renv_merge

        aid = ActorID.of(JobID.from_hex(self.job_id))
        tid = TaskID.for_actor_creation(aid)
        wire_args, contained = self._serialize_args(args, kwargs)
        spec = TaskSpec(
            task_id=tid.hex(), job_id=self.job_id, kind=ACTOR_CREATION_TASK,
            function_id=class_id, args=wire_args, num_returns=0,
            resources=resources or {"CPU": 1}, actor_id=aid.hex(),
            max_restarts=max_restarts, max_concurrency=max_concurrency,
            max_retries=max_task_retries, name=name,
            owner_addr=self.address, caller_id=self.worker_id,
            runtime_env=_renv_merge(self.job_runtime_env, runtime_env or {}),
            scheduling_strategy=scheduling_strategy or {},
            placement_group_id=placement_group_id,
            bundle_index=max(bundle_index, 0) if placement_group_id else -1)
        span, spec.trace_ctx = tracing.begin_submit(
            "create_actor " + (name or class_id[:8]))
        if span is not None:
            span.set_attribute("actor_id", aid.hex())
        self.head.call("create_actor", spec=spec.to_wire(), name=name,
                       method_num_returns=method_num_returns or {})
        if span is not None:
            span.end()
        # hold arg refs until the actor is alive; the head owns creation
        astate = _ActorState(aid.hex())
        self._actors[aid.hex()] = astate
        # keep contained refs pinned for the actor's lifetime (v1: simple)
        self._containers[f"actor:{aid.hex()}"] = contained
        return aid.hex()

    def submit_actor_task(self, actor_id: str, method_name: str, args: tuple,
                          kwargs: dict, num_returns: int = 1,
                          max_retries: int = 0,
                          timeout_s: Optional[float] = None
                          ) -> List[ObjectRef]:
        if num_returns == "streaming":
            num_returns = STREAMING
        astate = self._actors.get(actor_id)
        if astate is None:
            astate = self._actors.setdefault(actor_id, _ActorState(actor_id))
        tid = TaskID.for_actor_task(ActorID.from_hex(actor_id))
        wire_args, contained = self._serialize_args(args, kwargs)
        spec = TaskSpec(
            task_id=tid.hex(), job_id=self.job_id, kind=ACTOR_TASK,
            args=wire_args, num_returns=num_returns, resources={},
            max_retries=max_retries, actor_id=actor_id,
            method_name=method_name, caller_id=self.worker_id,
            owner_addr=self.address,
            deadline=deadlines.effective_deadline(timeout_s) or 0.0)
        span, spec.trace_ctx = tracing.begin_submit("submit " + method_name)
        if span is not None:
            span.set_attribute("task_id", spec.task_id)
            span.set_attribute("actor_id", actor_id)
            span.end()
        task = _TaskState(spec, contained)
        refs: List[Any] = []
        if num_returns == STREAMING:
            task.retries_left = 0
            self._streams[spec.task_id] = StreamState()
            refs.append(ObjectRefGenerator(self, spec.task_id))
        call_site = _user_call_site()
        for oid in task.return_oids:
            self.memory.ensure(oid)
            refs.append(ObjectRef(oid, owner_addr=self.address))
            self.rc.set_meta(oid, call_site=call_site, name=method_name)
        try:
            self._post_to_loop(self._actor_enqueue, astate, task)
        except RuntimeError:
            pass  # loop shut down
        if spec.deadline:
            # after the enqueue post (see submit_task): a sweep tick
            # between arm and enqueue could otherwise disarm for good
            self._arm_deadline_sweep()
        return refs

    def _actor_enqueue(self, astate: _ActorState, task: _TaskState) -> None:
        """Loop-side enqueue: assigns the seqno (in submission order —
        call_soon_threadsafe preserves caller order) and either marks the
        call ready or spawns dependency resolution.  Pumping is coalesced
        so rapid-fire calls form push_tasks batches (reference:
        direct_actor_task_submitter.h sequence numbers)."""
        if astate.dead:
            self._fail_task(task, ActorDiedError(
                astate.death_cause or "actor is dead"))
            return
        task.spec.seqno = astate.seq
        astate.seq += 1
        # enqueue BEFORE resolving deps so per-handle submission order is
        # preserved even when an earlier call waits on a pending ref
        if any(a.object_id is not None for a in task.spec.args):
            task.deps_ready = False
            astate.pending.append(task)
            self._spawn(self._actor_resolve_then_pump(astate, task))
        else:
            astate.pending.append(task)
            if not astate.pump_queued:
                astate.pump_queued = True
                self._loop().call_soon(self._actor_coalesced_pump, astate)

    def _actor_coalesced_pump(self, astate: _ActorState) -> None:
        astate.pump_queued = False
        self._spawn(self._actor_pump(astate))

    async def _actor_resolve_then_pump(self, astate: _ActorState,
                                       task: _TaskState):
        ok = await self._resolve_deps(task)
        if not ok:
            try:
                astate.pending.remove(task)
            except ValueError:
                pass
            await self._actor_pump(astate)  # unblock the queue behind it
            return
        task.deps_ready = True
        await self._actor_pump(astate)

    async def _actor_pump(self, astate: _ActorState):
        if astate.recovering or astate.dead:
            return
        while astate.addr is None:
            # keep long-polling until the actor lands somewhere: slow
            # constructors (first jax import in a fresh worker) can
            # outlast one poll window, and pushing with addr=None would
            # misclassify every queued task as a worker death.  One
            # coroutine polls per actor; concurrent pumps await it
            # instead of multiplying head long-polls.
            import asyncio

            if astate.resolving is not None:
                await astate.resolving
            else:
                astate.resolving = asyncio.get_running_loop().create_future()
                try:
                    await self._actor_resolve(astate)
                finally:
                    fut, astate.resolving = astate.resolving, None
                    fut.set_result(None)
            if astate.dead or astate.recovering:
                return
        batch: List[_TaskState] = []
        while astate.pending and astate.pending[0].deps_ready \
                and len(astate.inflight) < _MAX_ACTOR_INFLIGHT:
            task = astate.pending.popleft()
            astate.inflight[task.spec.seqno] = task
            batch.append(task)
        if len(batch) == 1:
            self._spawn(self._actor_push(astate, batch[0], astate.instance))
        elif batch:
            # one push_tasks frame for this tick's ready calls — the
            # worker executes FIFO so seqno order is preserved
            self._spawn(self._actor_push_batch(astate, batch,
                                               astate.instance))

    async def _actor_resolve(self, astate: _ActorState, known_instance: int = -1):
        try:
            info = await self.head.aio.call(
                "get_actor_info", actor_id=astate.actor_id, wait=True,
                known_instance=known_instance,
                timeout=config.pubsub_poll_timeout_ms / 1000.0 + 10.0)
        except Exception as e:
            astate.dead = True
            astate.death_cause = f"cannot reach head service: {e}"
            self._actor_fail_all(astate)
            return
        if info["state"] == "ALIVE":
            astate.addr = tuple(info["addr"])
            astate.instance = info["instance"]
        elif info["state"] == "DEAD":
            astate.dead = True
            astate.death_cause = info.get("death_cause", "actor died")
            self._actor_fail_all(astate)
        # PENDING/RESTARTING after long-poll timeout: stay unresolved; the
        # next pump retries

    def _actor_fail_all(self, astate: _ActorState):
        err = ActorDiedError(astate.death_cause or "actor died")
        for task in list(astate.inflight.values()):
            self._fail_task(task, err)
        astate.inflight.clear()
        while astate.pending:
            self._fail_task(astate.pending.popleft(), err)

    async def _actor_push(self, astate: _ActorState, task: _TaskState, instance: int):
        addr = astate.addr
        if addr is None:
            # a concurrent recovery cleared the address between pump and
            # push: this task was never sent — requeue it for free (it
            # must NOT be charged a retry or misreported as a death)
            astate.inflight.pop(task.spec.seqno, None)
            self._actor_requeue(astate, task)
            await self._actor_pump(astate)
            return
        try:
            c = await self._aclient_worker(addr)
            reply = await c.call("push_task", spec=task.spec.to_wire(),
                                 timeout=_TASK_PUSH_TIMEOUT)
        except (ConnectionLost, Exception) as e:
            await self._actor_recover(astate, [task], instance, e)
            return
        astate.inflight.pop(task.spec.seqno, None)
        d = self._reply_disposition(task, reply)
        if d == "retry":
            self._actor_requeue(astate, task)
        elif d == "resolve":
            # the snapshot, NOT astate.addr: a concurrent recovery may
            # have cleared/re-pointed the live field while we awaited the
            # reply, and borrows/acks must go to the executing worker
            await self._process_reply(task, reply, addr)
        await self._actor_pump(astate)

    async def _actor_push_batch(self, astate: _ActorState,
                                tasks: List[_TaskState], instance: int):
        """Batched actor push: one push_tasks frame for this tick's ready
        calls (FIFO on the worker preserves seqno order).  Per-task
        results arrive as "batch_result" pushes, so calls that completed
        before an actor death are never re-executed."""
        addr = astate.addr
        if addr is None:
            for task in tasks:
                astate.inflight.pop(task.spec.seqno, None)
                self._actor_requeue(astate, task)
            await self._actor_pump(astate)
            return
        for task in tasks:
            self._batch_pending[task.spec.task_id] = (
                "actor", astate, task, addr)
        try:
            c = await self._aclient_worker(addr)
            await c.call("push_tasks",
                         specs=[t.spec.to_wire() for t in tasks],
                         timeout=_TASK_PUSH_TIMEOUT)
        except (ConnectionLost, Exception) as e:
            unfinished = [t for t in tasks
                          if self._batch_pending.pop(t.spec.task_id, None)
                          is not None]
            await self._actor_recover(astate, unfinished, instance, e)
            return
        await self._actor_pump(astate)

    def _actor_requeue(self, astate: _ActorState, task: _TaskState) -> None:
        """Requeue preserving seqno order: concurrent pushes may requeue
        out of pop order, and the worker executes in arrival order.
        A task requeued after the actor died would sit in the dead
        actor's deque forever (pump no-ops on dead), pinning its arg
        refs — fail it instead."""
        if astate.dead:
            self._fail_task(task, ActorDiedError(
                astate.death_cause or "actor is dead"))
            return
        astate.pending.append(task)
        if len(astate.pending) > 1:
            astate.pending = deque(
                sorted(astate.pending, key=lambda t: t.spec.seqno))

    async def _actor_recover(self, astate: _ActorState,
                             tasks: List[_TaskState],
                             instance: int, error: Exception):
        """Connection to the actor failed mid-call."""
        for task in tasks:
            astate.inflight.pop(task.spec.seqno, None)
            if self._take_cancelled(task):
                continue
            if task.retries_left != 0:
                if task.retries_left > 0:
                    task.retries_left -= 1
                # retryable: requeued, re-sent after re-resolve
                self._actor_requeue(astate, task)
            else:
                self._fail_task(task, ActorDiedError(
                    f"actor task {task.spec.method_name} failed: "
                    f"worker died ({error})"))
        if astate.recovering or astate.dead:
            return
        astate.recovering = True
        try:
            if astate.instance == instance:  # nobody re-resolved yet
                astate.addr = None
                await self._actor_resolve(astate, known_instance=instance)
        finally:
            astate.recovering = False
        await self._actor_pump(astate)

    def kill_actor_async(self, actor_id: str):
        """Non-blocking kill, safe from __del__/GC contexts."""
        async def _kill():
            try:
                await self.head.aio.call("kill_actor", actor_id=actor_id,
                                         no_restart=True)
            except Exception:
                pass

        self._spawn(_kill())

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        self.head.call("kill_actor", actor_id=actor_id, no_restart=no_restart)
        astate = self._actors.get(actor_id)
        if astate is not None:
            astate.dead = True
            astate.death_cause = "killed via ray_tpu.kill"
        self._containers.pop(f"actor:{actor_id}", None)

    # ------------------------------------------------------- task execution

    def _apply_chip_env(self, tpu_chips: Optional[List[int]]) -> None:
        if tpu_chips:
            # the lease's node agent assigned these chips; jax reads
            # TPU_VISIBLE_CHIPS at (lazy) plugin init so tasks sharing a
            # node each see only their own chips (reference:
            # accelerators/tpu.py set_current_process_visible_accelerator_ids)
            os.environ["TPU_VISIBLE_CHIPS"] = ",".join(map(str, tpu_chips))
        elif tpu_chips is not None:
            # an explicit empty assignment (a CPU-task lease on a reused
            # worker) must not leak the previous lease's chips.  None —
            # actor METHOD pushes — leaves the constructor's assignment
            # intact for the actor's lifetime.
            os.environ.pop("TPU_VISIBLE_CHIPS", None)

    def _enqueue_exec(self, spec: Dict[str, Any], conn) -> "asyncio.Future":
        fut = self._loop().create_future()
        self._exec_pending.add(spec.get("tid", ""))
        self._task_queue.put((spec, fut, conn))
        return fut

    async def rpc_push_task(self, spec: Dict[str, Any], instance: int = 0,
                            tpu_chips: Optional[List[int]] = None,
                            _conn=None):
        """Execute a pushed task (worker mode). Runs user code on the exec
        thread; this handler awaits completion and carries the results back
        in the reply (reference: core_worker.proto PushTask)."""
        self._apply_chip_env(tpu_chips)
        return await self._enqueue_exec(spec, _conn)

    async def rpc_push_tasks(self, specs: List[Dict[str, Any]],
                             instance: int = 0,
                             tpu_chips: Optional[List[int]] = None,
                             _conn=None):
        """Batched push: N specs in one frame, executed FIFO (reference:
        the lease connection batching in direct_task_transport).

        Each task's result is pushed back ("batch_result" oneway) the
        moment it completes — NOT withheld until the whole batch is done —
        so the owner's failure accounting behaves exactly like per-task
        pushes: on a mid-batch worker death, finished results were
        already delivered and only the actually-running task is charged
        a retry.  The final reply is a bare completion marker."""
        import asyncio as _aio

        self._apply_chip_env(tpu_chips)
        futs = []
        for spec in specs:
            fut = self._enqueue_exec(spec, _conn)
            if _conn is not None:
                def _send(f, tid=spec.get("tid", "")):
                    self._queue_batch_result(_conn, tid, f.result())
                fut.add_done_callback(_send)
            futs.append(fut)
        await _aio.gather(*futs)
        if _conn is not None:
            # anything still buffered goes out BEFORE the completion
            # reply — the owner may treat the reply as "all results in"
            await self._drain_batch_results(_conn)
        return {"done": len(specs)}

    def _queue_batch_result(self, conn, tid: str, reply: Dict[str, Any]):
        """Micro-batch per-task results: flush when
        dispatch_result_batch_max are buffered or
        dispatch_result_flush_ms after the first, whichever comes first.
        Trivial-task bursts coalesce many results per frame (frames, not
        payload bytes, are what cap small-task throughput); the ms
        ceiling is noise next to any non-trivial task's runtime."""
        key = id(conn)
        ent = self._result_bufs.get(key)
        if ent is None:
            self._result_bufs[key] = (conn, [{"tid": tid, "reply": reply}])
            self._loop().call_later(
                config.dispatch_result_flush_ms / 1000.0,
                self._flush_batch_results, key)
        else:
            ent[1].append({"tid": tid, "reply": reply})
            if len(ent[1]) >= int(config.dispatch_result_batch_max):
                self._flush_batch_results(key)

    def _flush_batch_results(self, key: int) -> None:
        import asyncio as _aio

        ent = self._result_bufs.pop(key, None)
        if ent is None:
            return
        conn, items = ent
        _aio.ensure_future(conn.push("batch_results", {"items": items}))

    async def _drain_batch_results(self, conn) -> None:
        ent = self._result_bufs.pop(id(conn), None)
        if ent is not None:
            try:
                await conn.push("batch_results", {"items": ent[1]})
            except Exception:
                pass

    async def rpc_cancel_task(self, task_id: str, force: bool = False):
        """Owner requests cancellation of a task pushed to this worker
        (reference: core_worker.proto CancelTask; _raylet.pyx raises
        TaskCancelledError in the executing thread).

        Queued-but-unstarted: marked, skipped at dequeue.  Running async
        body: the asyncio task is cancelled.  Running sync body: a
        TaskCancelledError is raised in the exec thread at its next
        bytecode boundary (a body blocked in native code is only
        interruptible with force).  force=True: the whole worker process
        exits — the owner observes the connection drop and maps it to
        TaskCancelledError via its cancelled-task set."""
        if task_id not in self._exec_pending:
            return {"ok": False}  # finished or never here: no-op
        self._cancelled_exec.add(task_id)
        if force and (task_id in self._sync_running
                      or task_id in self._async_running):
            loop = self._loop()
            loop.call_later(0.05, os._exit, 1)  # let the reply flush
            return {"ok": True, "killing": True}
        fut = self._async_running.get(task_id)
        if fut is not None:
            fut.cancel()
            return {"ok": True}
        ident = self._sync_running.get(task_id)
        if ident is not None:
            import ctypes

            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_long(ident), ctypes.py_object(TaskCancelledError))
        return {"ok": True}

    def _maybe_chaos_oom(self, spec: TaskSpec) -> None:
        """Chaos ``worker.oom`` site (fault_injection.py): an allocation
        bomb in the EXECUTING worker — real touched pages, so the
        watchdog's RSS sampling, victim selection, typed receipt, and
        the owner's OOM-budget retry all exercise end to end.  Growth is
        stepped so the watchdog (or, unvirtualized, the host's real
        threshold) catches it mid-climb; the safety valve raises
        MemoryError rather than hang forever if nothing kills us (the
        watchdog disabled or the rule armed without one)."""
        from ray_tpu._private import fault_injection

        chaos = fault_injection.decide(
            "worker.oom",
            # keyed by the task's NAME first: rules can target one
            # function by its qualname without knowing function ids
            key=spec.name or spec.method_name or spec.function_id)
        if chaos is None or chaos.action != "oom":
            return
        # grow to just past the watchdog trigger, then park awaiting the
        # kill: under a virtual node envelope
        # (memory_monitor_node_total_bytes) this worker's RSS alone
        # crosses the threshold, so tests/bench never stress the real
        # host; without one the 4 GiB cap bounds the damage
        total = int(config.memory_monitor_node_total_bytes)
        threshold = float(config.memory_usage_threshold)
        target = min(int(total * threshold) + (64 << 20) if total > 0
                     else 4 << 30, 4 << 30)
        hoard = []
        step = 32 * 1024 * 1024
        while len(hoard) * step < target:
            hoard.append(b"\x01" * step)  # touched pages: real RSS
            time.sleep(0.02)  # let the watchdog sample mid-climb
        deadline = time.time() + 60.0
        while time.time() < deadline:  # the SIGKILL ends this park
            time.sleep(0.25)
        del hoard
        raise MemoryError(
            "chaos worker.oom bomb reached its allocation target but "
            "was never killed — is the memory watchdog disabled?")

    async def rpc_chaos_rules(self, rules: Optional[List] = None,
                              version: Optional[int] = None):
        """Agent-forwarded chaos rule set (fault_injection.py): installs
        the gossiped rules in THIS worker process so worker-side sites
        (worker.oom, rpc.*) fire here, including for workers that were
        already running when the rules were armed."""
        from ray_tpu._private import fault_injection

        if config.chaos_enabled:
            fault_injection.install(rules or [], version)
        return {"ok": True}

    async def rpc_chaos_stall(self, duration_s: float = 1.0):
        """Chaos ``worker.stall`` site (fault_injection.py): busy-hang
        this process's RPC IO loop for ``duration_s``.  Deliberately a
        BLOCKING sleep on the loop — every push reply, stream item, and
        cancel RPC stalls while the process stays alive, which is the
        gray-failure shape (a replica wedged mid-GC) that kill-based
        chaos cannot produce.  Sent by the node agent as a oneway (the
        stalled loop cannot reply until it wakes)."""
        from ray_tpu._private import fault_injection

        fault_injection.sleep_sync(min(float(duration_s), 600.0))
        return {"ok": True}

    async def rpc_exit_worker(self):
        self._task_queue.put(None)

    async def rpc_persist_actor_state(self):
        """Drain hook: flush this worker's actor state via ``__rt_save__``
        right now (the head calls it before migrating the actor off a
        draining node).  {"saved": False} when the actor has no save
        hook or no durable storage is configured — the head then falls
        back to a plain (stateless) restart or a normal death."""
        import asyncio as _aio

        saved = await _aio.get_running_loop().run_in_executor(
            None, self.persist_actor_state)
        return {"saved": bool(saved)}

    def _finish_exec(self, task_id: str) -> None:
        self._cancelled_exec.discard(task_id)
        self._exec_pending.discard(task_id)

    def exec_loop(self):
        """Worker main loop: executes tasks until exit (reference:
        python/ray/_private/workers/default_worker.py main loop).

        TaskCancelledError guards: PyThreadState_SetAsyncExc is
        inherently racy — a cancel aimed at a task that just finished
        can fire here between tasks.  A stale cancellation must not kill
        this thread (the worker would silently stop serving pushes)."""
        while True:
            item = None
            reply = None
            t0 = 0.0
            try:
                item = self._task_queue.get()
                if item is None:
                    # propagate shutdown to any extra concurrency threads
                    for _ in self._exec_threads:
                        self._task_queue.put(None)
                    return
                t0 = time.perf_counter()
                try:
                    reply = self._execute(item[0], item[2])
                except BaseException as e:  # _execute never raises by design
                    reply = self._classify_exec_error(
                        TaskSpec.from_wire(item[0]), e,
                        traceback.format_exc())
                # worker-reported execution time rides every result frame
                # so the owner's dispatch-depth estimator measures actual
                # service time, never the owner-side round trip
                reply["exec_s"] = time.perf_counter() - t0
                self._post_exec_reply(item[1], reply)
            except TaskCancelledError:
                # stale async-exc from an already-finished task fired
                # between tasks (or on the reply-post line): swallow it —
                # and still deliver the computed reply so the owner's
                # push never hangs on a lost future.  If it interrupted
                # this task's bookkeeping before a reply existed, report
                # a RETRYABLE worker fault — the interrupt belonged to a
                # different task, so this one must not read as cancelled
                if item is not None:
                    if reply is None:
                        reply = self._error_reply(
                            TaskSpec.from_wire(item[0]), RayWorkerError(
                                "exec interrupted by stale cancel"), "")
                        reply["retryable"] = True
                    if t0:
                        reply.setdefault(
                            "exec_s", time.perf_counter() - t0)
                    try:
                        self._post_exec_reply(item[1], reply)
                    except Exception:
                        pass
                continue

    def _classify_exec_error(self, spec: TaskSpec, e: BaseException,
                             tb: str) -> Dict[str, Any]:
        """Error reply for an exception that escaped task execution.

        A TaskCancelledError whose task was never actually cancelled here
        is a STALE interrupt: PyThreadState_SetAsyncExc aimed at a task
        that finished between the cancel RPC's liveness check and the
        raise lands at the next bytecode of whatever runs on this thread
        — i.e. inside the NEXT task's user code.  That task was disrupted
        through no fault of its own, so the reply is flagged retryable
        (the owner requeues it) instead of resolving as a cancellation
        of the wrong task."""
        if isinstance(e, TaskCancelledError) \
                and spec.task_id not in self._cancelled_exec:
            reply = self._error_reply(spec, RayWorkerError(
                f"task {spec.name or spec.function_id[:8]!r} was "
                f"interrupted by a stale cancellation aimed at an "
                f"already-finished task"), tb)
            reply["retryable"] = True
            return reply
        return self._error_reply(spec, e, tb)

    def _post_exec_reply(self, fut, reply) -> None:
        self._post_to_loop(self._set_exec_result, fut, reply)

    @staticmethod
    def _set_exec_result(fut, reply) -> None:
        if not fut.done():
            fut.set_result(reply)

    def _start_concurrency_threads(self, n: int):
        """Extra executors for actors with max_concurrency > 1
        (reference: concurrency groups / threaded actors,
        transport/concurrency_group_manager.h)."""
        for i in range(n):
            t = threading.Thread(target=self.exec_loop,
                                 name=f"rt-exec-{i + 1}", daemon=True)
            t.start()
            self._exec_threads.append(t)

    _metrics = None

    @classmethod
    def _get_metrics(cls):
        if cls._metrics is None:
            from ray_tpu._private.metrics import Counter, Histogram

            cls._metrics = {
                "finished": Counter("rt_tasks_finished",
                                    "tasks executed successfully"),
                "failed": Counter("rt_tasks_failed", "tasks that raised"),
                "duration": Histogram("rt_task_duration_seconds",
                                      "task execution wall time"),
            }
        return cls._metrics

    def _execute(self, spec_wire: Dict[str, Any],
                 conn=None) -> Dict[str, Any]:
        """Deadline wrapper around the traced execute: the spec's
        absolute deadline is re-activated on this exec thread (and, via
        the context copy in _run_coroutine, in async task bodies) so
        nested ``.remote()`` submissions and ``get()`` calls inside the
        task inherit the caller's remaining budget — the same
        propagation contract trace context has."""
        dl = spec_wire.get("dl")
        if not dl:
            return self._execute_traced(spec_wire, conn)
        token = deadlines.activate(float(dl))
        try:
            return self._execute_traced(spec_wire, conn)
        finally:
            deadlines.restore(token)

    def _execute_traced(self, spec_wire: Dict[str, Any],
                        conn=None) -> Dict[str, Any]:
        """Tracing wrapper: a sampled submission carries its context in
        the spec; the execute span parents to the caller's submit span,
        and — via the contextvar — any `.remote()` the task body makes
        chains into the same trace (reference: tracing_helper.py
        _inject_tracing_into_function)."""
        ctx = tracing.ctx_from_wire(spec_wire.get("trace"))
        if ctx is None:
            return self._execute_inner(spec_wire, conn)
        if not ctx.sampled:
            # inherit the caller's negative decision: nested submits
            # from the task body must not re-roll sampling
            token = tracing.activate(ctx)
            try:
                return self._execute_inner(spec_wire, conn)
            finally:
                tracing.restore(token)
        span = tracing.start_span(
            "execute " + (spec_wire.get("name")
                          or spec_wire.get("method")
                          or spec_wire.get("fid", "")[:8] or "task"),
            kind=tracing.KIND_SERVER, parent=ctx)
        if span is None:  # tracing disabled in this worker
            return self._execute_inner(spec_wire, conn)
        span.set_attribute("task_id", spec_wire.get("tid", ""))
        token = tracing.activate(span.context())
        try:
            reply = self._execute_inner(spec_wire, conn)
        except BaseException as e:  # pragma: no cover — inner returns
            span.end(error=f"{type(e).__name__}: {e}")
            raise
        finally:
            tracing.restore(token)
        span.end(error=reply.get("error_str", "")
                 if reply.get("error") else "")
        return reply

    def _execute_inner(self, spec_wire: Dict[str, Any],
                       conn=None) -> Dict[str, Any]:
        spec = TaskSpec.from_wire(spec_wire)
        self._exec.task_id = spec.task_id
        self._exec.job_id = spec.job_id
        self._exec.num_returns = spec.num_returns
        if spec.runtime_env:
            # nested tasks/actors submitted from inside this task inherit
            # its (already job-merged, normalized) runtime env — matching
            # the reference's parent-env inheritance.  Safe worker-wide:
            # this worker only ever serves tasks of this env_key.
            self.job_runtime_env = spec.runtime_env
        m = self._get_metrics()
        t0 = time.time()
        self.record_task_event(spec.task_id, "RUNNING", name=spec.name
                               or spec.method_name or spec.function_id[:8],
                               kind=spec.kind, job_id=spec.job_id)
        if spec.task_id in self._cancelled_exec:
            # cancelled while queued behind earlier tasks: never run it
            self.record_task_event(spec.task_id, "FAILED", error="cancelled")
            self._finish_exec(spec.task_id)
            return self._error_reply(
                spec, TaskCancelledError(f"task {spec.task_id[:12]} was "
                                         "cancelled before it started"), "")
        if spec.deadline and time.time() >= spec.deadline:
            # expired while queued in this worker's pipeline (behind
            # earlier tasks): fail fast without running — the owner's
            # sweep likely resolved it already and discards this reply
            deadlines.count_exceeded("queued")
            self.record_task_event(spec.task_id, "FAILED",
                                   error="deadline exceeded")
            self._finish_exec(spec.task_id)
            return self._error_reply(
                spec, DeadlineExceededError(
                    f"task {spec.task_id[:12]} exceeded its deadline "
                    f"before it started", where="queued"), "")
        # registered BEFORE arg materialization so a cancel arriving
        # during a long remote-arg fetch interrupts it (the async exc
        # fires at the fetch loop's next bytecode) instead of being lost
        self._sync_running[spec.task_id] = threading.get_ident()
        try:
            args, kwargs, arg_ref_oids = self._materialize_args(spec)
        except BaseException as e:
            m["failed"].inc()
            self.record_task_event(spec.task_id, "FAILED", error=str(e)[:200])
            # classify BEFORE _finish_exec clears the cancel mark
            reply = self._classify_exec_error(spec, e, traceback.format_exc())
            self._sync_running.pop(spec.task_id, None)
            self._finish_exec(spec.task_id)
            return reply
        if spec.task_id in self._cancelled_exec:
            # cancel landed during materialization, after the first check
            self._sync_running.pop(spec.task_id, None)
            self.record_task_event(spec.task_id, "FAILED", error="cancelled")
            self._finish_exec(spec.task_id)
            return self._error_reply(
                spec, TaskCancelledError(f"task {spec.task_id[:12]} was "
                                         "cancelled before it started"), "")
        try:
            if spec.kind == ACTOR_CREATION_TASK:
                cls = self.functions.fetch(spec.function_id)
                self._actor_instance = cls(*args, **kwargs)
                self._actor_creation_spec = spec
                self._maybe_restore_actor_state(spec)
                if spec.max_concurrency > 1 and not self._exec_threads:
                    self._start_concurrency_threads(spec.max_concurrency - 1)
                self.record_task_event(spec.task_id, "FINISHED")
                return {"results": []}
            if spec.kind == ACTOR_TASK:
                if self._actor_instance is None:
                    raise ActorDiedError("actor instance not initialized")
                if spec.method_name.startswith("__rt_dag_"):
                    # compiled-DAG system methods (dag/execution.py):
                    # the exec loop PINS this exec thread — it blocks on
                    # its input channels and replays the actor's bound
                    # methods until the graph is torn down.  Flagged to
                    # the agent so the OOM watchdog treats this worker
                    # as a last-resort victim (killing it tears down the
                    # whole graph/pipeline/engine, not one task)
                    from ray_tpu.dag import execution as _dag_exec

                    self._push_worker_flags(pinned=True)
                    try:
                        if spec.method_name == _dag_exec.DAG_INFO_METHOD:
                            value = _dag_exec.collect_node_info(self)
                        elif spec.method_name == _dag_exec.DAG_EXEC_METHOD:
                            value = _dag_exec.run_actor_loop(
                                self, self._actor_instance, *args)
                        elif spec.method_name in (PIPELINE_EXEC_METHOD,
                                                  PIPELINE_CTL_METHOD):
                            # MPMD pipeline stage loop / control ops
                            # (train/pipeline.py): the loop pins this
                            # exec thread for the whole training run,
                            # like the compiled-DAG loop above
                            from ray_tpu.train import pipeline as _pipe

                            if spec.method_name == PIPELINE_EXEC_METHOD:
                                value = _pipe.run_stage_loop(
                                    self, self._actor_instance, *args)
                            else:
                                value = _pipe.run_stage_ctl(
                                    self, self._actor_instance, *args)
                        elif spec.method_name == LLM_EXEC_METHOD:
                            # LLM serving decode loop (serve/llm.py):
                            # pins this exec thread to the replica
                            # engine's continuous-batching step loop
                            from ray_tpu.serve import llm as _serve_llm

                            value = _serve_llm.run_llm_loop(
                                self, self._actor_instance, *args)
                        else:
                            raise AttributeError(
                                f"unknown compiled-DAG system method "
                                f"{spec.method_name!r}")
                    finally:
                        self._push_worker_flags(pinned=False)
                else:
                    self._maybe_chaos_oom(spec)
                    fn = getattr(self._actor_instance, spec.method_name)
                    value = fn(*args, **kwargs)
            else:
                self._maybe_chaos_oom(spec)
                fn = self.functions.fetch(spec.function_id)
                value = fn(*args, **kwargs)
            if spec.num_returns == STREAMING:
                reply = self._stream_out(spec, value, conn)
                failed = bool(reply.get("error"))
                m["failed" if failed else "finished"].inc()
                m["duration"].observe(time.time() - t0)
                self.record_task_event(
                    spec.task_id, "FAILED" if failed else "FINISHED",
                    **({"error": reply.get("error_str", "")[:200]}
                       if failed else {}))
                return reply
            if inspect.iscoroutine(value):
                # async def tasks/actor methods (reference: async actors,
                # _raylet.pyx execute_task coroutine path).  All
                # coroutines share ONE persistent loop (see
                # _run_coroutine); a blocking call inside async code
                # stalls every async call on this worker — same caveat
                # as the reference's async actors.
                value = self._run_coroutine(value)
            if spec.kind == ACTOR_TASK \
                    and not spec.method_name.startswith("__rt_dag_"):
                # snapshot AFTER the method succeeded and BEFORE the
                # caller sees the result: state the reply proves is
                # durable enough to survive a SIGKILL right after
                self._maybe_save_actor_state()
        except BaseException as e:
            m["failed"].inc()
            m["duration"].observe(time.time() - t0)
            self.record_task_event(spec.task_id, "FAILED", error=str(e)[:200])
            # evaluated before the finally clears the cancel mark, so
            # stale-interrupt classification still sees _cancelled_exec
            return self._classify_exec_error(spec, e, traceback.format_exc())
        finally:
            self._sync_running.pop(spec.task_id, None)
            self._finish_exec(spec.task_id)
        m["finished"].inc()
        m["duration"].observe(time.time() - t0)
        self.record_task_event(spec.task_id, "FINISHED")
        try:
            return self._success_reply(spec, value, arg_ref_oids)
        except BaseException as e:
            # an unserializable return value (e.g. a generator returned
            # without num_returns="streaming") must produce an error
            # reply, not kill the exec thread and hang the owner's push
            return self._error_reply(spec, e, traceback.format_exc())

    # ------------------------------------------- stateful actor restarts

    def _actor_state_checkpoint(self, actor_id: str):
        """Snapshot store for this worker's actor (lazy): pickled blobs
        through train/checkpoint.py's storage layer, rooted at
        ``actor_state_storage_path`` (default <session_dir>/actor_state,
        reachable from every node in local clusters; point it at shared
        storage for real multi-host deployments)."""
        if self._actor_state_ckpt is not None:
            return self._actor_state_ckpt
        from ray_tpu.train.checkpoint import ActorStateCheckpoint
        from ray_tpu.train.storage import StorageContext

        root = config.actor_state_storage_path
        if not root:
            session = os.environ.get("RT_SESSION_DIR", "")
            if not session:
                return None  # nowhere durable to put snapshots
            root = os.path.join(session, "actor_state")
        self._actor_state_ckpt = ActorStateCheckpoint(
            StorageContext(root), actor_id,
            keep=int(config.actor_state_keep))
        return self._actor_state_ckpt

    def _maybe_restore_actor_state(self, spec: TaskSpec) -> None:
        """After the constructor ran: if the class opted in
        (``__rt_restore__``) and a previous incarnation of THIS actor id
        saved state, replay it — a killed counter/KV/optimizer actor
        resumes where its last completed call left it, instead of from
        __init__ (RESTARTING → ALIVE with state)."""
        inst = self._actor_instance
        if not hasattr(inst, "__rt_restore__") or not spec.actor_id:
            return
        try:
            ckpt = self._actor_state_checkpoint(spec.actor_id)
            if ckpt is None or not ckpt.has_snapshot():
                return
            state = ckpt.load_latest()
            if state is not None:
                inst.__rt_restore__(state)
        except Exception:
            # a broken restore must not fail the (re)start — the actor
            # comes up fresh, which is what it did before this feature
            traceback.print_exc()

    def persist_actor_state(self) -> bool:
        """Unconditional ``__rt_save__`` snapshot of this worker's actor,
        bypassing the per-method cadence — pinned loops (the MPMD
        pipeline stage loop) call this at optimizer-step boundaries,
        where ``_maybe_save_actor_state``'s after-each-method trigger
        never fires.  Returns False when the actor has no save hook or
        no durable storage root is configured."""
        inst = self._actor_instance
        spec = self._actor_creation_spec
        if inst is None or not hasattr(inst, "__rt_save__") \
                or spec is None or not spec.actor_id:
            return False
        with self._actor_state_save_lock:
            self._push_worker_flags(saving=True)
            try:
                ckpt = self._actor_state_checkpoint(spec.actor_id)
                if ckpt is None:
                    return False
                ckpt.save(inst.__rt_save__())
            finally:
                self._push_worker_flags(saving=False)
        return True

    def _maybe_save_actor_state(self) -> None:
        """After a successful actor method: persist ``__rt_save__()``
        every ``actor_state_save_every_n`` completed calls."""
        inst = self._actor_instance
        if not hasattr(inst, "__rt_save__"):
            return
        spec = self._actor_creation_spec
        if spec is None or not spec.actor_id:
            return
        # cadence bump under a short lock; the (possibly slow) pickle +
        # write serializes on a SEPARATE lock so concurrent methods that
        # don't save this call never queue behind an in-flight snapshot
        with self._actor_state_lock:
            self._actor_calls_since_save += 1
            if self._actor_calls_since_save \
                    < max(1, int(config.actor_state_save_every_n)):
                return
            self._actor_calls_since_save = 0
        with self._actor_state_save_lock:
            # marked mid-save for the OOM watchdog: killing a worker
            # inside __rt_save__ risks a torn/partial snapshot, so the
            # victim policy takes it only as a last resort
            self._push_worker_flags(saving=True)
            try:
                ckpt = self._actor_state_checkpoint(spec.actor_id)
                if ckpt is not None:
                    ckpt.save(inst.__rt_save__())
            except Exception:
                traceback.print_exc()  # snapshot loss, not call failure
            finally:
                self._push_worker_flags(saving=False)

    def _push_worker_flags(self, pinned: Optional[bool] = None,
                           saving: Optional[bool] = None) -> None:
        """Best-effort OOM-policy flags to our node agent (worker mode
        only): pinned-loop and mid-__rt_save__ workers are last-resort
        watchdog victims."""
        if self.mode != MODE_WORKER:
            return
        try:
            self.agent.oneway("worker_flags", worker_id=self.worker_id,
                              pinned=pinned, saving=saving)
        except Exception:
            pass  # the agent may be restarting; flags are advisory

    def _stream_out(self, spec: TaskSpec, value: Any,
                    conn) -> Dict[str, Any]:
        """Drive a streaming generator task: report each yield to the
        owner over the task-push connection as it is produced (reference:
        _raylet.pyx:1104 execute_streaming_generator_sync/async +
        ReportGeneratorItemReturns).  Sync and async generators both
        work; async items are pulled on the shared async-exec loop."""
        import asyncio as _aio

        if hasattr(value, "__anext__"):
            agen = value

            def _items():
                while True:
                    try:
                        yield self._run_coroutine(agen.__anext__())
                    except StopAsyncIteration:
                        return
            items = _items()
        elif hasattr(value, "__next__"):
            items = value
        else:
            return self._error_reply(spec, TypeError(
                "num_returns='streaming' requires the task body to be a "
                f"generator (got {type(value).__name__})"), "")
        tid = TaskID.from_hex(spec.task_id)
        loop = self._loop()
        n = 0
        try:
            for item in items:
                oid = ObjectID.from_index(tid, n + 1).hex()
                with SerializationContext() as ctx:
                    frames, size = serialization.serialize(item)
                if ctx.refs:
                    # items containing ObjectRefs would need the
                    # nested-ref ack/pin protocol per item; unsupported —
                    # fail loudly instead of letting the inner objects be
                    # released while the consumer still holds the refs
                    raise TypeError(
                        "streamed items must not contain ObjectRefs; "
                        "yield values, not references")
                if size <= config.max_direct_call_object_size:
                    blob = bytearray(size)
                    serialization.pack_into(frames, memoryview(blob))
                    wire = {"v": bytes(blob)}
                else:
                    self.plasma.put_serialized(oid, frames, size,
                                               primary=True)
                    wire = {"stored": {"oid": oid,
                                       "node": list(self.agent_addr),
                                       "size": size}}
                if conn is not None:
                    # per-connection coalescing: items buffer locally
                    # and ride ONE "stream_items" frame per flush tick
                    # shared by every stream on this owner connection.
                    # Ordering vs the final reply is preserved by the
                    # flush-now below: the drain lands on the IO loop's
                    # FIFO ahead of the reply post (_post_exec_reply)
                    self._queue_stream_item(conn, {
                        "task_id": spec.task_id, "index": n,
                        "item": wire})
                n += 1
        except BaseException as e:
            if conn is not None:
                self._flush_stream_items_now(conn)
            reply = self._error_reply(spec, e, traceback.format_exc())
            reply["stream_len"] = n  # items before the break stay valid
            return reply
        if conn is not None:
            self._flush_stream_items_now(conn)
        return {"results": [], "stream_len": n}

    _STREAM_FLUSH_S = 0.002  # stream-item coalescing window

    def _queue_stream_item(self, conn, payload: Dict[str, Any]) -> None:
        """Buffer one stream item for its owner connection; the first
        item of a batch schedules the flush tick."""
        key = id(conn)
        with self._stream_out_lock:
            ent = self._stream_out_bufs.get(key)
            if ent is None:
                ent = self._stream_out_bufs[key] = (conn, [])
            ent[1].append(payload)
            first = len(ent[1]) == 1
        if first:
            self._post_to_loop(self._schedule_stream_flush, key)

    def _schedule_stream_flush(self, key: int) -> None:
        # IO loop: delay one tick so concurrent streams' items coalesce
        self._loop().call_later(self._STREAM_FLUSH_S,
                                self._flush_stream_items, key)

    def _flush_stream_items(self, key: int) -> None:
        # IO loop: one frame carries everything buffered for this conn
        import asyncio as _aio

        with self._stream_out_lock:
            ent = self._stream_out_bufs.pop(key, None)
        if ent is None:
            return  # a flush-now already drained it
        conn, items = ent
        _aio.ensure_future(conn.push("stream_items", {"items": items}))

    def _flush_stream_items_now(self, conn) -> None:
        """Drain pending items ahead of this stream's final reply (the
        reply post queues behind this on the same IO-loop FIFO)."""
        self._post_to_loop(self._flush_stream_items, id(conn))

    _async_exec_loop = None
    _async_exec_lock = threading.Lock()

    def _run_coroutine(self, coro):
        """Drive an async task/method on ONE persistent event loop
        shared by every exec thread (reference: async actors run all
        coroutines on a single loop).  That makes loop-bound resources
        (client sessions, asyncio.Lock/Queue) created in one call usable
        in later calls regardless of which exec thread serves them, and
        keeps background asyncio.create_task work running between calls
        — the loop never stops.  Exec threads block on the result, so
        max_concurrency calls still overlap their awaits."""
        with self._async_exec_lock:
            loop = type(self)._async_exec_loop
            if loop is None or loop.is_closed():
                loop = asyncio.new_event_loop()
                type(self)._async_exec_loop = loop
                threading.Thread(target=loop.run_forever,
                                 name="rt-async-exec", daemon=True).start()
        # carry this exec thread's task context into the coroutine: the
        # loop thread's threading.local is empty, which would make put()
        # mint colliding driver-derived ObjectIDs and suppress the
        # blocked-worker notification.  run_coroutine_threadsafe copies
        # the CALLING thread's contextvars into the new asyncio.Task, so
        # the shadow is isolated per call.
        token = _exec_ctx.set(_ExecShadow(self._exec_tls))
        try:
            fut = asyncio.run_coroutine_threadsafe(coro, loop)
        finally:
            _exec_ctx.reset(token)
        # registered for cancellation: cancelling this concurrent future
        # cancels the wrapped asyncio task (reference: async actor task
        # cancel via Task.cancel)
        task_id = self._exec.task_id
        self._async_running[task_id] = fut
        if task_id in self._cancelled_exec:
            # cancel landed between exec registration and here, when the
            # sync path couldn't reach the coroutine — cancel it now so
            # it doesn't run on as an orphan
            fut.cancel()
        try:
            return fut.result()
        finally:
            self._async_running.pop(task_id, None)

    def _materialize_args(self, spec: TaskSpec):
        """Deserialize inline args and batch-fetch ref args, preserving
        positional order."""
        slots: List[Tuple[Optional[str], Any]] = []
        collected: List[ObjectRef] = []
        ref_list: List[ObjectRef] = []
        ref_slots: List[int] = []
        for arg in spec.args:
            if arg.object_id is not None:
                ref = ObjectRef(arg.object_id, owner_addr=arg.owner_addr)
                ref_list.append(ref)
                ref_slots.append(len(slots))
                slots.append((arg.kw, None))
            else:
                with SerializationContext() as ctx:
                    val = serialization.deserialize(arg.value)
                collected.extend(ctx.refs)
                slots.append((arg.kw, val))
        if ref_list:
            values = self.get(ref_list)
            for si, v in zip(ref_slots, values):
                slots[si] = (slots[si][0], v)
            collected.extend(ref_list)
        self._register_foreign_refs(collected)
        args = [v for kw, v in slots if not kw]
        kwargs = {kw: v for kw, v in slots if kw}
        return args, kwargs, {r.oid for r in collected}

    def _success_reply(self, spec: TaskSpec, value: Any,
                       arg_ref_oids: Set[str]) -> Dict[str, Any]:
        if spec.num_returns == 0:
            values = []
        elif spec.num_returns == 1:
            values = [value]
        else:
            values = list(value)
            if len(values) != spec.num_returns:
                return self._error_reply(spec, ValueError(
                    f"task declared num_returns={spec.num_returns} but returned "
                    f"{len(values)} values"), "")
        results = []
        nested: Dict[str, List] = {}
        needs_ack = False
        held = []
        tid = TaskID.from_hex(spec.task_id)
        for i, v in enumerate(values):
            oid = ObjectID.from_index(tid, i + 1).hex()
            with SerializationContext() as ctx:
                frames, size = serialization.serialize(v)
            if ctx.refs:
                nested[oid] = [[r.oid, list(r.owner_addr) if r.owner_addr else None,
                                list(r.node_addr) if r.node_addr else None]
                               for r in ctx.refs]
                needs_ack = True
                held.append((v, list(ctx.refs)))
            if size <= config.max_direct_call_object_size:
                blob = bytearray(size)
                serialization.pack_into(frames, memoryview(blob))
                results.append({"v": bytes(blob)})
            else:
                self.plasma.put_serialized(oid, frames, size, primary=True)
                results.append({"stored": {"oid": oid,
                                           "node": list(self.agent_addr),
                                           "size": size}})
        borrows = [oid for oid in arg_ref_oids if self.rc.count(oid) > 0]
        reply: Dict[str, Any] = {"results": results}
        if borrows:
            reply["borrows"] = borrows
        if nested:
            reply["nested"] = nested
            reply["needs_ack"] = True
            self._pending_acks[spec.task_id] = held
            # this runs on a task-execution thread; asyncio loops only allow
            # timer scheduling from the loop thread itself
            loop = self._loop()
            loop.call_soon_threadsafe(
                loop.call_later, 60.0,
                lambda: self._pending_acks.pop(spec.task_id, None))
        return reply

    def _error_reply(self, spec: TaskSpec, exc: BaseException, tb: str) -> Dict[str, Any]:
        name = spec.name or spec.method_name or spec.function_id[:8]
        # this interpreter build's concurrent.futures.CancelledError is a
        # DISTINCT class from asyncio.CancelledError (verified; upstream
        # they alias) — both appear on the async cancel path
        if isinstance(exc, (TaskCancelledError, asyncio.CancelledError,
                            _futures_cancelled)):
            # cancellation is not a task failure: surface the dedicated
            # type, unwrapped (reference: TaskCancelledError from get)
            blob = cloudpickle.dumps(TaskCancelledError(
                str(exc) or f"task {name!r} was cancelled"))
            n = max(1, spec.num_returns)
            return {"results": [{"err": blob} for _ in range(n)],
                    "error": True, "error_str": "task cancelled"}
        try:
            wrapped = RayTaskError(name, tb, cause=exc)
            blob = cloudpickle.dumps(wrapped)
        except Exception:
            blob = cloudpickle.dumps(RayTaskError(name, tb))
        n = max(1, spec.num_returns)
        return {"results": [{"err": blob} for _ in range(n)],
                "error": True, "error_str": f"{type(exc).__name__}: {exc}"}
