"""Control-plane RPC: length-prefixed msgpack over asyncio TCP.

Equivalent role to the reference's typed gRPC wrappers
(reference: src/ray/rpc/grpc_server.h, grpc_client.h) — every daemon
(control service, node agent, worker) exposes one RPC server; clients are
pooled and retryable.  We use a compact msgpack framing instead of gRPC:
the control plane carries small messages (leases, directory updates,
heartbeats); bulk data rides the object plane, never RPC.

Frame:  [u32 length][msgpack (kind, req_id, method, payload)]
  kind: 0=request, 1=reply, 2=error, 3=oneway
Payload is a msgpack-native structure (dict/list/bytes/str/int/float).

Servers subclass `RpcHost` and define ``async def rpc_<method>(self, **kw)``.
"""

from __future__ import annotations

import asyncio
import itertools
import random
import struct
import threading
from typing import Any, Dict, Iterator, Optional

import msgpack

from ray_tpu._private import fault_injection

_REQUEST, _REPLY, _ERROR, _ONEWAY = 0, 1, 2, 3
_LEN = struct.Struct("<I")
_MAX_FRAME = 1 << 30


def is_loopback(host: Any) -> bool:
    """Shared by the head's driver-callback classification and the
    worker's bind-host pick — ONE definition, so the two sides can
    never drift into classifying the same address differently."""
    h = str(host)
    return h.startswith("127.") or h in ("localhost", "::1")


def backoff_delays(base_s: float = 0.05, cap_s: float = 1.0,
                   rng: Optional[random.Random] = None
                   ) -> Iterator[float]:
    """Reconnect/refusal-retry schedule: exponential backoff with
    jitter, capped.  Each draw is uniform in [ceiling/2, ceiling] with
    the ceiling doubling from ``base_s`` up to ``cap_s`` — so a head
    restart with hundreds of agents/drivers in the retry loop does not
    produce a synchronized dial storm every N ms (every client draws
    its own phase), while the half-ceiling floor keeps the loop from
    hot-spinning against a refused socket.  ``rng`` is injectable so
    the schedule is unit-testable deterministically."""
    draw = (rng or random).uniform
    ceiling = max(1e-6, float(base_s))
    cap_s = max(float(cap_s), ceiling)
    while True:
        yield draw(ceiling / 2.0, ceiling)
        ceiling = min(ceiling * 2.0, cap_s)


class RpcError(Exception):
    """Remote handler raised; message carries the remote traceback string."""


class ConnectionLost(Exception):
    pass


def _pack(kind: int, req_id: int, method: str, payload: Any) -> bytes:
    body = msgpack.packb((kind, req_id, method, payload), use_bin_type=True)
    return _LEN.pack(len(body)) + body


async def _read_frame(reader: asyncio.StreamReader):
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > _MAX_FRAME:
        raise ConnectionLost(f"oversized frame: {length}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


class RpcHost:
    """Base for RPC-serving daemons. Handlers: ``async def rpc_<name>``.

    A host may expose ``rpc_op_loops`` — a ``{method: event_loop}`` map —
    to route specific ops onto OTHER event loops: the server's read loop
    dispatches a routed frame straight onto the owning loop (no hop
    through the serving loop's task queue) and marshals the reply bytes
    back.  This is how the sharded head (head_shards.py) keeps task-event
    and heartbeat ingest off its scheduling loop."""

    rpc_op_loops: Dict[str, asyncio.AbstractEventLoop] = {}

    async def dispatch(self, method: str, payload: Dict[str, Any]) -> Any:
        handler = getattr(self, f"rpc_{method}", None)
        if handler is None:
            raise RpcError(f"{type(self).__name__} has no method {method!r}")
        return await handler(**(payload or {}))

    def on_peer_disconnect(self, peer: "RpcServerConnection") -> None:
        """Override to observe client disconnects (e.g. worker death)."""


class RpcServerConnection:
    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.meta: Dict[str, Any] = {}  # set by register handlers

    async def push(self, method: str, payload: Any) -> None:
        """Server→client oneway push (used for pubsub, task push)."""
        self.writer.write(_pack(_ONEWAY, 0, method, payload))
        await self.writer.drain()


class RpcServer:
    def __init__(self, host_obj: RpcHost, listen_host: str = "127.0.0.1", port: int = 0):
        self._host_obj = host_obj
        self._listen_host = listen_host
        self._port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self.connections: set[RpcServerConnection] = set()
        self._wants_conn_cache: Dict[str, bool] = {}
        # concurrent.futures for handlers routed to foreign loops: a
        # run_coroutine_threadsafe future nothing references can be
        # GC'd mid-flight — retain until done
        self._routed_inflight: set = set()

    @property
    def port(self) -> int:
        return self._port

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._handle_conn, self._listen_host, self._port
        )
        self._port = self._server.sockets[0].getsockname()[1]
        return self._port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for conn in list(self.connections):
                try:
                    conn.writer.close()
                except Exception:
                    pass
            await self._server.wait_closed()

    async def _handle_conn(self, reader, writer):
        conn = RpcServerConnection(writer)
        self.connections.add(conn)
        try:
            while True:
                try:
                    kind, req_id, method, payload = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError,
                        ConnectionLost):
                    break
                except Exception:
                    # malformed frame or msgpack garbage: drop the peer,
                    # never the server — but leave a trace for debugging
                    import traceback

                    traceback.print_exc()
                    break
                if kind in (_ONEWAY, _REQUEST):
                    chaos = fault_injection.decide("rpc.recv", key=method)
                    if chaos is not None:
                        if chaos.action == "sever":
                            break  # connection dies under the peer
                        if chaos.action == "drop":
                            continue  # frame read, never dispatched
                        if chaos.action == "delay":
                            await fault_injection.sleep_async(chaos.delay_s)
                if kind == _ONEWAY:
                    target = self._route_loop(method)
                    if target is not None:
                        self._spawn_routed(
                            self._run_oneway(conn, method, payload), target)
                    else:
                        asyncio.ensure_future(
                            self._run_oneway(conn, method, payload))
                elif kind == _REQUEST:
                    # per-op loop routing: a frame for a shard-owned op
                    # dispatches onto the owning loop straight from the
                    # read loop; the reply marshals back to THIS loop,
                    # which owns the StreamWriter (see _run_request)
                    target = self._route_loop(method)
                    if target is not None:
                        self._spawn_routed(
                            self._run_request(conn, writer, req_id, method,
                                              payload,
                                              origin_loop=
                                              asyncio.get_running_loop()),
                            target)
                    else:
                        asyncio.ensure_future(
                            self._run_request(conn, writer, req_id, method,
                                              payload)
                        )
        finally:
            self.connections.discard(conn)
            try:
                self._host_obj.on_peer_disconnect(conn)
            except Exception:
                pass
            try:
                writer.close()
            except Exception:
                pass

    async def _run_oneway(self, conn, method, payload):
        try:
            payload = dict(payload or {})
            if self._wants_conn(method):
                payload["_conn"] = conn
            await self._host_obj.dispatch(method, payload)
        except Exception:
            import traceback

            traceback.print_exc()

    def _route_loop(self, method: str):
        """The foreign loop that owns this op, or None for the serving
        loop (the empty default map costs one attribute read + ``get``)."""
        # duck-typed hosts (e.g. the serve rpc ingress) may not carry
        # the RpcHost class attribute at all
        op_loops = getattr(self._host_obj, "rpc_op_loops", None)
        if not op_loops:
            return None
        target = op_loops.get(method)
        if target is None:
            return None
        try:
            if target is asyncio.get_running_loop():
                return None
        except RuntimeError:
            pass
        return target

    def _spawn_routed(self, coro, target_loop) -> None:
        fut = asyncio.run_coroutine_threadsafe(coro, target_loop)
        self._routed_inflight.add(fut)
        fut.add_done_callback(self._routed_inflight.discard)

    async def _run_request(self, conn, writer, req_id, method, payload,
                           origin_loop=None):
        try:
            payload = dict(payload or {})
            if self._wants_conn(method):
                payload["_conn"] = conn
            result = await self._host_obj.dispatch(method, payload)
            out = _pack(_REPLY, req_id, method, result)
        except Exception as e:
            import traceback

            out = _pack(_ERROR, req_id, method, f"{e}\n{traceback.format_exc()}")
        if origin_loop is not None \
                and origin_loop is not asyncio.get_running_loop():
            # routed handler: the StreamWriter belongs to the serving
            # loop — marshal the reply bytes back and write/drain there
            try:
                origin_loop.call_soon_threadsafe(
                    self._write_from_origin, writer, out)
            except RuntimeError:
                pass  # serving loop closed mid-flight
            return
        try:
            writer.write(out)
            await writer.drain()
        except (ConnectionResetError, RuntimeError):
            pass

    def _write_from_origin(self, writer, out: bytes) -> None:
        async def _w():
            try:
                writer.write(out)
                await writer.drain()
            except (ConnectionResetError, RuntimeError):
                pass

        asyncio.ensure_future(_w())

    def _wants_conn(self, method: str) -> bool:
        cached = self._wants_conn_cache.get(method)
        if cached is not None:
            return cached
        handler = getattr(self._host_obj, f"rpc_{method}", None)
        code = getattr(handler, "__code__", None) if handler is not None else None
        if code is None:
            result = False
        else:
            nparams = code.co_argcount + code.co_kwonlyargcount
            result = "_conn" in code.co_varnames[:nparams]
        self._wants_conn_cache[method] = result
        return result


class RpcClient:
    """Async client with reconnect-on-demand and push-message callback."""

    def __init__(self, host: str, port: int, on_push=None, label: str = ""):
        self.host, self.port = host, port
        self._on_push = on_push
        self._label = label
        self._reader = None
        self._writer = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._req_ids = itertools.count(1)
        self._read_task = None
        self._lock = asyncio.Lock()
        # True once the connection is unusable (read loop exited or
        # close() called).  Client caches must key replacement on THIS,
        # not on `connected`: a freshly created client is not yet
        # connected, and replacing it mid-connect orphans its read task
        # (GC'd while pending -> "Task was destroyed" spew + fd leak).
        self.dead = False

    @property
    def connected(self) -> bool:
        return self._writer is not None

    async def connect(self) -> None:
        from ray_tpu._private.config import config

        async with self._lock:
            if self._writer is not None:
                return
            self._reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port),
                timeout=config.rpc_connect_timeout_s,
            )
            self.dead = False  # a successful reconnect resurrects
            self._read_task = asyncio.ensure_future(self._read_loop())

    async def close(self) -> None:
        self.dead = True
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        task, self._read_task = self._read_task, None
        if task is not None:
            task.cancel()
            # Run the cancelled read loop to completion now: a Task left
            # pending when the loop stops spews "Task was destroyed but
            # it is pending!" at interpreter teardown.
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

    async def _read_loop(self):
        try:
            while True:
                kind, req_id, method, payload = await _read_frame(self._reader)
                if kind in (_REPLY, _ERROR):
                    fut = self._pending.pop(req_id, None)
                    if fut is not None and not fut.done():
                        if kind == _REPLY:
                            fut.set_result(payload)
                        else:
                            fut.set_exception(RpcError(payload))
                elif kind == _ONEWAY and self._on_push is not None:
                    try:
                        res = self._on_push(method, payload)
                        if asyncio.iscoroutine(res):
                            asyncio.ensure_future(res)
                    except Exception:
                        import traceback

                        traceback.print_exc()
        except (asyncio.IncompleteReadError, ConnectionResetError, asyncio.CancelledError):
            pass
        except Exception:
            import traceback

            traceback.print_exc()
        finally:
            self.dead = True
            self._writer = None
            err = ConnectionLost(f"connection to {self._label or self.host}:{self.port} lost")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()

    async def call(self, method: str, timeout: Optional[float] = None, **payload) -> Any:
        from ray_tpu._private.config import config

        if self._writer is None:
            await self.connect()
        writer = self._writer
        if writer is None:
            raise ConnectionLost(f"connection to {self._label or self.host}:{self.port} lost")
        req_id = next(self._req_ids)
        frame = _pack(_REQUEST, req_id, method, payload)
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            if await self._chaos_send(method):
                writer.write(frame)
                await writer.drain()
            # else: chaos "drop" — frame never hits the wire; the caller
            # times out exactly like a frame lost by the network would
        except ConnectionLost:
            self._pending.pop(req_id, None)
            raise
        except (OSError, RuntimeError, AttributeError) as e:
            self._pending.pop(req_id, None)
            raise ConnectionLost(str(e)) from e
        try:
            return await asyncio.wait_for(
                fut, timeout if timeout is not None else config.rpc_call_timeout_s
            )
        finally:
            self._pending.pop(req_id, None)

    async def _chaos_send(self, method: str) -> bool:
        """rpc.send chaos site.  True = write the frame; False = drop it
        silently (the request then times out, like a frame the network
        lost).  A "sever" decision closes the transport and raises
        ConnectionLost, like a real mid-call connection break."""
        if not fault_injection._rules:
            return True  # disarmed: skip even the key formatting
        chaos = fault_injection.decide(
            "rpc.send", key=f"{self._label}:{method}")
        if chaos is None:
            return True
        if chaos.action == "delay":
            await fault_injection.sleep_async(chaos.delay_s)
            return True
        if chaos.action == "sever":
            writer, self._writer = self._writer, None
            if writer is not None:
                try:
                    writer.close()
                except Exception:
                    pass
            raise ConnectionLost(
                f"chaos: connection to {self._label or self.host}:"
                f"{self.port} severed")
        return False  # drop

    async def oneway(self, method: str, **payload) -> None:
        if self._writer is None:
            await self.connect()
        writer = self._writer
        if writer is None:
            raise ConnectionLost(f"connection to {self._label or self.host}:{self.port} lost")
        try:
            if not await self._chaos_send(method):
                return  # chaos "drop": oneways vanish without a trace
            writer.write(_pack(_ONEWAY, 0, method, payload))
            await writer.drain()
        except (OSError, RuntimeError, AttributeError) as e:
            raise ConnectionLost(str(e)) from e


class EventLoopThread:
    """A dedicated asyncio loop thread.

    Worker/driver processes execute user code on the main thread; all their
    RPC (server + clients) runs here.  Mirrors the role of the reference
    core worker's io_service thread (reference: src/ray/core_worker/
    core_worker_process.h — the boost::asio io context).
    """

    def __init__(self, name: str = "rt-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        """Run coroutine on the loop from a foreign thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro):
        if threading.current_thread() is self._thread:
            # already on the loop: create_task directly —
            # run_coroutine_threadsafe would pay a self-pipe wakeup
            # SYSCALL per call even from the loop thread, and the
            # dispatch pump spawns a push per batch on the hot path
            return self.loop.create_task(coro)
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        async def _drain():
            # Sweep repeatedly: cancellation callbacks may spawn new
            # tasks (ensure_future in push handlers); a task left pending
            # at loop teardown spews "Task was destroyed but it is
            # pending!" when it is later garbage collected.
            for _ in range(3):
                tasks = [t for t in asyncio.all_tasks(self.loop)
                         if t is not asyncio.current_task()]
                if not tasks:
                    break
                for task in tasks:
                    task.cancel()
                # let cancelled tasks run their (possibly awaiting) cleanup
                await asyncio.gather(*tasks, return_exceptions=True)
            self.loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(_drain(), self.loop)
        except RuntimeError:
            pass
        self._thread.join(timeout=5)


class SyncRpcClient:
    """Blocking facade over RpcClient for use from the main thread.

    With ``retry_lost_s`` > 0, calls that fail on connection loss or
    refusal retry until the window closes — this is what lets drivers
    and workers ride out a head restart (reference: gcs_rpc_client.h
    retryable GCS client).  The retry schedule is ``backoff_delays``:
    exponential with jitter, capped — many clients riding out the same
    head restart desynchronize instead of dialing in lockstep.
    """

    def __init__(self, host: str, port: int, io: EventLoopThread, on_push=None,
                 label="", retry_lost_s: float = 0.0):
        self._io = io
        self._client = RpcClient(host, port, on_push=on_push, label=label)
        self._retry_lost_s = retry_lost_s

    @property
    def aio(self) -> RpcClient:
        return self._client

    def call(self, method: str, timeout: Optional[float] = None, **payload) -> Any:
        import time as _time

        from ray_tpu._private.config import config

        # Outer margin over the inner asyncio timeout so a wedged IO loop
        # cannot block the caller forever.
        inner = timeout if timeout is not None else config.rpc_call_timeout_s
        deadline = _time.monotonic() + self._retry_lost_s
        delays = backoff_delays()
        while True:
            try:
                return self._io.run(
                    self._client.call(method, timeout=timeout, **payload),
                    timeout=inner + 30.0,
                )
            except (ConnectionLost, ConnectionRefusedError, OSError,
                    asyncio.TimeoutError):
                if _time.monotonic() >= deadline:
                    raise
                _time.sleep(min(next(delays),
                                max(0.0, deadline - _time.monotonic())))

    def oneway(self, method: str, **payload) -> None:
        from ray_tpu._private.config import config

        self._io.run(
            self._client.oneway(method, **payload),
            timeout=config.rpc_call_timeout_s,
        )

    def close(self):
        try:
            self._io.run(self._client.close(), timeout=5)
        except Exception:
            pass
