"""Live introspection: stack dumps, a sampling profiler, loop-lag probes.

Equivalent role to the reference's reporter/profiling stack
(reference: dashboard/modules/reporter/profile_manager.py:79 — py-spy
dump/record driven over the reporter agent; `ray stack` in
scripts.py:1830) — but in-process: every daemon and worker answers a
``proc_stack``/``profile`` RPC itself via ``sys._current_frames()`` and
a timer-thread sampler, so no external profiler binary or ptrace
capability is needed.

Three pieces:
  - ``capture_stacks()`` / ``format_stacks()``: a point-in-time traceback
    of every thread in this process (the `rtpu stack` payload);
  - ``StackSampler``: an on-demand sampling profiler (configurable hz)
    whose aggregate emits collapsed-stack text (flamegraph.pl input) or
    speedscope-compatible JSON;
  - ``loop_lag_probe()``: an always-on asyncio coroutine measuring event
    -loop scheduling lag, exported as the
    ``ray_tpu_event_loop_lag_seconds{role=...}`` gauge — the first
    number to look at when a head/agent/worker feels wedged.

``IntrospectionRpcMixin`` gives any RpcHost (head, node agent, core
worker) the ``proc_stack`` and ``profile`` RPC surface.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple


# ---------------------------------------------------------------- stack dumps


def capture_stacks() -> List[Dict[str, Any]]:
    """Tracebacks of every live thread, outermost frame first
    (msgpack/json-safe — this is the ``proc_stack`` RPC payload)."""
    threads = {t.ident: t for t in threading.enumerate()}
    me = threading.get_ident()
    out: List[Dict[str, Any]] = []
    for ident, frame in sys._current_frames().items():
        t = threads.get(ident)
        frames = [{"file": fs.filename, "line": fs.lineno or 0,
                   "func": fs.name, "code": (fs.line or "").strip()}
                  for fs in traceback.extract_stack(frame)]
        out.append({
            "thread_id": ident,
            "name": t.name if t is not None else f"thread-{ident}",
            "daemon": bool(t.daemon) if t is not None else True,
            "current": ident == me,  # the dumping (RPC) thread itself
            "frames": frames,
        })
    # stable order: main thread first, then by name
    out.sort(key=lambda s: (s["name"] != "MainThread", s["name"]))
    return out


def format_stacks(stacks: List[Dict[str, Any]], title: str = "") -> str:
    """faulthandler-style text rendering of ``capture_stacks()``."""
    lines: List[str] = []
    if title:
        lines.append(title)
    for s in stacks:
        flags = []
        if s.get("daemon"):
            flags.append("daemon")
        if s.get("current"):
            flags.append("introspection rpc")
        suffix = f" ({', '.join(flags)})" if flags else ""
        lines.append(f"Thread {s['thread_id']} [{s['name']}]{suffix}:")
        for f in s.get("frames") or []:
            lines.append(f"  File \"{f['file']}\", line {f['line']}, "
                         f"in {f['func']}")
            if f.get("code"):
                lines.append(f"    {f['code']}")
        lines.append("")
    return "\n".join(lines)


def proc_stack_payload() -> Dict[str, Any]:
    stacks = capture_stacks()
    return {
        "pid": os.getpid(),
        "argv0": sys.argv[0] if sys.argv else "",
        "threads": stacks,
        "text": format_stacks(stacks, title=f"process {os.getpid()}"),
    }


# ----------------------------------------------------------- sampling profiler


class StackSampler:
    """Timer-thread sampler: every 1/hz seconds snapshot every thread's
    stack via ``sys._current_frames()`` and aggregate counts per unique
    stack (reference role: `py-spy record`, without the dependency —
    the GIL makes the snapshot itself consistent)."""

    def __init__(self, hz: float):
        self.hz = max(1.0, float(hz))
        self.interval = 1.0 / self.hz
        self.started_at = time.time()
        self._t0 = time.monotonic()
        self.duration_s = 0.0
        self.samples = 0  # sampling ticks taken
        # (thread_name, ((file, line, func), ... root->leaf)) -> count
        self._counts: Dict[Tuple[str, Tuple], int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="rt-profiler", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        self.duration_s = time.monotonic() - self._t0

    def _run(self) -> None:
        own = threading.get_ident()
        names: Dict[int, str] = {}
        refresh = 0
        while not self._stop.wait(self.interval):
            if refresh <= 0:  # thread-name map refreshes ~1/s, not per tick
                names = {t.ident: t.name for t in threading.enumerate()}
                refresh = int(self.hz) or 1
            refresh -= 1
            frames = sys._current_frames()
            self.samples += 1
            for ident, frame in frames.items():
                if ident == own:
                    continue
                stack: List[Tuple[str, int, str]] = []
                f = frame
                while f is not None:
                    code = f.f_code
                    stack.append((code.co_filename, f.f_lineno,
                                  code.co_name))
                    f = f.f_back
                stack.reverse()  # root first
                key = (names.get(ident, f"thread-{ident}"), tuple(stack))
                self._counts[key] = self._counts.get(key, 0) + 1

    # ---- output formats ----------------------------------------------------

    @staticmethod
    def _frame_label(file: str, line: int, func: str) -> str:
        return f"{func}@{os.path.basename(file)}:{line}"

    def collapsed(self) -> str:
        """flamegraph.pl-compatible collapsed stacks: semicolon-joined
        frames (thread name as the root frame), space, sample count."""
        lines = []
        for (tname, stack), count in sorted(
                self._counts.items(), key=lambda kv: -kv[1]):
            path = ";".join(
                [tname.replace(";", "_").replace(" ", "_")]
                + [self._frame_label(*fr) for fr in stack])
            lines.append(f"{path} {count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def speedscope(self, name: str = "") -> Dict[str, Any]:
        """speedscope "sampled" profile (https://www.speedscope.app —
        schema per its file-format-schema.json): one profile merging all
        threads, weights in seconds (count * sampling interval)."""
        frame_index: Dict[Tuple[str, int, str], int] = {}
        frames_out: List[Dict[str, Any]] = []

        def idx(fr: Tuple[str, int, str]) -> int:
            i = frame_index.get(fr)
            if i is None:
                i = frame_index[fr] = len(frames_out)
                frames_out.append({"name": fr[2], "file": fr[0],
                                   "line": fr[1]})
            return i

        samples: List[List[int]] = []
        weights: List[float] = []
        total = 0.0
        for (tname, stack), count in self._counts.items():
            chain = [idx((f"[thread {tname}]", 0, f"[thread {tname}]"))]
            chain.extend(idx(fr) for fr in stack)
            samples.append(chain)
            w = count * self.interval
            weights.append(w)
            total += w
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "shared": {"frames": frames_out},
            "profiles": [{
                "type": "sampled",
                "name": name or f"pid {os.getpid()}",
                "unit": "seconds",
                "startValue": 0,
                "endValue": total,
                "samples": samples,
                "weights": weights,
            }],
            "exporter": "ray_tpu-profiler",
        }


# process-singleton sampler handle (one profile at a time per process)
_sampler_lock = threading.Lock()
_active_sampler: Optional[StackSampler] = None


def start_sampler(hz: float = 0) -> Dict[str, Any]:
    from ray_tpu._private.config import config

    global _active_sampler
    with _sampler_lock:
        if _active_sampler is not None:
            return {"ok": False, "error": "profiler already running"}
        s = StackSampler(hz or float(config.profiler_default_hz))
        # start inside the lock: a concurrent stop_sampler() must never
        # observe (and join) a published-but-unstarted thread
        s.start()
        _active_sampler = s
    return {"ok": True, "hz": s.hz, "pid": os.getpid()}


def stop_sampler(fmt: str = "collapsed") -> Dict[str, Any]:
    global _active_sampler
    with _sampler_lock:
        s, _active_sampler = _active_sampler, None
    if s is None:
        return {"ok": False, "error": "no profiler running"}
    s.stop()
    if fmt == "speedscope":
        profile = json.dumps(s.speedscope())
    else:
        fmt = "collapsed"
        profile = s.collapsed()
    return {"ok": True, "format": fmt, "profile": profile,
            "pid": os.getpid(), "hz": s.hz, "samples": s.samples,
            "duration_s": round(s.duration_s, 3)}


def sampler_status() -> Dict[str, Any]:
    with _sampler_lock:
        s = _active_sampler
    if s is None:
        return {"running": False, "pid": os.getpid()}
    return {"running": True, "pid": os.getpid(), "hz": s.hz,
            "samples": s.samples,
            "elapsed_s": round(time.monotonic() - s._t0, 3)}


# ------------------------------------------------------------ loop-lag probes


async def loop_lag_probe(role: str,
                         on_sample: Optional[Callable[[float], None]] = None,
                         tags: Optional[Dict[str, str]] = None
                         ) -> None:
    """Always-on health probe for the calling event loop: sleep a fixed
    interval and measure how late the wakeup lands.  A loop wedged by a
    long callback (accidental sync IO, GIL-hogging deserialization)
    shows up here seconds before anything times out.  Exported as
    ``ray_tpu_event_loop_lag_seconds{role=...}``; ``tags`` adds extra
    labels beside role (the head's ingest shards probe their own loops
    as ``{role=head_shard,shard=...}``); ``on_sample`` lets the host
    also fold the value into heartbeats/time-series."""
    from ray_tpu._private.config import config
    from ray_tpu._private.metrics import loop_lag_gauge

    gauge = loop_lag_gauge()
    gauge_tags = {"role": role, **(tags or {})}
    interval = max(0.05, config.loop_lag_probe_interval_ms / 1000.0)
    loop = asyncio.get_running_loop()
    while True:
        t0 = loop.time()
        await asyncio.sleep(interval)
        lag = max(0.0, loop.time() - t0 - interval)
        try:
            gauge.set(lag, tags=gauge_tags)
            if on_sample is not None:
                on_sample(lag)
        except Exception:
            pass


# ------------------------------------------------------------- RPC surface


class IntrospectionRpcMixin:
    """proc_stack + profile RPCs for any RpcHost-derived daemon.  The
    handlers run on the host's IO/event loop, which stays responsive
    while user code occupies other threads — exactly why the stack of a
    busy worker is still observable."""

    async def rpc_proc_stack(self):
        return proc_stack_payload()

    async def rpc_profile(self, op: str = "run", hz: float = 0,
                          duration_s: float = 2.0, fmt: str = "collapsed"):
        """op="run": start → sleep duration_s → stop, returning the
        profile in one call (the CLI path).  op="start"/"stop"/"status"
        expose the same sampler for long manual sessions."""
        from ray_tpu._private.config import config

        if op == "start":
            return start_sampler(hz)
        if op == "stop":
            return stop_sampler(fmt)
        if op == "status":
            return sampler_status()
        started = start_sampler(hz)
        if not started.get("ok"):
            return started
        try:
            await asyncio.sleep(
                min(float(duration_s), float(config.profiler_max_duration_s)))
        finally:
            result = stop_sampler(fmt)
        return result
