"""Metrics: process-local registry + Prometheus text exposition.

Equivalent of the reference's stats layer
(reference: src/ray/stats/metric.h:102 — OpenCensus measures exported
through the node metrics agent to Prometheus endpoints;
src/ray/stats/metric_defs.cc for the core metric set;
python/ray/util/metrics.py for the user-facing API).

Design: every process owns one MetricsRegistry.  Daemons (head, node
agent) expose theirs over a minimal HTTP endpoint (`GET /metrics`);
workers push periodic snapshots to their node agent, which re-exports
them with worker labels — one scrape target per node, like the
reference's reporter agent (dashboard/modules/reporter/reporter_agent.py).
"""

from __future__ import annotations

import asyncio
import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _labelkey(tags: Optional[Dict[str, str]]) -> _LabelKey:
    return tuple(sorted((tags or {}).items()))


def _fmt_labels(key: _LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 registry: Optional["MetricsRegistry"] = None):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        self._registry = registry or default_registry
        self._registry.register(self)

    def _tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        base = self._registry.default_tags
        return {**base, **(tags or {})} if base else (tags or {})

    def render(self) -> List[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry)
        self._values: Dict[_LabelKey, float] = {}

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        key = _labelkey(self._tags(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def render(self) -> List[str]:
        with self._lock:
            items = list(self._values.items())
        out = [f"# HELP {self.name} {self.description}",
               f"# TYPE {self.name} counter"]
        for key, v in items or [((), 0.0)]:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry)
        self._values: Dict[_LabelKey, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[_labelkey(self._tags(tags))] = float(value)

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        key = _labelkey(self._tags(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def dec(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        self.inc(-value, tags)

    def render(self) -> List[str]:
        with self._lock:
            items = list(self._values.items())
        out = [f"# HELP {self.name} {self.description}",
               f"# TYPE {self.name} gauge"]
        for key, v in items or [((), 0.0)]:
            out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Histogram(_Metric):
    kind = "histogram"

    DEFAULT_BOUNDARIES = [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60]

    def __init__(self, name, description="", boundaries=None, registry=None):
        super().__init__(name, description, registry)
        self.boundaries = list(boundaries or self.DEFAULT_BOUNDARIES)
        self._counts: Dict[_LabelKey, List[int]] = {}
        self._sums: Dict[_LabelKey, float] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _labelkey(self._tags(tags))
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def render(self) -> List[str]:
        with self._lock:
            items = [(k, list(c), self._sums.get(k, 0.0))
                     for k, c in self._counts.items()]
        out = [f"# HELP {self.name} {self.description}",
               f"# TYPE {self.name} histogram"]
        for key, counts, total in items:
            cum = 0
            for b, c in zip(self.boundaries, counts):
                cum += c
                lk = key + (("le", str(b)),)
                out.append(f"{self.name}_bucket{_fmt_labels(lk)} {cum}")
            cum += counts[-1]
            lk = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {cum}")
            out.append(f"{self.name}_count{_fmt_labels(key)} {cum}")
            out.append(f"{self.name}_sum{_fmt_labels(key)} {total}")
        return out


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        # foreign snapshots re-exported verbatim (worker pushes)
        self._foreign: Dict[str, Tuple[str, float]] = {}
        self.foreign_ttl_s = 30.0
        # merged into every sample's tags (e.g. worker_id) so pushed
        # snapshots from many workers don't collide on one endpoint
        self.default_tags: Dict[str, str] = {}
        self._collectors: List[Any] = []  # callables run before render

    def register(self, metric: _Metric) -> None:
        with self._lock:
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def add_collector(self, fn) -> None:
        """fn() runs right before each render — the place to sample
        gauges from live state (store occupancy, queue depths)."""
        with self._lock:
            self._collectors.append(fn)

    def remove_collector(self, fn) -> None:
        """Deregister a collector.  Hosts with a shorter lifetime than
        the process (CoreWorker across init/shutdown cycles, restarted
        serve proxies) MUST remove their collectors — the registry is a
        process singleton, so a leaked closure pins its whole object
        graph and re-runs on every render forever."""
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    def ingest_foreign(self, source: str, text: str) -> None:
        """Store a pushed snapshot (e.g. from a worker) for re-export."""
        with self._lock:
            self._foreign[source] = (text, time.monotonic())

    def render(self) -> str:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass
        with self._lock:
            metrics = list(self._metrics.values())
            now = time.monotonic()
            self._foreign = {s: (t, ts) for s, (t, ts) in
                             self._foreign.items()
                             if now - ts < self.foreign_ttl_s}
            foreign = [t for t, _ in self._foreign.values()]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        for text in foreign:
            lines.extend(text.splitlines())
        return "\n".join(_merge_families(lines)) + "\n"

    def has_samples(self) -> bool:
        with self._lock:
            return bool(self._metrics)

    def foreign_sample_sum(self, name: str) -> Optional[float]:
        """Sum a gauge/counter family's sample values across the pushed
        worker snapshots (None when no pusher reports it).  Cheap line
        scan over the cached exposition texts — how the node agent folds
        worker-process signals (the LLM replica's queue depth and
        tokens-per-step) into its heartbeat gauge summary without a
        side-channel RPC."""
        with self._lock:
            now = time.monotonic()
            texts = [t for t, ts in self._foreign.values()
                     if now - ts < self.foreign_ttl_s]
        total, found = 0.0, False
        for text in texts:
            for ln in text.splitlines():
                if not ln.startswith(name) or ln.startswith("#"):
                    continue
                rest = ln[len(name):]
                if rest[:1] not in ("{", " "):
                    continue  # longer name sharing the prefix
                try:
                    total += float(ln.rsplit(" ", 1)[1])
                    found = True
                except (ValueError, IndexError):
                    pass
        return total if found else None


def _merge_families(lines: List[str]) -> List[str]:
    """Merge exposition lines from several sources into one valid text
    exposition: exactly one HELP/TYPE header per metric family, with all
    of a family's samples contiguous under it.  Needed because every
    worker pushes a snapshot carrying its own headers — Prometheus
    rejects duplicate TYPE lines and interleaved families."""
    order: List[str] = []  # family names in first-seen order
    families: Dict[str, Dict[str, Any]] = {}
    suffix_of: Dict[str, str] = {}  # histogram child name -> family

    def fam(name: str) -> Dict[str, Any]:
        base = suffix_of.get(name, name)
        f = families.get(base)
        if f is None:
            f = families[base] = {"help": None, "type": None, "samples": []}
            order.append(base)
        return f

    for ln in lines:
        if not ln:
            continue
        if ln.startswith("# "):
            parts = ln.split(None, 3)
            if len(parts) < 3:
                continue
            kind, name = parts[1], parts[2]
            f = fam(name)
            if kind == "HELP" and f["help"] is None:
                f["help"] = ln
            elif kind == "TYPE" and f["type"] is None:
                f["type"] = ln
                if len(parts) > 3 and parts[3].startswith("histogram"):
                    for suffix in ("_bucket", "_count", "_sum"):
                        suffix_of[name + suffix] = name
            continue
        name = ln.split("{", 1)[0].split(" ", 1)[0]
        fam(name)["samples"].append(ln)

    out: List[str] = []
    for base in order:
        f = families[base]
        if f["help"]:
            out.append(f["help"])
        if f["type"]:
            out.append(f["type"])
        out.extend(f["samples"])
    return out


default_registry = MetricsRegistry()

_xfer_metrics: Optional[Tuple[Counter, Histogram]] = None


def object_transfer_metrics() -> Tuple[Counter, Histogram]:
    """Process-singleton bulk-transfer metrics, observed on the PULLING
    node agent once per completed cross-node object transfer:
    ``ray_tpu_object_transfer_bytes_total`` (labeled by plane=bulk|rpc
    and direction=in) and ``ray_tpu_object_transfer_seconds`` (wall time
    per transfer, same labels) — throughput is bytes_total/seconds_sum
    per plane.  Lives here so the agent's registry exports them on the
    standard per-node Prometheus endpoint."""
    global _xfer_metrics
    if _xfer_metrics is None:
        _xfer_metrics = (
            Counter("ray_tpu_object_transfer_bytes_total",
                    "bytes moved between node object stores"),
            Histogram("ray_tpu_object_transfer_seconds",
                      "wall time of one cross-node object transfer",
                      boundaries=[0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                                  0.25, 0.5, 1, 2.5, 5, 10, 30, 60]),
        )
    return _xfer_metrics


_dag_metrics: Optional[Tuple[Histogram, Counter]] = None


def dag_metrics() -> Tuple[Histogram, Counter]:
    """Process-singleton compiled-DAG metrics (dag/execution.py +
    dag/channel.py): ``ray_tpu_dag_execute_latency_seconds`` — wall
    time from ``CompiledGraph.execute()`` to the result landing in
    ``CompiledDAGRef.get()``, observed driver-side — and
    ``ray_tpu_dag_channel_ops_total`` — channel version reads/writes
    plus executes, labeled by op=read|write|execute.  Drivers and actor
    workers each export through the standard worker→node-agent push."""
    global _dag_metrics
    if _dag_metrics is None:
        _dag_metrics = (
            Histogram("ray_tpu_dag_execute_latency_seconds",
                      "compiled-DAG execute-to-result latency",
                      boundaries=[0.0002, 0.0005, 0.001, 0.0025, 0.005,
                                  0.01, 0.025, 0.05, 0.1, 0.25, 1, 5, 30]),
            Counter("ray_tpu_dag_channel_ops_total",
                    "compiled-DAG channel version operations"),
        )
    return _dag_metrics


_loop_lag_gauge: Optional[Gauge] = None


def loop_lag_gauge() -> Gauge:
    """Process-singleton ``ray_tpu_event_loop_lag_seconds``: scheduling
    lag of the process's event loop(s), sampled by the always-on
    profiling.loop_lag_probe and labeled by role (head | agent | driver
    | worker | serve_proxy).  The first gauge to read when something
    feels wedged — a loop hogged by a long callback lags here seconds
    before RPCs time out."""
    global _loop_lag_gauge
    if _loop_lag_gauge is None:
        _loop_lag_gauge = Gauge(
            "ray_tpu_event_loop_lag_seconds",
            "event-loop scheduling lag measured by the liveness probe")
    return _loop_lag_gauge


_pump_depth_gauge: Optional[Gauge] = None


def dispatch_pump_depth_gauge() -> Gauge:
    """Process-singleton ``ray_tpu_dispatch_pump_depth``: tasks sitting
    in this owner's dispatch pump (pending per-class + per-actor queues,
    not yet pushed to a leased worker) — sampled by a collector at
    scrape/push time.  Rising depth with idle cluster CPU is the
    signature of owner-side dispatch being the bottleneck (ROADMAP open
    item 3)."""
    global _pump_depth_gauge
    if _pump_depth_gauge is None:
        _pump_depth_gauge = Gauge(
            "ray_tpu_dispatch_pump_depth",
            "owner-side tasks queued in the dispatch pump")
    return _pump_depth_gauge


_dag_occupancy_gauge: Optional[Gauge] = None


def dag_channel_occupancy_gauge() -> Gauge:
    """Process-singleton ``ray_tpu_dag_channel_occupancy``: versions in
    flight in a compiled-DAG channel ring (writer seq minus the slowest
    reader's cursor), labeled by channel oid prefix.  Occupancy pinned
    at max_in_flight marks the pipeline stage readers can't keep up
    with — the pipeline-bubble signal the MPMD work needs."""
    global _dag_occupancy_gauge
    if _dag_occupancy_gauge is None:
        _dag_occupancy_gauge = Gauge(
            "ray_tpu_dag_channel_occupancy",
            "compiled-DAG channel ring versions in flight")
    return _dag_occupancy_gauge


_serve_inflight_gauge: Optional[Gauge] = None


def serve_proxy_inflight_gauge() -> Gauge:
    """Process-singleton ``ray_tpu_serve_proxy_inflight``: requests
    currently admitted past the Serve proxy's shed gate (serve/http.py),
    sampled by a collector at scrape time.  Tracks how close the proxy
    runs to ``serve_max_inflight_requests``."""
    global _serve_inflight_gauge
    if _serve_inflight_gauge is None:
        _serve_inflight_gauge = Gauge(
            "ray_tpu_serve_proxy_inflight",
            "serve HTTP requests currently in flight past the shed gate")
    return _serve_inflight_gauge


_task_event_dropped: Optional[Counter] = None


def task_events_dropped_counter() -> Counter:
    """Process-singleton ``ray_tpu_task_events_dropped_total``: task
    state-transition records discarded before reaching the head's
    store, labeled ``shard`` with WHERE the loss happened —
    ``shard=owner`` for owner-side buffer overflow
    (``task_events_buffer_size`` before a flush could drain it),
    ``shard=task_events`` for head ingest-inbox overflow
    (``head_inbox_max_frames``).  A nonzero rate means the
    observability plane is lossy — raise the relevant bound or
    investigate a wedged flush/shard; the drop itself is deliberate
    (events must never backpressure the submit hot path)."""
    global _task_event_dropped
    if _task_event_dropped is None:
        _task_event_dropped = Counter(
            "ray_tpu_task_events_dropped_total",
            "task events dropped on buffer or ingest-inbox overflow")
    return _task_event_dropped


_head_inbox_depth: Optional[Gauge] = None


def head_inbox_depth_gauge() -> Gauge:
    """Process-singleton ``ray_tpu_head_inbox_depth``: high-water mark
    of a head ingest shard's inbound queue over the last drain window,
    labeled ``shard`` (``task_events`` = event frames queued before the
    per-tick merge; ``telemetry`` = heartbeat updates queued toward the
    scheduling core).  The saturation early-warning: depth climbing
    toward ``head_inbox_max_frames`` means drops are imminent while
    ``ray_tpu_event_loop_lag_seconds{role=head_shard}`` shows which
    plane is too slow."""
    global _head_inbox_depth
    if _head_inbox_depth is None:
        _head_inbox_depth = Gauge(
            "ray_tpu_head_inbox_depth",
            "head ingest shard inbound-queue high-water mark")
    return _head_inbox_depth


_dispatch_batch_hist: Optional[Histogram] = None


def dispatch_batch_size_histogram() -> Histogram:
    """Process-singleton ``ray_tpu_dispatch_batch_size``: tasks carried
    per owner→worker push frame (1 = the unbatched direct call).  The
    companion gauge to ``ray_tpu_dispatch_pump_depth`` when hunting a
    tasks/s plateau: high pump depth with batch size pinned at 1 means
    the pump is fragmenting — frames, not payload bytes, cap small-task
    throughput."""
    global _dispatch_batch_hist
    if _dispatch_batch_hist is None:
        _dispatch_batch_hist = Histogram(
            "ray_tpu_dispatch_batch_size",
            "tasks per owner-side push_tasks frame",
            boundaries=[1, 2, 4, 8, 16, 32, 64])
    return _dispatch_batch_hist


_pipeline_metrics: Optional[Tuple[Gauge, Counter]] = None


def pipeline_metrics() -> Tuple[Gauge, Counter]:
    """Process-singleton MPMD pipeline instrumentation (driver-side,
    set from per-stage loop reports each optimizer step):
    ``ray_tpu_pipeline_bubble_pct`` — idle share of the step window,
    labeled stage=<i> (that stage's idle %) plus stage=all (the whole
    pipeline's bubble: 1 - Σbusy / (S·wall));
    ``ray_tpu_pipeline_stage_busy_seconds_total`` — cumulative stage
    compute seconds labeled stage + phase=fwd|bwd|opt (bwd includes the
    recompute-forward)."""
    global _pipeline_metrics
    if _pipeline_metrics is None:
        _pipeline_metrics = (
            Gauge("ray_tpu_pipeline_bubble_pct",
                  "pipeline idle percentage per stage and overall"),
            Counter("ray_tpu_pipeline_stage_busy_seconds_total",
                    "cumulative pipeline stage compute seconds by phase"),
        )
    return _pipeline_metrics


_ft_metrics: Optional[Tuple[Counter, Counter, Counter]] = None


def fault_tolerance_metrics() -> Tuple[Counter, Counter, Counter]:
    """Process-singleton fault-tolerance counters:
    ``ray_tpu_actor_restarts_total`` — head-side, one per ALIVE→
    RESTARTING transition (an actor worker/node died with restart budget
    left); ``ray_tpu_object_reconstructions_total`` — owner-side lineage
    reconstruction outcomes, labeled outcome=ok|failed (failed = the
    object is permanently lost after the retry budget); and
    ``ray_tpu_chaos_injections_total`` — one per fault-injection rule
    firing, labeled by site (fault_injection.py)."""
    global _ft_metrics
    if _ft_metrics is None:
        _ft_metrics = (
            Counter("ray_tpu_actor_restarts_total",
                    "actor restarts begun after a worker/node death"),
            Counter("ray_tpu_object_reconstructions_total",
                    "lineage reconstructions of lost objects by outcome"),
            Counter("ray_tpu_chaos_injections_total",
                    "chaos fault-injection rule firings by site"),
        )
    return _ft_metrics


_leaked_bytes_gauge: Optional[Gauge] = None


def object_leaked_bytes_gauge() -> Gauge:
    """Process-singleton ``ray_tpu_object_leaked_bytes``: bytes the
    head's periodic memory scan attributes to leaks, labeled by
    kind=dead_owner|borrowed_ttl|channel_slot (head.py leak tripwires).
    Set on every complete scan, so it returns to 0 once the leak is
    cleaned up.  Alert on dead_owner/channel_slot staying nonzero —
    those are definite leaks.  borrowed_ttl is a SUSPICION signal: a
    borrow older than the TTL is indistinguishable from an actor
    legitimately caching refs for the job's lifetime, so long-running
    workloads keep it nonzero by design (tune object_leak_ttl_s to
    your hold patterns before paging on it)."""
    global _leaked_bytes_gauge
    if _leaked_bytes_gauge is None:
        _leaked_bytes_gauge = Gauge(
            "ray_tpu_object_leaked_bytes",
            "object-store bytes flagged as leaked by the head memory scan")
    return _leaked_bytes_gauge


_scan_partial_gauge: Optional[Gauge] = None


def memory_scan_partial_gauge() -> Gauge:
    """Process-singleton ``ray_tpu_memory_scan_partial``: 1 while the
    head's leak scan sees a partial ownership join (unreachable owner,
    truncated table, gapped driver) — leak values hold their last
    complete reading during that time, so a frozen
    ``ray_tpu_object_leaked_bytes`` is only trustworthy when this is
    0.  Alert on it staying 1."""
    global _scan_partial_gauge
    if _scan_partial_gauge is None:
        _scan_partial_gauge = Gauge(
            "ray_tpu_memory_scan_partial",
            "1 while the head memory scan's ownership join is partial "
            "(leak detection suspended, gauges hold last complete values)")
    return _scan_partial_gauge


_store_breakdown_gauge: Optional[Gauge] = None


def object_store_breakdown_gauge() -> Gauge:
    """Process-singleton ``ray_tpu_object_store_bytes``: the node
    store's byte breakdown, labeled by kind=arena_used|arena_free|
    pinned|spilled|channel|mmap_cache — sampled by an agent collector at
    scrape time from StoreCore.byte_breakdown().  The per-node half of
    `rtpu memory`, exported so dashboards can graph who owns the arena
    without polling the state API."""
    global _store_breakdown_gauge
    if _store_breakdown_gauge is None:
        _store_breakdown_gauge = Gauge(
            "ray_tpu_object_store_bytes",
            "node object-store bytes by kind (arena/pinned/spilled/...)")
    return _store_breakdown_gauge


_memory_pressure_metrics = None


def memory_pressure_metrics() -> Tuple[Counter, Gauge, Gauge]:
    """Process-singleton memory-pressure resilience families (see
    _private/memory_monitor.py + node_agent watchdog + head.py
    quarantine): ``ray_tpu_oom_kills_total`` — agent-side, one per
    watchdog kill, labeled reason=node_pressure|chaos;
    ``ray_tpu_node_memory_pressure`` — the agent's sampled node memory
    usage fraction (the watchdog's own gauge, also gossiped on
    heartbeats for pressure-aware scheduling); and
    ``ray_tpu_quarantined_tasks`` — head-side, the number of task/actor
    classes currently quarantined as poison (fail-fast with
    PoisonedTaskError instead of worker churn)."""
    global _memory_pressure_metrics
    if _memory_pressure_metrics is None:
        _memory_pressure_metrics = (
            Counter("ray_tpu_oom_kills_total",
                    "workers deliberately killed by the node memory "
                    "watchdog, by reason"),
            Gauge("ray_tpu_node_memory_pressure",
                  "sampled node memory usage fraction (watchdog input)"),
            Gauge("ray_tpu_quarantined_tasks",
                  "task/actor classes currently poison-quarantined"),
        )
    return _memory_pressure_metrics


_checksum_failures_counter: Optional[Counter] = None


def object_checksum_failures_counter() -> Counter:
    """Process-singleton ``ray_tpu_object_checksum_failures_total``:
    bulk-pull payloads whose CRC32 did not match the holder's seal-time
    checksum — the pull quarantines that copy (the holder re-verifies
    and drops a genuinely-corrupt secondary) and retries from an
    alternate holder, so a nonzero rate means corruption is being
    CAUGHT, not served."""
    global _checksum_failures_counter
    if _checksum_failures_counter is None:
        _checksum_failures_counter = Counter(
            "ray_tpu_object_checksum_failures_total",
            "object pulls whose payload failed CRC32 verification")
    return _checksum_failures_counter


_autoscaler_metrics = None


def autoscaler_metrics() -> Tuple[Gauge, Counter, Histogram]:
    """Process-singleton autoscaler families (head-side; see
    _private/head.py drain state machine + autoscaler/autoscaler.py):
    ``ray_tpu_autoscaler_nodes`` — node counts by
    state=running|draining|pending_launch (pending_launch comes from the
    autoscaler's status report, the rest from the head node table);
    ``ray_tpu_autoscaler_scale_events_total`` — scale decisions acted
    on, labeled kind=up|down; ``ray_tpu_autoscaler_drain_seconds`` —
    wall time of each graceful drain (lease quiesce + actor migration +
    object re-replication), the latency cost of a scale-down."""
    global _autoscaler_metrics
    if _autoscaler_metrics is None:
        _autoscaler_metrics = (
            Gauge("ray_tpu_autoscaler_nodes",
                  "autoscaler node view by state "
                  "(running|draining|pending_launch)"),
            Counter("ray_tpu_autoscaler_scale_events_total",
                    "autoscaler scale decisions acted on, by kind=up|down"),
            Histogram("ray_tpu_autoscaler_drain_seconds",
                      "graceful node drain duration",
                      boundaries=[0.1, 0.5, 1, 2, 5, 10, 30, 60, 120]),
        )
    return _autoscaler_metrics


_serve_sheds_counter: Optional[Counter] = None


def serve_sheds_counter() -> Counter:
    """Process-singleton ``ray_tpu_serve_sheds_total``: requests turned
    away with 503, labeled reason=proxy (the proxy-wide inflight gate)
    or reason=replica (replica-side admission shed, e.g. an LLM
    engine's full admission queue).  A rising rate is the serve
    autoscaler's SLO-pressure signal — replicas (and, transitively,
    nodes) should be scaling up while this climbs."""
    global _serve_sheds_counter
    if _serve_sheds_counter is None:
        _serve_sheds_counter = Counter(
            "ray_tpu_serve_sheds_total",
            "serve requests shed with 503, by reason=proxy|replica")
    return _serve_sheds_counter


_deadline_counter: Optional[Counter] = None


def deadline_metrics() -> Counter:
    """Process-singleton ``ray_tpu_deadline_exceeded_total``: requests
    /tasks failed because their end-to-end deadline expired, labeled by
    enforcement site — where=queued (failed fast without dispatching:
    owner pump, agent lease queue, or worker task queue), running (the
    owner's deadline sweep cancelled an in-flight task), get (a
    ``get()`` bounded by the ambient budget ran out), admission (the
    LLM engine refused a sequence whose remaining budget cannot cover
    prefill + one decode step).  A rising queued share means work is
    arriving already-doomed — shed earlier; a rising running share
    means budgets are too tight for the service time."""
    global _deadline_counter
    if _deadline_counter is None:
        _deadline_counter = Counter(
            "ray_tpu_deadline_exceeded_total",
            "deadline expiries by enforcement site "
            "(queued|running|get|admission)")
    return _deadline_counter


_serve_tail_metrics: Optional[Tuple[Counter, Counter]] = None


def serve_tail_metrics() -> Tuple[Counter, Counter]:
    """Process-singleton Serve tail-tolerance counters (serve/api.py):
    ``ray_tpu_serve_hedges_total`` — hedged duplicate requests fired
    against a second replica, labeled outcome=won (the hedge's response
    was used; the primary was slow) or lost (the primary finished
    first; the hedge was cancelled).  A high won share marks a gray
    replica the circuit breaker should be evicting.
    ``ray_tpu_serve_circuit_open_total`` — per-replica circuit-breaker
    open transitions (a replica's windowed error/slow score crossed the
    threshold and it was removed from routing until a half-open probe
    re-admits it), labeled by deployment."""
    global _serve_tail_metrics
    if _serve_tail_metrics is None:
        _serve_tail_metrics = (
            Counter("ray_tpu_serve_hedges_total",
                    "hedged serve requests by outcome (won|lost)"),
            Counter("ray_tpu_serve_circuit_open_total",
                    "per-replica circuit breaker open transitions"),
        )
    return _serve_tail_metrics


_serve_request_latency: Optional[Histogram] = None


def serve_request_latency_histogram() -> Histogram:
    """Process-singleton ``ray_tpu_serve_request_latency_seconds``:
    proxy-side ingress latency, observed once per routed HTTP request in
    serve/http.py (socket-in to response-ready, labeled by status code).
    Lives here so the proxy actor's registry exports it through the
    standard worker->node-agent push path."""
    global _serve_request_latency
    if _serve_request_latency is None:
        _serve_request_latency = Histogram(
            "ray_tpu_serve_request_latency_seconds",
            "serve HTTP ingress request latency (proxy-side)",
            boundaries=[0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                        0.25, 0.5, 1, 2.5, 5, 10, 60])
    return _serve_request_latency


_llm_metrics: Optional[Tuple[Counter, Gauge, Gauge, Histogram,
                             Gauge, Gauge, Histogram]] = None


def llm_metrics() -> Tuple[Counter, Gauge, Gauge, Histogram, Gauge, Gauge,
                           Histogram]:
    """Process-singleton LLM serving-tier metrics (serve/llm.py, set by
    the replica engine each decode step):
    ``ray_tpu_llm_tokens_total`` — tokens processed, labeled
    phase=prefill|decode (decode rate IS the serving throughput);
    ``ray_tpu_llm_kv_pages`` — paged KV-cache pages by state=used|free
    (used pinned at capacity + queue depth rising = scale out);
    ``ray_tpu_llm_batch_size`` — decode lanes in the last engine step;
    ``ray_tpu_llm_ttft_seconds`` — submit-to-first-token latency
    (admission queueing + chunked prefill, the serving SLO histogram);
    ``ray_tpu_llm_queue_depth`` — sequences waiting in the admission
    queue; ``ray_tpu_llm_tokens_per_step`` — tokens the last engine
    step processed (prefill chunk + decode lanes);
    ``ray_tpu_llm_decode_step_seconds`` — wall time of one decode
    forward over the batch (the paged-attention kernel's target: step
    time should track USED context, not max context).  The queue/step
    gauges also ride the agent heartbeat into the head time-series ring
    (``rtpu status --watch`` serving-pressure pane)."""
    global _llm_metrics
    if _llm_metrics is None:
        _llm_metrics = (
            Counter("ray_tpu_llm_tokens_total",
                    "LLM tokens processed by phase (prefill|decode)"),
            Gauge("ray_tpu_llm_kv_pages",
                  "paged KV-cache pages by state (used|free)"),
            Gauge("ray_tpu_llm_batch_size",
                  "decode lanes in the last continuous-batching step"),
            Histogram("ray_tpu_llm_ttft_seconds",
                      "LLM time-to-first-token (submit to first emit)",
                      boundaries=[0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                                  0.5, 1, 2.5, 5, 10, 30]),
            Gauge("ray_tpu_llm_queue_depth",
                  "sequences waiting in the LLM admission queue"),
            Gauge("ray_tpu_llm_tokens_per_step",
                  "tokens processed by the last LLM engine step"),
            Histogram("ray_tpu_llm_decode_step_seconds",
                      "wall time of one batched LLM decode forward",
                      boundaries=[0.0005, 0.001, 0.0025, 0.005, 0.01,
                                  0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5]),
        )
    return _llm_metrics


_llm_prefix_metrics: Optional[Tuple[Counter, Counter]] = None


def llm_prefix_metrics() -> Tuple[Counter, Counter]:
    """Process-singleton prefix-sharing / disaggregated-prefill metrics
    (serve/llm.py):
    ``ray_tpu_llm_prefix_hits_total`` — admissions that attached at
    least one shared KV page from the refcounted prefix index, labeled
    kind=page|cow (cow = a mid-page divergence that copy-on-write split
    into a private page); ``ray_tpu_llm_kv_pages_shipped_total`` — KV
    pages exported by prefill replicas / imported by decode replicas
    over the bulk transfer plane, labeled direction=out|in.  The
    shared-page population itself rides the existing
    ``ray_tpu_llm_kv_pages`` gauge as state=shared."""
    global _llm_prefix_metrics
    if _llm_prefix_metrics is None:
        _llm_prefix_metrics = (
            Counter("ray_tpu_llm_prefix_hits_total",
                    "LLM admissions that attached shared prefix KV pages "
                    "(kind=page|cow)"),
            Counter("ray_tpu_llm_kv_pages_shipped_total",
                    "KV pages shipped between prefill and decode "
                    "replicas (direction=out|in)"),
        )
    return _llm_prefix_metrics


async def start_metrics_http_server(registry: MetricsRegistry,
                                    host: str = "127.0.0.1",
                                    port: int = 0,
                                    extra_routes=None
                                    ) -> Tuple[asyncio.AbstractServer, int]:
    """Minimal HTTP/1.0 exposition endpoint: `GET /metrics`, plus any
    ``extra_routes`` ({path: () -> (content_type, bytes)}) — the head
    mounts its dashboard page here.  A route key ENDING in "/" is a
    prefix route: its handler is called with the remaining path suffix
    (e.g. "/api/traces/" serves /api/traces/<trace_id>).  A handler
    carrying a truthy ``wants_query`` attribute additionally receives
    the raw query string as its last positional argument, and a handler
    returning a coroutine is awaited on the serving loop (the head's
    /api/stack and /api/profile fan out over RPC).

    Handcrafted on asyncio (no aiohttp in the image); Prometheus needs
    nothing beyond status line + content-type + body."""
    extra_routes = extra_routes or {}

    def _match(path: str):
        """Exact route → (handler, None); prefix route → (handler,
        suffix); no match → (None, None)."""
        h = extra_routes.get(path)
        if h is not None:
            return h, None
        for key, fn in extra_routes.items():
            if len(key) > 1 and key.endswith("/") \
                    and path.startswith(key) and len(path) > len(key):
                return fn, path[len(key):]
        return None, None

    async def handle(reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter):
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=10.0)
            while True:  # drain headers
                line = await asyncio.wait_for(reader.readline(), timeout=10.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request.decode("latin-1").split()
            raw_path = parts[1] if len(parts) >= 2 else "/"
            path, _, query = raw_path.partition("?")
            ctype = b"text/plain; version=0.0.4"
            route, suffix = _match(path)
            if route is not None:
                try:
                    args = [] if suffix is None else [suffix]
                    if getattr(route, "wants_query", False):
                        args.append(query)
                    res = route(*args)
                    if asyncio.iscoroutine(res):
                        res = await res
                    ct, body = res
                    ctype = ct.encode()
                    status = b"200 OK"
                except Exception as e:  # route bug must not kill serving
                    body = f"error: {e}\n".encode()
                    status = b"500 Internal Server Error"
            elif path in ("/metrics", "/"):
                body = registry.render().encode()
                status = b"200 OK"
            else:
                body = b"not found\n"
                status = b"404 Not Found"
            writer.write(b"HTTP/1.0 " + status +
                         b"\r\nContent-Type: " + ctype +
                         b"\r\nContent-Length: " + str(len(body)).encode() +
                         b"\r\n\r\n" + body)
            await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    server = await asyncio.start_server(handle, host, port)
    bound = server.sockets[0].getsockname()[1]
    return server, bound
