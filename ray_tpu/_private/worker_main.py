"""Worker process entry point.

Equivalent of the reference's default_worker.py
(reference: python/ray/_private/workers/default_worker.py): the node
agent's worker pool forks this executable; it connects back to its agent
and the head, then executes pushed tasks on the main thread until told
to exit.
"""

from __future__ import annotations

import os
import sys


def main():
    head = (os.environ["RT_HEAD_HOST"], int(os.environ["RT_HEAD_PORT"]))
    agent = (os.environ["RT_AGENT_HOST"], int(os.environ["RT_AGENT_PORT"]))
    arena = os.environ["RT_ARENA_PATH"]
    node_id = os.environ["RT_NODE_ID"]
    worker_id = os.environ["RT_WORKER_ID"]

    from ray_tpu._private.ids import JobID
    from ray_tpu._private.worker import CoreWorker, MODE_WORKER, set_global_worker

    # runtime env (reference: default_worker.py applies the env before
    # task execution): extracted package dirs go on sys.path, and the
    # working_dir becomes the process cwd
    for extra in reversed(os.environ.get("RT_PY_MODULES", "").split(os.pathsep)):
        if extra:
            sys.path.insert(0, extra)
    working_dir = os.environ.get("RT_WORKING_DIR")
    if working_dir:
        sys.path.insert(0, working_dir)
        os.chdir(working_dir)

    worker = CoreWorker(MODE_WORKER, head, agent, arena, node_id,
                        worker_id=worker_id, job_id=JobID.nil().hex())
    set_global_worker(worker)
    # chaos rules active when this worker was spawned (the agent stamps
    # them into the env): worker-side sites (worker.oom, rpc.*) fire in
    # THIS process too, not just in daemons.  Later rule changes reach
    # running workers via the agent's chaos_rules forward.
    rules = os.environ.get("RT_CHAOS_RULES")
    if rules:
        import json

        from ray_tpu._private import fault_injection

        try:
            payload = json.loads(rules)
            fault_injection.install(payload.get("rules", []),
                                    payload.get("version"))
        except Exception:
            pass
    reply = worker.agent.call("worker_ready", worker_id=worker_id,
                              port=worker.address[1])
    if not reply.get("ok"):
        sys.stderr.write("agent rejected worker registration\n")
        sys.exit(1)
    # first `import jax` in a task will register the TPU PJRT plugin
    from ray_tpu._private.spawn import install_jax_site_hook

    install_jax_site_hook()
    try:
        worker.exec_loop()
    finally:
        set_global_worker(None)
        worker.shutdown()


if __name__ == "__main__":
    main()
