"""Local + cluster scheduling policy.

Equivalent of the reference's two-level scheduler
(reference: src/ray/raylet/scheduling/cluster_resource_scheduler.h,
local_task_manager.h, policy/hybrid_scheduling_policy.h): the cluster
policy picks a node for a lease request (prefer-local below a
utilization threshold, then top-k random among the best-scoring nodes);
the local scheduler grants leases against the node's available resources
in FIFO-with-resources order.

TPU note: TPU chips are ordinary resources here; slice gang placement is
layered on via placement groups whose bundles carry TPU resources, so
multi-host slices are all-or-nothing (reference:
python/ray/_private/accelerators/tpu.py TPU-{type}-head resources).
"""

from __future__ import annotations

import random
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ray_tpu._private.resources import NodeResources, ResourceSet


class LocalScheduler:
    """FIFO-with-resources lease granting against one node's resources."""

    def __init__(self, resources: NodeResources):
        self.resources = resources
        # queue of (token, demand); granted via callback to preserve FIFO
        self._queue: Deque[Tuple[object, ResourceSet]] = deque()

    def try_acquire(self, demand: ResourceSet) -> bool:
        """Immediately acquire if available AND nothing older is waiting."""
        if self._queue:
            return False
        return self.resources.acquire(demand)

    def acquire_many(self, demand: ResourceSet, max_n: int) -> int:
        """Acquire up to ``max_n`` copies of ``demand`` immediately
        (FIFO-respecting: nothing while older requests queue).  Returns
        how many were acquired — the grant count of one batched
        request_leases frame (see node_agent.rpc_request_leases)."""
        n = 0
        while n < max_n and self.try_acquire(demand):
            n += 1
        return n

    def enqueue(self, token: object, demand: ResourceSet) -> None:
        self._queue.append((token, demand))

    def cancel(self, token: object) -> Tuple[bool, List[object]]:
        """Remove a queued request. Returns (found, newly-grantable tokens) —
        removing a head-of-line blocker can unblock the queue."""
        for i, (t, _) in enumerate(self._queue):
            if t == token:
                del self._queue[i]
                return True, self.drain()
        return False, []

    def pending_demands(self) -> List[Dict[str, float]]:
        """Resource shapes of queued (unsatisfiable-right-now) requests —
        the per-node half of the autoscaler's demand signal
        (reference: load_metrics.py resource_load_by_shape)."""
        return [d.to_dict() for _, d in self._queue]

    def cancel_all(self) -> List[object]:
        """Drop every queued request; returns their tokens (the caller
        wakes the waiters, who observe the queue's backing pool is gone)."""
        tokens = [t for t, _ in self._queue]
        self._queue.clear()
        return tokens

    def release(self, demand: ResourceSet) -> List[object]:
        """Release resources; returns tokens of newly grantable requests."""
        self.resources.release(demand)
        return self.drain()

    def drain(self) -> List[object]:
        """Grant queued requests in FIFO order while they fit."""
        granted = []
        while self._queue:
            token, demand = self._queue[0]
            if not self.resources.acquire(demand):
                break
            self._queue.popleft()
            granted.append(token)
        return granted

    @property
    def num_queued(self) -> int:
        return len(self._queue)


def pick_node(
    cluster: Dict[str, NodeResources],
    demand: ResourceSet,
    local_node_id: str,
    spread_threshold: float = 0.5,
    top_k_fraction: float = 0.2,
    top_k_absolute: int = 5,
    rng: Optional[random.Random] = None,
    strategy: Optional[Dict[str, object]] = None,
    labels_by_node: Optional[Dict[str, Dict[str, str]]] = None,
    arg_bytes_by_node: Optional[Dict[str, float]] = None,
    locality_min_bytes: int = 0,
    pressure_by_node: Optional[Dict[str, float]] = None,
    pressure_threshold: float = 1.0,
) -> Optional[str]:
    """Hybrid policy: choose the node to send a lease request to.

    0. Locality: with ``arg_bytes_by_node`` (argument bytes already
       resident per node, from the submission's WireArg hints plus the
       head's object directory) a feasible node holding at least
       ``locality_min_bytes`` wins, skipping the transfer entirely —
       the holder with the most bytes that can fit the demand now,
       else the best busy-but-feasible holder (the lease queues there;
       queued demand triggers the warm-lease reclaim push).  Reference:
       locality_aware_lease_policy.cc — "the best node is the one with
       the most object bytes local".  Explicit strategy overrides
       disable this; infeasible holders fall through to the hybrid
       default below.
    1. Local node if it has the resources available and is under the
       spread threshold.
    2. Otherwise a random pick among the top-k least-utilized nodes with
       the resources available.
    3. Otherwise any node where the demand is *feasible* (total resources
       cover it) — the request queues there.
    4. None if infeasible everywhere (caller surfaces a scheduling error).

    ``strategy`` overrides the hybrid default (reference:
    scheduling_strategy.py + policies under raylet/scheduling/policy/):
      {"type": "spread"}                     — least-utilized feasible
        node, no local preference (spread_scheduling_policy.cc)
      {"type": "node_affinity", "node_id", "soft"} — pin to one node;
        hard pins never fall back (node_affinity_scheduling_policy.cc)
      {"type": "node_label", "hard": {k: v}} — restrict to nodes whose
        labels match, then run the default policy
        (node_label_scheduling_policy.cc)

    ``pressure_by_node`` (node memory usage fraction, from the agents'
    watchdog samples riding heartbeats) demotes nodes at/above
    ``pressure_threshold``: while ANY under-pressure node can fit the
    demand, the over-pressure ones are removed from consideration — new
    work stops landing where the OOM watchdog is about to kill.  Hard
    placement constraints (node_affinity, node_label) and the
    no-alternative case still use the full set: a pressured node beats
    no node.
    """
    rng = rng or random
    stype = (strategy or {}).get("type", "")
    if (pressure_by_node and stype in ("", "spread")
            and pressure_threshold < 1.0):
        calm = {nid: nr for nid, nr in cluster.items()
                if pressure_by_node.get(nid, 0.0) < pressure_threshold}
        if calm and any(nr.can_fit(demand) for nr in calm.values()):
            cluster = calm
    if stype == "node_affinity":
        target = strategy.get("node_id", "")
        node = cluster.get(target)
        if node is not None and node.is_feasible(demand):
            return target  # available now or queues there
        if not strategy.get("soft"):
            return None  # hard affinity: never reschedule elsewhere
        # soft: fall through to the default policy
    elif stype == "node_label":
        labels_by_node = labels_by_node or {}
        hard = strategy.get("hard") or {}
        cluster = {
            nid: nr for nid, nr in cluster.items()
            if all(labels_by_node.get(nid, {}).get(k) == v
                   for k, v in hard.items())
        }
        if not cluster:
            return None
    elif stype == "spread":
        available = [(nid, nr) for nid, nr in cluster.items()
                     if nr.can_fit(demand)]
        if available:
            low = min(nr.utilization() for _, nr in available)
            best = [nid for nid, nr in available
                    if nr.utilization() <= low + 1e-9]
            return rng.choice(best)
        feasible = [nid for nid, nr in cluster.items()
                    if nr.is_feasible(demand)]
        if feasible:
            feasible.sort(key=lambda nid: cluster[nid].utilization())
            return feasible[0]
        return None
    if not stype and arg_bytes_by_node and locality_min_bytes > 0:
        # most-bytes-first; ties broken toward the colder node, then a
        # stable id order so repeated submissions don't flap
        holders = sorted(
            ((b, nid) for nid, b in arg_bytes_by_node.items()
             if b >= locality_min_bytes and nid in cluster
             and cluster[nid].is_feasible(demand)),
            key=lambda kv: (-kv[0], cluster[kv[1]].utilization(), kv[1]))
        for _b, nid in holders:
            if cluster[nid].can_fit(demand):
                return nid
        if holders:
            # no holder has free capacity RIGHT NOW, but skipping the
            # transfer usually beats a short queue wait: send the lease
            # to the best holder anyway — queued demand there triggers
            # the warm-lease reclaim push, and the holder's own
            # pick_node pass can still spill the request back if it is
            # genuinely saturated (reference: locality_aware lease
            # policy + retry_at_raylet spillback)
            return holders[0][1]
    local = cluster.get(local_node_id)
    if (local is not None and local.can_fit(demand)
            and local.utilization() < spread_threshold):
        return local_node_id

    available = [(nid, nr) for nid, nr in cluster.items() if nr.can_fit(demand)]
    if available:
        # under-threshold nodes score strictly better than hot ones
        # (reference: hybrid_scheduling_policy.h score buckets); the top-k
        # random pick is only for herd avoidance among the best bucket
        cold = [kv for kv in available if kv[1].utilization() < spread_threshold]
        pool = cold or available
        pool.sort(key=lambda kv: kv[1].utilization())
        # absolute floor is configurable (reference: ray_config_def.h
        # scheduler_top_k_fraction / scheduler_top_k_absolute)
        k = min(len(pool), max(top_k_absolute, int(len(pool) * top_k_fraction)))
        return rng.choice(pool[:k])[0]

    feasible = [nid for nid, nr in cluster.items() if nr.is_feasible(demand)]
    if feasible:
        # queue on the least loaded feasible node
        feasible.sort(key=lambda nid: cluster[nid].utilization())
        return feasible[0]
    return None
