"""Deterministic, seedable fault-injection plane (chaos engineering).

Production traffic means nodes die mid-flight; this module lets tests,
benchmarks and operators *provoke* those failures deterministically
instead of waiting for them (the preemption-tolerance framing of
"Exploring the limits of Concurrency in ML Training on Google TPUs" —
recovery is a throughput concern, so it must be measurable on demand).

Named injection sites, threaded through the layers where real failures
happen:

  ``rpc.send``     client about to write a request/oneway frame
                   (rpc.py) — actions: drop | delay | sever
  ``rpc.recv``     server just read a frame, before dispatch
                   (rpc.py) — actions: drop | delay | sever
  ``xfer.send``    bulk-plane holder about to serve a range request
                   (object_transfer.py) — actions: truncate | corrupt |
                   delay | sever
  ``lease.grant``  a worker-lease grant is being produced (head actor
                   scheduling + node_agent request_lease) — action: delay
  ``worker.kill``  node agent SIGKILLs one of its worker processes
                   (node_agent.py; key = worker_id) — action: kill
  ``worker.stall`` node agent tells a worker to busy-hang its RPC loop
                   for ``delay_s`` seconds (key = worker_id) — action:
                   stall.  The worker stays ALIVE (heartbeats, probes
                   answered late, nothing crashes): the GRAY-failure
                   generator, distinct from kill
  ``worker.oom``   the executing worker, just before running the task
                   body (worker.py; key = function_id) — action: oom.
                   Allocates real touched pages in steps until the
                   node memory watchdog kills it: exercises RSS
                   sampling, victim selection, the typed
                   OutOfMemoryError receipt, and the owner's separate
                   OOM retry budget end to end
  ``agent.kill``   node agent SIGKILLs itself (key = node_id) — action:
                   kill
  ``head.kill``    head service SIGKILLs itself (key = "head") —
                   action: kill.  Exercises the GCS fault-tolerance
                   paths (agent re-register, driver retry window)
                   under `rtpu chaos`.  Like the other kill/stall
                   sites, the rule is evaluated when the rule set is
                   (re-)applied, not per request — ``p``/``at`` index
                   over rule applications, not invocations

Rules are installed process-locally (``install``/``inject``) or cluster-
wide through the head's ``chaos`` RPC (`rtpu chaos inject|schedule|
clear|status`), which applies them on the head and gossips them to every
node agent (push + heartbeat catch-up).  Agents execute kill rules;
everything else fires inline at the site.

Determinism: each rule carries its own ``random.Random(seed)`` and a
per-rule match counter.  A *schedule* (``make_schedule``) derives, from
one seed, explicit per-site invocation indices at which to fire — the
same seed always reproduces the same failure sequence, which is what
makes a chaos run a regression test instead of a dice roll.

Overhead discipline: ``decide()`` is a single module-global list check
when no rules are installed — the plane costs nothing until armed.
Tests inject a clock via ``set_timers`` so delay rules never really
sleep.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

SITES = ("rpc.send", "rpc.recv", "xfer.send", "lease.grant",
         "worker.kill", "worker.stall", "worker.oom", "agent.kill",
         "head.kill")
ACTIONS = ("drop", "delay", "sever", "truncate", "corrupt", "kill",
           "stall", "oom")

_rule_ids = itertools.count(1)


@dataclass
class Decision:
    """What a site should do for this invocation."""

    action: str
    delay_s: float = 0.0
    rule_id: str = ""


@dataclass
class ChaosRule:
    site: str
    action: str
    p: float = 1.0           # firing probability per matching invocation
    # max firings; -1 = unlimited.  PER PROCESS: gossip installs an
    # independent copy of the rule on every agent, each enforcing its
    # own cap — a count=1 worker.kill with no target kills one worker
    # on EVERY node.  Use `target` to scope cluster-wide one-shots.
    count: int = -1
    delay_s: float = 0.05    # used by action == "delay"
    target: str = ""         # substring match against the site key
    seed: Optional[int] = None
    # explicit schedule: fire exactly at these (0-based) per-rule match
    # indices — overrides `p` (seeded schedules compile to this)
    at: Optional[List[int]] = None
    rule_id: str = ""
    fired: int = 0
    matched: int = field(default=0, repr=False)
    _rng: Any = field(default=None, repr=False)
    _at_set: Any = field(default=None, repr=False)

    def __post_init__(self):
        if self.site not in SITES and not self.site.endswith("*"):
            raise ValueError(f"unknown chaos site {self.site!r} "
                             f"(known: {', '.join(SITES)})")
        if self.action not in ACTIONS:
            raise ValueError(f"unknown chaos action {self.action!r} "
                             f"(known: {', '.join(ACTIONS)})")
        if not self.rule_id:
            self.rule_id = f"chaos-{next(_rule_ids)}"
        if self.seed is None:
            from ray_tpu._private.config import config

            self.seed = int(config.chaos_seed)
        self._rng = random.Random(self.seed)
        self._at_set = frozenset(self.at) if self.at is not None else None

    def matches(self, site: str, key: str) -> bool:
        if self.site.endswith("*"):
            if not site.startswith(self.site[:-1]):
                return False
        elif site != self.site:
            return False
        return not self.target or self.target in key

    def roll(self) -> bool:
        """Advance this rule's deterministic sequence by one matching
        invocation; True when the rule fires for it."""
        idx = self.matched
        self.matched += 1
        if self.count >= 0 and self.fired >= self.count:
            return False
        if self._at_set is not None:
            fire = idx in self._at_set
        else:
            # the RNG advances once per MATCH (not per fire) so the
            # decision sequence is a pure function of (seed, match index)
            fire = self._rng.random() < self.p
        if fire:
            self.fired += 1
        return fire

    def to_wire(self) -> Dict[str, Any]:
        return {"site": self.site, "action": self.action, "p": self.p,
                "count": self.count, "delay_s": self.delay_s,
                "target": self.target, "seed": self.seed,
                "at": list(self.at) if self.at is not None else None,
                "rule_id": self.rule_id, "fired": self.fired}

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "ChaosRule":
        return cls(site=d["site"], action=d["action"],
                   p=d.get("p", 1.0), count=d.get("count", -1),
                   delay_s=d.get("delay_s", 0.05),
                   target=d.get("target", ""), seed=d.get("seed"),
                   at=d.get("at"), rule_id=d.get("rule_id", ""))


_lock = threading.Lock()
_rules: List[ChaosRule] = []   # the fast-path gate: empty = plane inert
version = 0                    # bumped on every install/inject/clear

# injectable timers (tests swap these so delay rules never really sleep)
_sleep: Callable[[float], None] = time.sleep


def set_timers(sleep: Optional[Callable[[float], None]] = None) -> None:
    """Test hook: replace the blocking sleeper used by delay decisions
    (the async helper routes through it via the loop's executor-free
    ``asyncio.sleep`` only when the default is in place)."""
    global _sleep
    _sleep = sleep if sleep is not None else time.sleep


def decide(site: str, key: str = "") -> Optional[Decision]:
    """The site entry point.  Returns None (almost always, at the cost
    of one global list check) or the Decision of the first matching rule
    that fires."""
    if not _rules:
        return None
    return _decide_slow(site, key)


def _decide_slow(site: str, key: str) -> Optional[Decision]:
    with _lock:
        for rule in _rules:
            if not rule.matches(site, key):
                continue
            if not rule.roll():
                continue
            _injections_counter().inc(tags={"site": site})
            return Decision(rule.action, rule.delay_s, rule.rule_id)
    return None


def _injections_counter():
    from ray_tpu._private.metrics import fault_tolerance_metrics

    return fault_tolerance_metrics()[2]


def sleep_sync(delay_s: float) -> None:
    """Blocking delay, through the injected clock."""
    _sleep(delay_s)


async def sleep_async(delay_s: float) -> None:
    """Event-loop delay; honors an injected clock (which must then not
    block) so unit tests stay sleep-free."""
    import asyncio

    if _sleep is time.sleep:
        await asyncio.sleep(delay_s)
    else:
        _sleep(delay_s)


def inject(site: str, action: str, **kw) -> Dict[str, Any]:
    """Add one rule to this process; returns its wire form."""
    global version
    from ray_tpu._private.config import config

    if not config.chaos_enabled:
        raise RuntimeError("chaos fault injection is disabled "
                           "(chaos_enabled=False)")
    rule = ChaosRule(site=site, action=action, **kw)
    with _lock:
        _rules.append(rule)
        version += 1
    return rule.to_wire()


def install(rules_wire: Sequence[Dict[str, Any]],
            new_version: Optional[int] = None) -> None:
    """Replace this process's full rule set (gossip application).
    Counters restart from zero — determinism is per-process."""
    global version
    rules = [ChaosRule.from_wire(d) for d in rules_wire]
    with _lock:
        _rules[:] = rules
        version = new_version if new_version is not None else version + 1


def clear() -> None:
    global version
    with _lock:
        _rules.clear()
        version += 1


def status() -> Dict[str, Any]:
    with _lock:
        return {"version": version,
                "rules": [r.to_wire() for r in _rules]}


def fired_counts() -> Dict[str, int]:
    """{rule_id: firings in THIS process} — agents piggyback this on
    heartbeats so `rtpu chaos status` can aggregate cluster-wide."""
    with _lock:
        return {r.rule_id: r.fired for r in _rules if r.fired}


def make_schedule(seed: int, sites: Sequence[str],
                  actions: Optional[Dict[str, str]] = None,
                  events_per_site: int = 3, span: int = 100,
                  delay_s: float = 0.05) -> List[Dict[str, Any]]:
    """Compile one seed into an explicit failure schedule: for each
    site, `events_per_site` distinct invocation indices within
    [0, span) at which the site's action fires.  Pure function of its
    arguments — the same seed reproduces the same failure sequence on
    any process, which is the property the reproducibility test
    asserts."""
    default_action = {"rpc.send": "drop", "rpc.recv": "drop",
                      "xfer.send": "truncate", "lease.grant": "delay",
                      "worker.kill": "kill", "worker.stall": "stall",
                      "worker.oom": "oom", "agent.kill": "kill",
                      "head.kill": "kill"}
    rng = random.Random(seed)
    rules: List[Dict[str, Any]] = []
    for site in sites:
        if site not in SITES:
            raise ValueError(f"unknown chaos site {site!r}")
        n = min(events_per_site, span)
        at = sorted(rng.sample(range(span), n))
        action = (actions or {}).get(site, default_action[site])
        rules.append(ChaosRule(site=site, action=action, at=at,
                               delay_s=delay_s, seed=seed,
                               count=n).to_wire())
    return rules
