"""Fast child-process spawning.

This host's `sitecustomize` registers the TPU PJRT plugin by importing
jax at every interpreter start (~2s).  Control-plane daemons never touch
jax, and workers only need it before their first jax-using task — so all
children are spawned with `-S` (skip site/sitecustomize) plus an explicit
PYTHONPATH carrying the site-packages dirs, and workers import
`sitecustomize` lazily in the background after registering (see
worker_main.py).  This cuts process startup from ~1.9s to ~0.05s, which
is what makes worker-pool scale-up and multi-node tests fast
(reference: worker_pool.h prestart exists for the same reason).
"""

from __future__ import annotations

import os
import site
import sys
from typing import Dict, List, Tuple


# Pre-bound at import time: preexec_fn runs between fork and exec, where
# imports/dlopen may deadlock if another thread held a lock at fork.
_PR_SET_PDEATHSIG = 1
_SIGKILL = 9
try:
    import ctypes as _ctypes

    _libc = _ctypes.CDLL("libc.so.6", use_errno=True)
except Exception:
    _libc = None


def set_pdeathsig():
    """preexec_fn: deliver SIGKILL to the child when its parent dies, so
    killing a node agent takes its workers down with it (real node-death
    semantics for fault-injection tests; Linux only).  Only calls the
    pre-bound libc.prctl — no imports or allocation post-fork."""
    if _libc is not None:
        _libc.prctl(_PR_SET_PDEATHSIG, _SIGKILL)


def fast_python_cmd(module: str, argv: List[str] = ()) -> Tuple[List[str], Dict[str, str]]:
    """Returns (cmd, env_updates) to run `python -m module` without site."""
    paths: List[str] = []
    try:
        paths.extend(site.getsitepackages())
    except Exception:
        pass
    try:
        import ray_tpu

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        paths.append(repo_root)
    except Exception:
        pass
    existing = os.environ.get("PYTHONPATH", "")
    if existing:
        paths.append(existing)
    env = {"PYTHONPATH": os.pathsep.join(dict.fromkeys(paths))}
    return [sys.executable, "-S", "-m", module, *argv], env


def install_jax_site_hook() -> None:
    """Make the first `import jax` trigger sitecustomize (TPU PJRT plugin
    registration) before jax loads.  Workers that never touch jax never
    pay the ~2s registration cost; a fleet of fresh workers importing jax
    eagerly would saturate the host's cores.

    Implemented by wrapping builtins.__import__ rather than a meta-path
    finder: a finder that imports jax as a side effect trips CPython's
    `_find_spec` sys.modules re-check, which re-executes jax/__init__
    into a fresh module and corrupts its deprecation registry.
    __import__ short-circuits on sys.modules, so after sitecustomize has
    fully imported jax the original import proceeds without re-execution.
    """
    import builtins
    import importlib
    import sys

    orig_import = builtins.__import__
    orig_import_module = importlib.import_module

    def _maybe_load_site(name: str) -> None:
        if (name == "jax" or name.startswith("jax.")) and "jax" not in sys.modules:
            builtins.__import__ = orig_import
            importlib.import_module = orig_import_module
            import os

            # an explicit cpu platform (tests' virtual meshes) must not
            # pull in the TPU plugin
            if os.environ.get("JAX_PLATFORMS") != "cpu":
                try:
                    import sitecustomize  # noqa: F401
                except ImportError:
                    pass

    def hooked_import(name, *args, **kwargs):
        _maybe_load_site(name)
        return orig_import(name, *args, **kwargs)

    def hooked_import_module(name, package=None):
        _maybe_load_site(name)
        return orig_import_module(name, package)

    builtins.__import__ = hooked_import
    importlib.import_module = hooked_import_module
