"""Fast child-process spawning.

This host's `sitecustomize` registers the TPU PJRT plugin by importing
jax at every interpreter start (~2s).  Control-plane daemons never touch
jax, and workers only need it before their first jax-using task — so all
children are spawned with `-S` (skip site/sitecustomize) plus an explicit
PYTHONPATH carrying the site-packages dirs, and workers import
`sitecustomize` lazily in the background after registering (see
worker_main.py).  This cuts process startup from ~1.9s to ~0.05s, which
is what makes worker-pool scale-up and multi-node tests fast
(reference: worker_pool.h prestart exists for the same reason).
"""

from __future__ import annotations

import os
import site
import sys
from typing import Dict, List, Tuple


def fast_python_cmd(module: str, argv: List[str] = ()) -> Tuple[List[str], Dict[str, str]]:
    """Returns (cmd, env_updates) to run `python -m module` without site."""
    paths: List[str] = []
    try:
        paths.extend(site.getsitepackages())
    except Exception:
        pass
    try:
        import ray_tpu

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        paths.append(repo_root)
    except Exception:
        pass
    existing = os.environ.get("PYTHONPATH", "")
    if existing:
        paths.append(existing)
    env = {"PYTHONPATH": os.pathsep.join(dict.fromkeys(paths))}
    return [sys.executable, "-S", "-m", module, *argv], env


class _JaxSiteHook:
    """Meta-path hook: the first `import jax` triggers sitecustomize
    (TPU PJRT plugin registration) before jax loads.  Workers that never
    touch jax never pay the ~2s registration cost; a fleet of fresh
    workers importing jax eagerly would saturate the host's cores."""

    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            import sys

            try:
                sys.meta_path.remove(self)
            except ValueError:
                return None
            try:
                import sitecustomize  # noqa: F401
            except ImportError:
                pass
        return None


def install_jax_site_hook() -> None:
    import sys

    sys.meta_path.insert(0, _JaxSiteHook())
