"""Web dashboard: a dependency-free single-page app served by the head.

Plays the role of the reference's React dashboard client
(reference: dashboard/client/src — 21k LoC of React/TS built by webpack;
here ONE JavaScript file served straight from the head's metrics port,
rendering live cluster state from /api/snapshot): overview stat tiles
with sparklines, and tables for nodes, actors, tasks (filterable by
state), placement groups, and jobs, plus a Chrome-trace timeline
download (/api/timeline — open in chrome://tracing or Perfetto).

Design notes (kept deliberately boring): all dynamic text is inserted
via textContent (no innerHTML of cluster-supplied strings — node labels,
actor names and error strings are user input and must not XSS the
operator); sparklines are single-series inline SVG (one hue, no legend
needed — the tile title names the series); state chips pair color WITH
the state text, never color alone.
"""

APP_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<meta name="viewport" content="width=device-width, initial-scale=1">
<style>
:root {
  --surface: #fcfcfb; --ink: #222; --muted: #6b6b68; --line: #e4e4e0;
  --accent: #3987e5; --good: #0ca30c; --warn: #fab219; --crit: #d03b3b;
}
* { box-sizing: border-box; }
body { margin: 0; background: var(--surface); color: var(--ink);
       font: 14px/1.45 system-ui, sans-serif; }
header { display: flex; align-items: baseline; gap: 1em;
         padding: 14px 22px; border-bottom: 1px solid var(--line); }
header h1 { font-size: 17px; margin: 0; }
header .links { margin-left: auto; font-size: 13px; }
header a { color: var(--accent); text-decoration: none; margin-left: 1em; }
main { padding: 18px 22px; max-width: 1200px; }
.tiles { display: flex; gap: 14px; flex-wrap: wrap; margin-bottom: 20px; }
.tile { border: 1px solid var(--line); border-radius: 8px;
        padding: 10px 14px; min-width: 170px; background: #fff; }
.tile .label { color: var(--muted); font-size: 12px; }
.tile .value { font-size: 24px; font-weight: 600; margin: 2px 0 4px; }
nav.tabs { display: flex; gap: 2px; border-bottom: 1px solid var(--line);
           margin-bottom: 12px; }
nav.tabs button { border: none; background: none; padding: 8px 14px;
  font: inherit; color: var(--muted); cursor: pointer;
  border-bottom: 2px solid transparent; }
nav.tabs button.active { color: var(--ink);
  border-bottom-color: var(--accent); }
table { border-collapse: collapse; width: 100%; background: #fff; }
th, td { border: 1px solid var(--line); padding: 5px 10px;
         text-align: left; font-size: 13px; }
th { color: var(--muted); font-weight: 500; }
code { font-size: 12px; }
.chip { display: inline-block; padding: 0 8px; border-radius: 9px;
        font-size: 12px; border: 1px solid var(--line); }
.chip::before { content: "●"; margin-right: 5px; }
.chip.ok::before { color: var(--good); }
.chip.warn::before { color: var(--warn); }
.chip.bad::before { color: var(--crit); }
.chip.idle::before { color: var(--muted); }
select { font: inherit; margin-bottom: 10px; }
.empty { color: var(--muted); padding: 16px 0; }
#error { color: var(--crit); display: none; padding: 8px 0; }
</style></head>
<body>
<header><h1>ray_tpu cluster</h1><span id="updated" class="label"
style="color:var(--muted);font-size:12px"></span>
<span class="links"><a href="/api/snapshot">snapshot</a>
<a href="/api/timeline" download="timeline.json">timeline</a>
<a href="/metrics">metrics</a></span></header>
<main>
<div id="error"></div>
<div class="tiles" id="tiles"></div>
<div id="shards" style="color:var(--muted);font-size:12px;
padding:2px 0 6px"></div>
<nav class="tabs" id="tabs"></nav>
<div id="view"></div>
</main>
<script src="/app.js"></script>
</body></html>
"""

APP_JS = r"""// ray_tpu dashboard app (single file, no build step)
"use strict";
let SNAP = null;
let TSERIES = null;  // /api/timeseries: head + per-agent gauge rings
let MEM = null;      // /api/memory: joined memory/object accounting
let TAB = "nodes";
let TASK_FILTER = "";

const TABS = [
  ["nodes", "Nodes"], ["actors", "Actors"], ["tasks", "Tasks"],
  ["pgs", "Placement groups"], ["jobs", "Jobs"], ["traces", "Traces"],
  ["memory", "Memory"], ["series", "Series"],
];

function el(tag, attrs, ...children) {
  const e = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs || {})) {
    if (k === "class") e.className = v;
    else if (k.startsWith("on")) e.addEventListener(k.slice(2), v);
    else e.setAttribute(k, v);
  }
  for (const c of children) {
    if (c == null) continue;
    e.append(c.nodeType ? c : document.createTextNode(String(c)));
  }
  return e;
}

function chip(state) {
  const good = ["ALIVE", "CREATED", "FINISHED", "SUCCEEDED", "RUNNING"];
  const bad = ["DEAD", "FAILED", "STOPPED"];
  const warn = ["RESTARTING", "PENDING", "SUBMITTED"];
  let cls = "idle";
  if (good.includes(state)) cls = "ok";
  else if (bad.includes(state)) cls = "bad";
  else if (warn.includes(state)) cls = "warn";
  return el("span", {class: "chip " + cls}, state || "?");
}

// single-series sparkline: 2px accent line on a plain surface, no axes
// (the tile label names the series; a legend would be noise)
function sparkline(values) {
  const W = 140, H = 34, P = 2;
  const svg = document.createElementNS("http://www.w3.org/2000/svg", "svg");
  svg.setAttribute("width", W); svg.setAttribute("height", H);
  if (!values || values.length < 2) return svg;
  const lo = Math.min(...values), hi = Math.max(...values);
  const span = (hi - lo) || 1;
  const pts = values.map((v, i) => [
    P + (i * (W - 2 * P)) / (values.length - 1),
    H - P - ((v - lo) * (H - 2 * P)) / span,
  ]);
  const path = document.createElementNS("http://www.w3.org/2000/svg", "path");
  path.setAttribute("d", "M" + pts.map(p =>
    p[0].toFixed(1) + " " + p[1].toFixed(1)).join("L"));
  path.setAttribute("fill", "none");
  path.setAttribute("stroke", "#3987e5");
  path.setAttribute("stroke-width", "2");
  svg.appendChild(path);
  return svg;
}

function tile(label, value, series) {
  return el("div", {class: "tile"},
    el("div", {class: "label"}, label),
    el("div", {class: "value"}, value),
    series ? sparkline(series) : null);
}

function fmtRes(res) {
  const total = res.total || {}, avail = res.available || {};
  return Object.keys(total).sort().filter(k => !k.startsWith("node:"))
    .map(k => `${k}: ${avail[k] ?? 0}/${total[k]}`).join(", ");
}

function table(headers, rows) {
  if (!rows.length) return el("div", {class: "empty"}, "nothing here yet");
  const t = el("table", {},
    el("tr", {}, ...headers.map(h => el("th", {}, h))));
  for (const r of rows) t.appendChild(el("tr", {}, ...r.map(c =>
    c && c.nodeType ? el("td", {}, c) : el("td", {}, c == null ? "" : c))));
  return t;
}

const VIEWS = {
  nodes: s => {
    const t = table(
      ["node", "address", "role", "resources (avail/total)", "labels"],
      s.nodes.map(n => [
        el("code", {}, n.node_id.slice(0, 12)),
        `${n.addr[0]}:${n.addr[1]}`,
        n.is_head_node ? "head" : (n.draining ? chip("DRAINING") : "worker"),
        fmtRes(n.resources || {}),
        JSON.stringify(n.labels || {}),
      ]));
    const a = s.autoscaler || {};
    const rep = a.report || {};
    if (!rep.ts && !(a.draining || []).length) return t;
    const line = el("p", {}, "autoscaler: pending launches " +
      (rep.pending_launches || 0) + " · scale events up=" +
      (rep.scale_up_total || 0) + " down=" + (rep.scale_down_total || 0) +
      (rep.last_decision ? " · " + rep.last_decision : "") +
      ((a.draining || []).length
        ? " · draining " + a.draining.map(n => n.slice(0, 12)).join(", ")
        : ""));
    return el("div", {}, line, t);
  },
  actors: s => table(
    ["id", "name", "state", "node", "restarts left"],
    s.actors.map(a => [
      el("code", {}, (a.actor_id || "").slice(0, 12)),
      a.name || "", chip(a.state),
      el("code", {}, (a.node_id || "").slice(0, 12)),
      a.restarts_left,
    ])),
  tasks: s => {
    const states = [...new Set(s.tasks.map(t => t.state))].sort();
    const sel = el("select", {onchange: e => {
      TASK_FILTER = e.target.value; render();
    }}, el("option", {value: ""}, "all states"),
      ...states.map(st => {
        const o = el("option", {value: st}, st);
        if (st === TASK_FILTER) o.selected = true;
        return o;
      }));
    const rows = s.tasks.filter(
      t => !TASK_FILTER || t.state === TASK_FILTER);
    return el("div", {}, sel, table(
      ["id", "name", "state", "node", "error"],
      rows.map(t => [
        el("code", {}, (t.task_id || "").slice(0, 12)),
        t.name || "", chip(t.state),
        el("code", {}, (t.node_id || "").slice(0, 12)),
        (t.error || "").slice(0, 90),
      ])));
  },
  pgs: s => table(
    ["id", "state", "strategy", "bundles", "placements"],
    s.placement_groups.map(p => [
      el("code", {}, (p.pg_id || "").slice(0, 12)),
      chip(p.state), p.strategy,
      JSON.stringify(p.bundles),
      (p.placements || []).map(
        x => x ? x.node_id.slice(0, 8) : "-").join(", "),
    ])),
  jobs: s => table(
    ["job", "status", "entrypoint", "message"],
    s.jobs.map(j => [
      el("code", {}, j.job_id || ""), chip(j.status),
      j.entrypoint || "", (j.message || "").slice(0, 90),
    ])),
  // recent traces off the head's trace store; the trace id links to
  // the span dump at /api/traces/<id> (same data as `rtpu trace get`)
  traces: s => table(
    ["trace", "root span", "spans", "duration"],
    (s.traces || []).map(t => [
      el("a", {href: "/api/traces/" + t.trace_id},
         el("code", {}, (t.trace_id || "").slice(0, 16))),
      t.root || "", t.num_spans,
      (t.duration_s * 1000).toFixed(1) + " ms",
    ])),
  // joined memory/object accounting (/api/memory): per-node byte
  // breakdowns, top objects with owner + call-site, leak tripwires
  memory: () => {
    if (!MEM) return el("div", {class: "empty"}, "loading memory view…");
    const fb = n => {
      n = n || 0;
      if (n < 1024) return n + "B";
      if (n < 1048576) return (n / 1024).toFixed(1) + "KiB";
      if (n < 1073741824) return (n / 1048576).toFixed(1) + "MiB";
      return (n / 1073741824).toFixed(2) + "GiB";
    };
    const nodes = table(
      ["node", "arena used/cap", "objects", "pinned", "channels",
       "spilled", "mmap cache", "pulls in flight"],
      Object.entries(MEM.nodes || {}).map(([nid, b]) => [
        el("code", {}, nid.slice(0, 12)),
        fb(b.arena_used) + " / " + fb(b.capacity),
        b.num_objects,
        fb(b.pinned_bytes),
        (b.channel_slots || 0) + " (" + fb(b.channel_bytes) + ")",
        fb(b.spilled_bytes) + " (" + (b.spilled_files || 0) + " files)",
        fb(b.mmap_cache_bytes),
        b.inflight_pulls || 0,
      ]));
    const lk = MEM.leaks || {};
    // "DEAD" is only trustworthy on a complete join — a partial view
    // (unreachable worker, truncated table) just means UNKNOWN owner
    const noOwner = lk.partial ? "unknown" : "DEAD";
    const objs = table(
      ["object", "size", "node", "loc", "pins", "owner", "call-site"],
      (MEM.objects || []).map(o => [
        el("code", {}, (o.object_id || "").slice(0, 16)),
        fb(o.size),
        el("code", {}, (o.node_id || "").slice(0, 12)),
        o.location + (o.channel ? " (chan)" : ""),
        o.pins,
        o.owner ? (o.owner.kind + ":" + o.owner.worker_id.slice(0, 8)
                   + " " + (o.owner.name || ""))
                : chip(noOwner),
        el("code", {}, (o.owner && o.owner.call_site) || ""),
      ]));
    const leakRows = []
      .concat((lk.dead_owner || []).map(e =>
        ["dead-owner", e.object_id.slice(0, 16), fb(e.size),
         Math.round(e.age_s) + "s", (e.node_id || "").slice(0, 12)]))
      .concat((lk.borrowed_ttl || []).map(e =>
        ["borrowed>TTL", e.object_id.slice(0, 16), fb(e.size),
         Math.round(e.age_s) + "s", (e.worker_id || "").slice(0, 12)]))
      .concat((lk.channel_slots || []).map(e =>
        ["channel slot", e.object_id.slice(0, 16), fb(e.size),
         Math.round(e.age_s) + "s", (e.node_id || "").slice(0, 12)]));
    const attributed = MEM.store_object_bytes
      ? Math.round(100 * MEM.attributed_bytes / MEM.store_object_bytes)
      : 100;
    return el("div", {},
      el("div", {class: "tiles"},
        tile("store objects", MEM.num_objects || 0),
        tile("payload bytes", fb(MEM.store_object_bytes)),
        tile("attributed to owners", attributed + "%"),
        tile("leaked bytes", fb(lk.leaked_bytes))),
      el("h3", {}, "per-node breakdown"), nodes,
      el("h3", {}, "top objects"), objs,
      el("h3", {}, "leaks" + (lk.partial ? " (partial view)" : "")),
      leakRows.length
        ? table(["kind", "object", "size", "age", "where"], leakRows)
        : el("div", {class: "empty"}, "no leaks flagged"));
  },
  // head time-series ring (/api/timeseries): loop lag and health
  // gauges per node, one sparkline tile per series
  series: () => {
    const rows = (TSERIES && TSERIES.series) || [];
    if (!rows.length) return el("div", {class: "empty"},
                                "no samples yet (first heartbeat pending)");
    return el("div", {class: "tiles"}, ...rows.map(r => {
      const vals = r.points.map(p => p[1]);
      const last = vals.length ? vals[vals.length - 1] : 0;
      const shown = Math.abs(last) < 1 && last !== 0
        ? last.toExponential(2) : String(Math.round(last * 1000) / 1000);
      return tile(`${r.name} @ ${r.node}`, shown, vals);
    }));
  },
};

function render() {
  if (!SNAP) return;
  const s = SNAP;
  const tiles = document.getElementById("tiles");
  tiles.replaceChildren(
    tile("nodes", s.nodes.length, s.series.map(p => p.nodes)),
    tile("CPUs available", s.summary.cpus_avail + " / " + s.summary.cpus_total,
         s.series.map(p => p.cpus_avail)),
    tile("actors alive", s.summary.actors_alive,
         s.series.map(p => p.actors_alive)),
    tile("tasks finished / 10s", s.summary.task_rate,
         s.series.map(p => p.task_rate)),
  );
  // head ingest shard topology + per-loop lag (shards: 0 = single-loop
  // compat mode, every plane rides the scheduling loop)
  const sh = s.shards || {count: 0, planes: {}};
  const shardLine = document.getElementById("shards");
  if (sh.count > 0) {
    const parts = Object.entries(sh.planes).map(([name, p]) =>
      `${name}: ${p.own_thread ? "own loop" : "head loop"}` +
      ` lag ${((p.lag_s || 0) * 1000).toFixed(1)}ms` +
      (p.dropped ? ` dropped ${p.dropped}` : ""));
    shardLine.textContent =
      `head ingest shards: ${sh.count} — ` + parts.join(" · ");
  } else {
    shardLine.textContent =
      "head ingest shards: 0 (single-loop compat)";
  }
  const tabs = document.getElementById("tabs");
  tabs.replaceChildren(...TABS.map(([id, label]) => {
    const counts = {nodes: s.nodes.length, actors: s.actors.length,
                    tasks: s.tasks.length, pgs: s.placement_groups.length,
                    jobs: s.jobs.length, traces: (s.traces || []).length,
                    memory: MEM ? (MEM.num_objects || 0) : 0,
                    series: ((TSERIES && TSERIES.series) || []).length};
    const b = el("button", {class: id === TAB ? "active" : "",
                            onclick: () => {
                              TAB = id;
                              if (id === "memory")
                                refreshMemory(true).then(render);
                              render();
                            }},
                 `${label} (${counts[id]})`);
    return b;
  }));
  document.getElementById("view").replaceChildren(VIEWS[TAB](s));
  document.getElementById("updated").textContent =
    "updated " + new Date().toLocaleTimeString();
}

let MEM_TS = 0;
async function refreshMemory(force) {
  // fetched only while the Memory tab is active, and at most every
  // 10s: the view fans out to every agent + owner, so it must not
  // ride the 2s background poll (force = explicit tab activation)
  if (!force && Date.now() - MEM_TS < 10000) return;
  MEM_TS = Date.now();
  try {
    MEM = await (await fetch("/api/memory")).json();
  } catch (e) { /* memory tab degrades to loading note */ }
}

async function refresh() {
  try {
    const r = await fetch("/api/snapshot");
    SNAP = await r.json();
    try {
      TSERIES = await (await fetch("/api/timeseries")).json();
    } catch (e) { /* series tab degrades to empty */ }
    if (TAB === "memory") await refreshMemory();
    document.getElementById("error").style.display = "none";
    render();
  } catch (e) {
    const box = document.getElementById("error");
    box.textContent = "head unreachable: " + e;
    box.style.display = "block";
  }
}
refresh();
setInterval(refresh, 2000);
"""
