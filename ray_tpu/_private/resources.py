"""Resource sets with fixed-point arithmetic.

Equivalent of the reference's scheduling resource primitives
(reference: src/ray/common/scheduling/resource_set.h, fixed_point.h,
cluster_resource_data.h): quantities are fixed-point integers with 1e-4
granularity so fractional resources (0.1 CPU) add and subtract exactly;
"TPU" is a first-class resource name alongside CPU/GPU/memory, and TPU
pod slices appear as custom resources (reference:
python/ray/_private/accelerators/tpu.py:335-398).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Mapping, Optional

PRECISION = 10_000  # 1e-4 resource granularity, matches reference FixedPoint

# Well-known resource names.
CPU = "CPU"
GPU = "GPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"


def _to_fixed(v: float) -> int:
    return round(v * PRECISION)


def _from_fixed(v: int) -> float:
    return v / PRECISION


class ResourceSet:
    """An immutable-by-convention mapping of resource name -> fixed quantity."""

    __slots__ = ("_q",)

    def __init__(self, quantities: Optional[Mapping[str, float]] = None,
                 _fixed: Optional[Dict[str, int]] = None):
        if _fixed is not None:
            self._q = {k: v for k, v in _fixed.items() if v != 0}
        else:
            self._q = {}
            for k, v in (quantities or {}).items():
                fv = _to_fixed(v)
                if fv < 0:
                    raise ValueError(f"negative resource {k}={v}")
                if fv:
                    self._q[k] = fv

    # ---- accessors -------------------------------------------------------

    def get(self, name: str) -> float:
        return _from_fixed(self._q.get(name, 0))

    def names(self) -> Iterable[str]:
        return self._q.keys()

    def is_empty(self) -> bool:
        return not self._q

    def to_dict(self) -> Dict[str, float]:
        return {k: _from_fixed(v) for k, v in self._q.items()}

    def key(self) -> tuple:
        """Hashable scheduling-class key (reference: SchedulingClass)."""
        return tuple(sorted(self._q.items()))

    # ---- arithmetic ------------------------------------------------------

    def fits(self, other: "ResourceSet") -> bool:
        """True if `other` (a demand) fits within self (availability)."""
        return all(self._q.get(k, 0) >= v for k, v in other._q.items())

    def add(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._q)
        for k, v in other._q.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet(_fixed=out)

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        """Subtract, clamping at zero would hide bugs — raises on underflow."""
        out = dict(self._q)
        for k, v in other._q.items():
            nv = out.get(k, 0) - v
            if nv < 0:
                raise ValueError(f"resource underflow on {k}")
            out[k] = nv
        return ResourceSet(_fixed=out)

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._q == other._q

    def __hash__(self):
        return hash(self.key())

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"


class NodeResources:
    """Mutable per-node accounting: total and available."""

    def __init__(self, total: ResourceSet):
        self.total = total
        self.available = total

    def can_fit(self, demand: ResourceSet) -> bool:
        return self.available.fits(demand)

    def is_feasible(self, demand: ResourceSet) -> bool:
        """Could this node *ever* run the demand (ignores current load)."""
        return self.total.fits(demand)

    def acquire(self, demand: ResourceSet) -> bool:
        if not self.available.fits(demand):
            return False
        self.available = self.available.subtract(demand)
        return True

    def release(self, demand: ResourceSet) -> None:
        merged = self.available.add(demand)
        # guard against double-release drifting above total; rebuild so the
        # no-zero-entries ResourceSet invariant holds
        clamped = {k: min(v, self.total._q.get(k, 0)) for k, v in merged._q.items()}
        self.available = ResourceSet(_fixed=clamped)

    def utilization(self) -> float:
        """Max over resources of used/total; 0 when idle (hybrid policy input)."""
        worst = 0.0
        for k, tot in self.total._q.items():
            if tot <= 0:
                continue
            used = tot - self.available._q.get(k, 0)
            worst = max(worst, used / tot)
        return worst

    def to_dict(self) -> Dict[str, Any]:
        return {"total": self.total.to_dict(), "available": self.available.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NodeResources":
        nr = cls(ResourceSet(d["total"]))
        nr.available = ResourceSet(d["available"])
        return nr
