"""Head service: the cluster control plane (GCS equivalent).

Equivalent role to the reference's GCS server
(reference: src/ray/gcs/gcs_server/gcs_server.h:78 — GcsNodeManager,
GcsActorManager, GcsKvManager, GcsHealthCheckManager, function table via
internal KV).  One process per cluster, all state in memory (the
reference's InMemoryStoreClient mode; Redis persistence is a later
layer).

Services, all over the msgpack RPC plane (rpc.py):
  - node table + resource view aggregation (agents heartbeat; the reply
    carries the cluster resource snapshot so agents can make hybrid
    scheduling/spillback decisions without a second round trip —
    equivalent of the reference's ray_syncer resource broadcast,
    src/ray/common/ray_syncer/ray_syncer.h:88)
  - internal KV (function table lives under "fn:" keys; reference:
    gcs_service.proto:522 InternalKVGcsService)
  - actor directory + lifecycle: creation scheduling, ALIVE publication,
    restart-on-death with max_restarts (reference:
    src/ray/gcs/gcs_server/gcs_actor_manager.h, gcs_actor_scheduler.h)
  - named actors (get_actor), job registration
  - health: connection-drop + heartbeat-age node failure detection
    (reference: gcs_health_check_manager.h:39)
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.config import config
from ray_tpu._private.ids import JobID
from ray_tpu._private.profiling import IntrospectionRpcMixin, loop_lag_probe
from ray_tpu._private.resources import NodeResources, ResourceSet
from ray_tpu._private.rpc import (RpcClient, RpcHost, RpcServer, RpcError,
                                  is_loopback)
from ray_tpu._private.scheduler import pick_node
from ray_tpu._private.task_spec import (ACTOR_CREATION_TASK, ACTOR_TASK,
                                        NORMAL_TASK, TaskSpec)

# Actor states (reference: rpc::ActorTableData::ActorState)
PENDING, ALIVE, RESTARTING, DEAD = "PENDING", "ALIVE", "RESTARTING", "DEAD"

# Placement group states (reference: gcs_placement_group_manager.h)
PG_PENDING, PG_CREATED, PG_REMOVED = "PENDING", "CREATED", "REMOVED"


class _PgEntry:
    __slots__ = ("pg_id", "bundles", "strategy", "state", "placements",
                 "name", "waiters", "failure", "opt_wait_used")

    def __init__(self, pg_id: str, bundles: List[Dict[str, float]],
                 strategy: str, name: str):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.state = PG_PENDING
        self.placements: List[Optional[str]] = [None] * len(bundles)  # node ids
        self.name = name
        self.waiters: List[asyncio.Event] = []
        self.failure = ""
        # an optimistic (totals-based) reservation may head-of-line block
        # a node's lease queue for pg_reserve_wait_ms — each entry gets
        # exactly one such waited attempt; once it times out the
        # unavailability is genuine occupancy, not view staleness, and
        # retries must not keep stalling unrelated tasks
        self.opt_wait_used = False

    def info(self, nodes: Dict[str, "_NodeEntry"]) -> Dict[str, Any]:
        placements = []
        for nid in self.placements:
            node = nodes.get(nid) if nid else None
            placements.append(
                {"node_id": nid, "addr": [node.host, node.port]} if node else None)
        return {"pg_id": self.pg_id, "state": self.state,
                "strategy": self.strategy, "bundles": self.bundles,
                "placements": placements, "failure": self.failure}

    def wake(self):
        for ev in self.waiters:
            ev.set()
        self.waiters.clear()


class _ActorEntry:
    __slots__ = ("actor_id", "spec_wire", "state", "node_id", "worker_id",
                 "addr", "instance", "restarts_left", "name", "waiters",
                 "death_cause", "kill_requested", "sched_gen", "sched_node",
                 "sched_task", "method_num_returns")

    def __init__(self, actor_id: str, spec_wire: Dict[str, Any], name: str,
                 max_restarts: int):
        self.actor_id = actor_id
        self.spec_wire = spec_wire
        self.state = PENDING
        self.kill_requested = False
        self.node_id: str = ""
        self.worker_id: str = ""
        self.addr: Optional[Tuple[str, int]] = None
        self.instance = 0  # bumped on every (re)start
        self.restarts_left = max_restarts  # -1 = infinite
        self.name = name
        self.waiters: List[asyncio.Event] = []
        self.death_cause = ""
        # scheduling ownership: only the coroutine holding the current
        # generation may mutate this actor's state; sched_node/sched_task
        # let node-death tear down an in-flight creation push
        self.sched_gen = 0
        self.sched_node: str = ""
        self.sched_task: Optional[asyncio.Task] = None
        # @method(num_returns=...) annotations, served to get_actor so a
        # handle fetched by name streams the same as the creating handle
        self.method_num_returns: Dict[str, Any] = {}

    def info(self) -> Dict[str, Any]:
        return {
            "actor_id": self.actor_id,
            "state": self.state,
            "addr": list(self.addr) if self.addr else None,
            "worker_id": self.worker_id,
            "node_id": self.node_id,
            "instance": self.instance,
            "name": self.name,
            "death_cause": self.death_cause,
        }

    def wake(self):
        for ev in self.waiters:
            ev.set()
        self.waiters.clear()


class _NodeEntry:
    __slots__ = ("node_id", "host", "port", "arena_path", "resources",
                 "last_heartbeat", "client", "is_head_node",
                 "pending_demands", "labels", "xfer_port", "memory",
                 "draining", "pressure")

    def __init__(self, node_id: str, host: str, port: int, arena_path: str,
                 resources: NodeResources, is_head_node: bool,
                 labels: Optional[Dict[str, str]] = None,
                 xfer_port: int = 0):
        self.node_id = node_id
        self.host = host
        self.port = port
        self.arena_path = arena_path
        self.resources = resources
        self.last_heartbeat = time.monotonic()
        self.client: Optional[RpcClient] = None
        self.is_head_node = is_head_node
        # queued + infeasible lease demands, piggybacked on heartbeats —
        # the autoscaler's scale-up signal (reference: load_metrics.py)
        self.pending_demands: List[Dict[str, float]] = []
        # static key/value labels for NodeLabelSchedulingStrategy
        # (reference: common.proto NodeLabels)
        self.labels: Dict[str, str] = labels or {}
        # bulk object-transfer plane listener (object_transfer.py)
        self.xfer_port = xfer_port
        # latest store byte breakdown off this node's heartbeat — the
        # cheap (no fan-out) half of /api/memory and rtpu summary
        self.memory: Dict[str, Any] = {}
        # latest watchdog-sampled memory usage fraction (heartbeat);
        # rides the cluster view so pick_node demotes pressured nodes
        self.pressure: Optional[float] = None
        # graceful scale-down: a DRAINING node grants no new leases and
        # is excluded from every placement decision; the drain state
        # machine (HeadService._drain_task) owns the flag's lifecycle
        self.draining = False
        # NOTE: object locations live in HeadService.dir (the sharded
        # object directory), no longer per-node snapshot maps here

    def table_entry(self) -> Dict[str, Any]:
        return {
            "node_id": self.node_id,
            "addr": [self.host, self.port],
            "arena_path": self.arena_path,
            "resources": self.resources.to_dict(),
            "is_head_node": self.is_head_node,
            "labels": self.labels,
            "xfer_port": self.xfer_port,
            "draining": self.draining,
        }


class HeadService(IntrospectionRpcMixin, RpcHost):
    def __init__(self, state_path: str = ""):
        self.nodes: Dict[str, _NodeEntry] = {}
        self.kv: Dict[str, bytes] = {}
        self.actors: Dict[str, _ActorEntry] = {}
        self.named_actors: Dict[str, str] = {}  # name -> actor_id
        self.placement_groups: Dict[str, _PgEntry] = {}
        self._next_job_int = 1  # persisted; itertools.count has no peek
        self._server: Optional[RpcServer] = None
        self._health_task: Optional[asyncio.Task] = None
        self._persist_task: Optional[asyncio.Task] = None
        self._node_conns: Dict[Any, str] = {}  # conn -> node_id
        self._cluster_version = 0  # bumped on membership change
        # sharded object directory (object_directory.py): per-oid-hash
        # buckets, each with its own lock + version — heartbeat deltas,
        # location lookups, and mirror gossip on different buckets never
        # serialize on one structure.  The epoch token handshakes full
        # re-sends across head restarts.
        import os as _os

        from ray_tpu._private.object_directory import ShardedObjectDirectory

        self.dir = ShardedObjectDirectory(
            int(config.object_directory_shards),
            epoch=_os.urandom(8).hex())
        self._shutdown = asyncio.Event()
        # general pub/sub: per-channel ring buffer + long-poll waiters
        # (reference: pubsub/publisher.h:307 — typed channels for node
        # events, actor state, errors; here any named channel works)
        self._pubsub: Dict[str, Any] = {}        # channel -> deque[(seq, payload)]
        self._pubsub_seq: Dict[str, int] = {}
        self._pubsub_waiters: Dict[str, List[asyncio.Event]] = {}
        # persistence (reference: gcs/store_client/redis_store_client.h —
        # GCS tables behind a store so the head survives restarts; we
        # snapshot to a local file, atomic tmp+rename)
        self._state_path = state_path
        self._dirty = False
        self.restarted = False  # loaded pre-existing state on boot
        # node types an autoscaler announced it can launch
        self._autoscaler_types: Dict[str, Dict[str, Any]] = {}
        # elastic autoscaling: per-node graceful-drain records
        # (node_id -> {state, phase, ...}; state=draining|drained|failed)
        # plus the autoscaler's latest status report — together they are
        # /api/autoscaler and the `rtpu status` autoscaler pane
        self._drains: Dict[str, Dict[str, Any]] = {}
        self._autoscaler_status: Dict[str, Any] = {}
        # control-plane ingest shards (head_shards.py): the task-event
        # plane owns the task-event store + trace store + sched-latency
        # feed; the telemetry plane owns heartbeat ingest + the time-
        # series ring.  Constructed in start() (the compat topology
        # wraps the running loop); head_ingest_shards=0 keeps every
        # plane on this loop.  The membership snapshot is the core ->
        # shard handshake: republished synchronously with every
        # cluster/chaos/quarantine mutation, read lock-free by the
        # telemetry plane when assembling heartbeat replies.
        from ray_tpu._private.head_shards import VersionedSnapshot

        self.shards = None
        self._ev_plane = None
        self._telem = None
        self._core_queue = None
        self._membership = VersionedSnapshot(payload=None)
        self._core_inbox_gauge = None
        self._metrics_server = None
        self.metrics_port = 0
        # pending-PG replan wakeups: futures resolved whenever cluster
        # resources may have freed (heartbeat showing changed availability,
        # bundle return, node registration) — _schedule_pg waits on these
        # instead of polling with sleep backoff (reference:
        # gcs_placement_group_manager.cc SchedulePendingPlacementGroups,
        # fired on resource-change events from the syncer)
        self._pg_wake_waiters: List[asyncio.Future] = []
        # ditto for PENDING actors parked on "no feasible node": a node
        # registration wakes them immediately instead of them sleeping
        # out a backoff window — without this an autoscaled node can sit
        # idle past the drain timeout before the actor it was launched
        # for even retries (launch/drain churn)
        self._actor_wake_waiters: List[asyncio.Future] = []
        # dashboard sparkline ring: 2s samples, ~5 minutes of history
        from collections import deque as _deque

        self._dash_series = _deque(maxlen=150)
        self._dash_task: Optional[asyncio.Task] = None
        self._head_loop_lag = 0.0
        self._lag_task: Optional[asyncio.Task] = None
        # chaos fault-injection rules (fault_injection.py): the head is
        # the distribution point — rules install here, apply to the
        # head's own sites, and gossip to agents (push + heartbeat
        # catch-up, version-gated like the object directory)
        self._chaos_rules: List[Dict[str, Any]] = []
        self._chaos_version = 0
        # per-node chaos firing counts now live on the telemetry plane
        # (heartbeats land there); status aggregates them with the
        # head's own counts via _telem.chaos_fired_counts()
        # poison-task quarantine: fid -> {kills, history, until, name,
        # detail}.  Owners report each worker kill their class caused
        # (task_kill_report) and the first success after one
        # (task_ok_report, resetting the CONSECUTIVE count); at
        # poison_task_threshold kills the class quarantines for
        # poison_task_ttl_s — agents refuse its leases (gossiped on
        # heartbeat replies, version-gated like chaos rules) and owners
        # fail submissions fast with PoisonedTaskError.
        self._poison: Dict[str, Dict[str, Any]] = {}
        self._quarantine_version = 1
        # memory/object accounting (rtpu memory): registered driver
        # callback addresses by job id (bounded — oldest fall off), the
        # pooled clients to them, and the periodic leak-scan task that
        # feeds ray_tpu_object_leaked_bytes
        from collections import OrderedDict as _OrderedDict

        self.driver_addrs: Dict[str, Tuple[str, int]] = _OrderedDict()
        self._driver_clients: Dict[Tuple[str, int], RpcClient] = {}
        self._driver_join_gap = False
        # drivers whose callback is unreachable (loopback addr from a
        # remote peer): the join is gapped only while their connection
        # lives — a PERMANENT flag here would turn the dead-owner and
        # channel tripwires off forever on any multi-machine cluster
        self._gapped_driver_conns: set = set()
        # leak TTLs run from when an object was first seen UNCLAIMED
        # (complete scans only), not from creation: an old object whose
        # owner just exited gets a full TTL of grace for the in-flight
        # store_free instead of being flagged on the next scan
        self._unclaimed_since: Dict[str, float] = {}
        self._memory_task: Optional[asyncio.Task] = None
        self._last_memory_scan: Dict[str, Any] = {}
        self._memview_inflight: Dict[Tuple[int, int], asyncio.Future] = {}

    # ---- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        if self._state_path:
            self._load_state()
        # ingest shards before the server: routed ops (task_events,
        # heartbeat, ...) dispatch onto their loops from the very first
        # frame.  The cross-shard queue is the telemetry plane's only
        # write path into core state (_NodeEntry mutations), drained
        # once per core tick.
        from ray_tpu._private.head_shards import (CrossShardQueue,
                                                  HeadShards,
                                                  TaskEventPlane,
                                                  TelemetryPlane)

        core_loop = asyncio.get_running_loop()
        self.shards = HeadShards(int(config.head_ingest_shards), core_loop)
        self._core_queue = CrossShardQueue(
            core_loop, self._apply_node_updates, name="telemetry")
        self._ev_plane = TaskEventPlane(self.shards.task_events)
        self._telem = TelemetryPlane(self.shards.telemetry, self.dir,
                                     self._membership, self._core_queue)
        self.rpc_op_loops = self.shards.op_loops()
        self.shards.start()
        self._publish_membership()
        self._server = RpcServer(self, host, port)
        p = await self._server.start()
        self._health_task = asyncio.ensure_future(self._health_loop())

        def _lag(sample: float) -> None:
            self._head_loop_lag = sample

        self._lag_task = asyncio.ensure_future(
            loop_lag_probe("head", on_sample=_lag))
        if self._state_path:
            self._persist_task = asyncio.ensure_future(self._persist_loop())
        if float(config.memory_scan_interval_s) > 0:
            self._memory_task = asyncio.ensure_future(
                self._memory_scan_loop())
        await self._start_metrics(host)
        # resume interrupted scheduling work from the restored tables
        for actor in self.actors.values():
            if actor.state in (PENDING, RESTARTING):
                self._spawn_scheduler(actor)
        for pg in self.placement_groups.values():
            if pg.state == PG_PENDING:
                asyncio.ensure_future(self._schedule_pg(pg))
        return p

    async def stop(self):
        if self._health_task:
            self._health_task.cancel()
        if self._lag_task:
            self._lag_task.cancel()
        if self._persist_task:
            self._persist_task.cancel()
        if self._dash_task:
            self._dash_task.cancel()
        if self._memory_task:
            self._memory_task.cancel()
        if self._state_path and self._dirty:
            self._save_state()
        # snapshot both tables: each close() yields, and a late register
        # or reap can resize the dict mid-iteration
        for n in list(self.nodes.values()):
            if n.client is not None:
                await n.client.close()
        for c in list(self._driver_clients.values()):
            await c.close()
        self._driver_clients.clear()
        if self._metrics_server is not None:
            self._metrics_server.close()
        if getattr(self, "_metrics_collector", None) is not None:
            from ray_tpu._private.metrics import default_registry

            default_registry.remove_collector(self._metrics_collector)
            self._metrics_collector = None
        if self._server:
            await self._server.stop()
        if self.shards is not None:
            self.shards.stop()
        self._shutdown.set()

    # ---- persistence -------------------------------------------------------

    def mark_dirty(self) -> None:
        self._dirty = True

    async def _persist_loop(self):
        interval = config.gcs_persist_interval_ms / 1000.0
        while True:
            await asyncio.sleep(interval)
            if self._dirty:
                self._dirty = False
                try:
                    self._save_state()
                except Exception:
                    import traceback

                    traceback.print_exc()

    def _snapshot(self) -> Dict[str, Any]:
        return {
            "kv": dict(self.kv),
            "named_actors": dict(self.named_actors),
            "job_counter": self._next_job_int,
            # memory aggregator callbacks: without these a head restart
            # makes every live driver's objects look ownerless (and the
            # dead-owner tripwire would flag them after one TTL)
            "driver_addrs": {j: list(a)
                             for j, a in self.driver_addrs.items()},
            # conn-scoped gaps can't survive a restart (the conns are
            # gone but the drivers may live on) — fold them into the
            # permanent flag so the restarted head stays conservative
            "driver_join_gap": (self._driver_join_gap
                                or bool(self._gapped_driver_conns)),
            "cluster_version": self._cluster_version,
            "autoscaler_types": dict(self._autoscaler_types),
            "actors": [
                {"actor_id": a.actor_id, "spec_wire": a.spec_wire,
                 "state": a.state, "node_id": a.node_id,
                 "worker_id": a.worker_id,
                 "addr": list(a.addr) if a.addr else None,
                 "instance": a.instance, "restarts_left": a.restarts_left,
                 "name": a.name, "death_cause": a.death_cause,
                 "kill_requested": a.kill_requested,
                 "method_num_returns": a.method_num_returns}
                for a in self.actors.values()],
            "placement_groups": [
                {"pg_id": p.pg_id, "bundles": p.bundles,
                 "strategy": p.strategy, "state": p.state,
                 "placements": p.placements, "name": p.name,
                 "failure": p.failure}
                for p in self.placement_groups.values()],
            "nodes": [
                {"node_id": n.node_id, "host": n.host, "port": n.port,
                 "arena_path": n.arena_path, "is_head_node": n.is_head_node,
                 "total": n.resources.total.to_dict(),
                 "available": n.resources.available.to_dict(),
                 "xfer_port": n.xfer_port}
                for n in self.nodes.values()],
        }

    def _save_state(self) -> None:
        import os

        import msgpack

        blob = msgpack.packb(self._snapshot(), use_bin_type=True)
        tmp = self._state_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, self._state_path)

    def _load_state(self) -> None:
        import os

        import msgpack

        if not os.path.exists(self._state_path):
            return
        try:
            with open(self._state_path, "rb") as f:
                snap = msgpack.unpackb(f.read(), raw=False,
                                       strict_map_key=False)
        except Exception as e:
            # a corrupt snapshot must not crash-loop the head: boot empty
            # (agents re-register via heartbeats) and keep the bad file
            # aside for diagnosis
            import sys

            sys.stderr.write(f"head state unreadable ({e}); starting fresh\n")
            try:
                os.replace(self._state_path, self._state_path + ".corrupt")
            except OSError:
                pass
            return
        self.kv = dict(snap.get("kv", {}))
        self.named_actors = dict(snap.get("named_actors", {}))
        self._next_job_int = int(snap.get("job_counter", 1))
        for j, a in (snap.get("driver_addrs") or {}).items():
            self.driver_addrs[j] = (a[0], a[1])
        self._driver_join_gap = bool(
            snap.get("driver_join_gap", False))
        self._cluster_version = int(snap.get("cluster_version", 0))
        self._autoscaler_types = dict(snap.get("autoscaler_types", {}))
        for a in snap.get("actors", []):
            entry = _ActorEntry(a["actor_id"], a["spec_wire"], a["name"], 0)
            entry.state = a["state"]
            entry.node_id = a["node_id"]
            entry.worker_id = a["worker_id"]
            entry.addr = tuple(a["addr"]) if a["addr"] else None
            entry.instance = a["instance"]
            entry.restarts_left = a["restarts_left"]
            entry.death_cause = a["death_cause"]
            entry.method_num_returns = dict(a.get("method_num_returns") or {})
            entry.kill_requested = a["kill_requested"]
            self.actors[entry.actor_id] = entry
        for p in snap.get("placement_groups", []):
            entry = _PgEntry(p["pg_id"], p["bundles"], p["strategy"],
                             p["name"])
            entry.state = p["state"]
            entry.placements = list(p["placements"])
            entry.failure = p["failure"]
            self.placement_groups[entry.pg_id] = entry
        # nodes are restored provisionally: agents keep running across a
        # head restart and re-register on the next heartbeat (reference:
        # node_manager.proto NotifyGCSRestart).  A restored node that
        # never reports in is reaped by the health loop.
        for nd in snap.get("nodes", []):
            entry = _NodeEntry(
                nd["node_id"], nd["host"], nd["port"], nd["arena_path"],
                NodeResources(ResourceSet(nd["total"])),
                nd["is_head_node"], xfer_port=nd.get("xfer_port", 0))
            entry.resources.available = ResourceSet(nd["available"])
            self.nodes[entry.node_id] = entry
        self.restarted = True

    async def wait_for_shutdown(self):
        await self._shutdown.wait()

    # ---- node table --------------------------------------------------------

    async def rpc_register_node(self, node_id: str, host: str, port: int,
                                arena_path: str, resources: Dict[str, float],
                                is_head_node: bool = False,
                                labels: Optional[Dict[str, str]] = None,
                                xfer_port: int = 0, _conn=None):
        entry = _NodeEntry(node_id, host, port, arena_path,
                           NodeResources(ResourceSet(resources)), is_head_node,
                           labels=labels or {}, xfer_port=xfer_port)
        self.nodes[node_id] = entry
        if _conn is not None:
            self._node_conns[_conn] = node_id
        self.publish("node_events", {"event": "registered",
                                     "node_id": node_id,
                                     "addr": [host, port],
                                     "is_head_node": is_head_node})
        self._cluster_version += 1
        self.mark_dirty()
        self._broadcast_cluster_view()
        # fresh capacity invalidates earlier "genuinely occupied"
        # conclusions: pending PGs may spend a new waited reservation
        for pg in self.placement_groups.values():
            pg.opt_wait_used = False
        self._wake_pending_pgs()
        self._wake_pending_actors()
        if self._chaos_version:
            # late joiners inherit the armed rule set immediately
            asyncio.get_running_loop().call_soon(self._broadcast_chaos)
        return {"ok": True, "cluster": self._cluster_view(),
                "version": self._cluster_version,
                "dir_epoch": self.dir.epoch,
                "dir": self.dir.updates_since(None)}

    def _publish_membership(self) -> None:
        """Publish the scheduling core's membership snapshot for the
        telemetry plane: node identity/addr/labels/totals/draining plus
        the version-gated gossip payloads (chaos, quarantine, scalable
        shapes).  Republished synchronously with EVERY mutation of that
        state, so the plane's heartbeat replies are stale by at most
        the in-flight beats of one publish — the DirectoryMirror
        version-handshake pattern, core->shard direction."""
        if self._membership is None:
            return
        nodes: Dict[str, Dict[str, Any]] = {}
        for nid, n in self.nodes.items():
            nodes[nid] = {"addr": [n.host, n.port], "labels": n.labels,
                          "xfer": n.xfer_port, "draining": n.draining,
                          "is_head": n.is_head_node,
                          "total": n.resources.total.to_dict(),
                          "available": n.resources.available.to_dict(),
                          "pressure": n.pressure}
        self._membership.publish({
            "nodes": nodes,
            "version": self._cluster_version,
            "scalable": self._scalable_shapes(),
            "chaos_version": self._chaos_version,
            "chaos_payload": self._chaos_payload(),
            "quarantine_version": self._quarantine_version,
            "quarantine_payload": self._quarantine_payload(),
        })

    def _apply_node_updates(self, items: List[Dict[str, Any]]) -> None:
        """Core-loop drain of the telemetry plane's cross-shard queue:
        fold heartbeat-derived per-node state into the scheduling
        core's _NodeEntry records (availability for placement, pending
        demand for the autoscaler, liveness for the health loop).  One
        callback per core tick regardless of how many beats landed."""
        woke = False
        for up in items:
            entry = self.nodes.get(up["node_id"])
            if entry is None:
                continue
            entry.last_heartbeat = up["hb_mono"]
            if up.get("memory"):
                entry.memory = up["memory"]
            if up.get("pressure") is not None:
                entry.pressure = float(up["pressure"])
            fresh = ResourceSet(up.get("available") or {})
            if fresh != entry.resources.available:
                woke = True
            entry.resources.available = fresh
            entry.pending_demands = up.get("pending") or []
        if woke:
            self._wake_pending_pgs()
        if self._core_inbox_gauge is None:
            from ray_tpu._private.metrics import head_inbox_depth_gauge

            self._core_inbox_gauge = head_inbox_depth_gauge()
        self._core_inbox_gauge.set(self._core_queue.take_high_water(),
                                   tags={"shard": "telemetry"})

    def _broadcast_cluster_view(self):
        """Membership changed: push the fresh view to every agent so
        feasibility checks don't wait out a heartbeat period (equivalent
        of the reference's ray_syncer broadcast).  One task per peer so a
        wedged agent can't stall the others."""
        self._publish_membership()
        view = self._cluster_view()
        version = self._cluster_version
        scalable = self._scalable_shapes()

        async def _push_one(conn):
            try:
                await asyncio.wait_for(
                    conn.push("cluster_update",
                              {"cluster": view, "version": version,
                               "scalable": scalable}),
                    timeout=5.0)
            except Exception:
                pass

        for conn in list(self._node_conns):
            asyncio.ensure_future(_push_one(conn))

    async def rpc_heartbeat(self, node_id: str, available: Dict[str, float],
                            pending: Optional[List[Dict[str, float]]] = None,
                            objects_delta: Optional[Dict[str, Any]] = None,
                            dir_versions: Optional[List[int]] = None,
                            metrics: Optional[Dict[str, float]] = None,
                            memory: Optional[Dict[str, Any]] = None,
                            pressure: Optional[float] = None,
                            seen_chaos_version: int = 0,
                            seen_quarantine_version: int = 0,
                            chaos_fired: Optional[Dict[str, int]] = None):
        """Routed to the telemetry shard's loop (rpc_op_loops): the
        whole beat — directory delta application, gauge-summary ring
        append, reply assembly off the membership snapshot — runs off
        the scheduling loop.  Only the per-node core state (entry
        availability/liveness) crosses back, over the single-producer
        queue drained once per core tick (_apply_node_updates)."""
        return self._telem.heartbeat(
            node_id=node_id, available=available, pending=pending,
            objects_delta=objects_delta, dir_versions=dir_versions,
            metrics=metrics, memory=memory, pressure=pressure,
            seen_chaos_version=seen_chaos_version,
            seen_quarantine_version=seen_quarantine_version,
            chaos_fired=chaos_fired)

    async def rpc_object_locations(self, oids: List[str]):
        """Directory lookup: which nodes' stores hold each oid (per the
        latest heartbeat deltas).  Pullers use it to retry from an
        alternate holder when their recorded source died mid-transfer
        (reference: ObjectDirectory location subscriptions).  One shard
        lock per oid — no scan over every node's object map."""
        out: Dict[str, List[List[Any]]] = {}
        for oid in oids:
            holders = []
            for nid in self.dir.locations(oid):
                n = self.nodes.get(nid)
                if n is not None:
                    holders.append([n.host, n.port])
            if holders:
                out[oid] = holders
        return {"locations": out}

    async def rpc_node_table(self):
        return {nid: n.table_entry() for nid, n in self.nodes.items()}

    # ---- pub/sub -----------------------------------------------------------

    def publish(self, channel: str, payload: Any) -> int:
        """Append an event to a channel's ring buffer and wake pollers
        (reference: pubsub/publisher.h Publish)."""
        from collections import deque

        seq = self._pubsub_seq.get(channel, 0) + 1
        self._pubsub_seq[channel] = seq
        buf = self._pubsub.get(channel)
        if buf is None:
            buf = self._pubsub[channel] = deque(maxlen=1000)
        buf.append((seq, payload))
        for ev in self._pubsub_waiters.pop(channel, []):
            ev.set()
        return seq

    async def rpc_publish(self, channel: str, payload: Any):
        return {"seq": self.publish(channel, payload)}

    async def rpc_subscribe_poll(self, channel: str, after_seq: int = 0,
                                 timeout_ms: int = 0):
        """Long-poll: events with seq > after_seq, waiting up to
        timeout_ms when none are buffered yet (reference: the
        subscriber's long-poll loop in pubsub/subscriber.h)."""
        # 0 means "return immediately"; positive values are clamped
        timeout_ms = min(timeout_ms, config.pubsub_poll_timeout_ms) \
            if timeout_ms > 0 else 0

        def collect():
            buf = self._pubsub.get(channel) or ()
            return [{"seq": s, "payload": p} for s, p in buf if s > after_seq]

        events = collect()
        if not events and timeout_ms > 0:
            ev = asyncio.Event()
            self._pubsub_waiters.setdefault(channel, []).append(ev)
            try:
                await asyncio.wait_for(ev.wait(), timeout_ms / 1000.0)
            except asyncio.TimeoutError:
                pass
            finally:
                waiters = self._pubsub_waiters.get(channel, [])
                if ev in waiters:
                    waiters.remove(ev)
            events = collect()
        return {"events": events,
                "latest_seq": self._pubsub_seq.get(channel, 0)}

    async def rpc_drain_node(self, node_id: str):
        """Immediate removal (reference: node_manager.proto DrainRaylet).
        The node is dropped from the tables at once — in-flight work
        dies and objects are NOT re-replicated.  The autoscaler's
        scale-down path uses rpc_drain_node_graceful instead; this stays
        as the forced/operator path."""
        await self._on_node_dead(node_id, "drained")
        return {"ok": True}

    async def rpc_drain_node_graceful(self, node_id: str):
        """Start the graceful drain state machine for one node
        (reference: DrainRaylet with a deadline + the autoscaler's
        drain-before-terminate protocol).  Returns immediately; poll
        rpc_drain_status.  Idempotent while a drain is in flight.

        Phases (see _drain_task): quiesce (no new leases, warm pools
        reclaimed) -> migrate_actors (__rt_save__ snapshot + restart
        elsewhere, no restart budget spent) -> quiesce_leases (in-flight
        work finishes) -> replicate_objects (sole primary copies pushed
        to live nodes over the bulk plane and promoted) -> terminate.
        A drain never proceeds past replicate_objects while a live
        object's last copy would die with the node."""
        entry = self.nodes.get(node_id)
        if entry is None:
            rec = self._drains.get(node_id)
            if rec is not None:
                return {"ok": True, "state": rec["state"]}
            return {"ok": False, "error": f"unknown node {node_id!r}"}
        if entry.is_head_node:
            return {"ok": False, "error": "refusing to drain the head node"}
        rec = self._drains.get(node_id)
        if rec is not None and rec["state"] == "draining":
            return {"ok": True, "state": "draining"}
        while len(self._drains) >= 32:  # bounded: drop oldest finished
            done = next((k for k, v in self._drains.items()
                         if v["state"] != "draining"), None)
            if done is None:
                break
            self._drains.pop(done)
        rec = self._drains[node_id] = {
            "node_id": node_id, "state": "draining", "phase": "quiesce",
            "started_ts": time.time(), "detail": "",
            "migrated_actors": 0, "replicated_objects": 0,
            "replicated_bytes": 0,
        }
        entry.draining = True
        self._cluster_version += 1
        self.mark_dirty()
        self._broadcast_cluster_view()
        self.publish("node_events", {"event": "draining",
                                     "node_id": node_id})
        asyncio.ensure_future(self._drain_task(entry, rec))
        return {"ok": True, "state": "draining"}

    async def rpc_drain_status(self, node_id: str):
        rec = self._drains.get(node_id)
        if rec is None:
            return {"state": "none"}
        return dict(rec)

    async def _drain_task(self, entry: _NodeEntry, rec: Dict[str, Any]):
        node_id = entry.node_id
        t0 = time.monotonic()
        deadline = t0 + float(config.drain_timeout_s)
        try:
            client = self._node_client(entry)
            # 1. the agent stops granting leases, cancels queued
            # waiters (owners re-route on the drained view) and pushes
            # an unbounded warm-lease reclaim to every owner
            await client.call("prepare_drain", timeout=10.0)
            # 2. restartable actors migrate off: snapshot via
            # __rt_save__ where supported, restart elsewhere without
            # spending the restart budget (a drain is not a failure)
            rec["phase"] = "migrate_actors"
            await self._drain_migrate_actors(entry, rec)
            # 3. wait out in-flight task leases — bounded by the grace
            # budget so one long-running task cannot wedge scale-down
            rec["phase"] = "quiesce_leases"
            grace_end = min(deadline,
                            t0 + float(config.drain_lease_grace_s))
            while time.monotonic() < grace_end:
                try:
                    info = await client.call("drain_info", timeout=10.0)
                except Exception:
                    break  # agent gone: node death path takes over
                if not info.get("leases"):
                    break
                await asyncio.sleep(0.2)
            # 4. no live object's last copy may die with the node
            rec["phase"] = "replicate_objects"
            await self._drain_replicate_objects(entry, rec, deadline)
            # 5. done: drop the node (actors/PGs left on it take the
            # normal death path — all migratable state is already off)
            rec["phase"] = "terminate"
            if node_id in self.nodes:
                try:
                    await client.oneway("shutdown_node")
                except Exception:
                    pass
                await self._on_node_dead(node_id, "drained")
            rec["state"] = "drained"
            rec["drain_s"] = round(time.monotonic() - t0, 3)
            from ray_tpu._private.metrics import autoscaler_metrics

            # scale_events_total counts DECISIONS and comes solely from
            # the autoscaler's report deltas (counting here too would
            # double every drain); the head owns the duration histogram
            _g, _events_c, drain_h = autoscaler_metrics()
            drain_h.observe(time.monotonic() - t0)
        except Exception as e:
            # abandon, don't force: the node keeps running with its
            # data; the autoscaler sees "failed" and may retry later
            rec["state"] = "failed"
            rec["detail"] = f"{type(e).__name__}: {e}"[:300]
            cur = self.nodes.get(node_id)
            if cur is not None:
                cur.draining = False
                self._cluster_version += 1
                self._broadcast_cluster_view()
                try:
                    await self._node_client(cur).call("cancel_drain",
                                                      timeout=10.0)
                except Exception:
                    pass

    async def _drain_migrate_actors(self, entry: _NodeEntry,
                                    rec: Dict[str, Any]) -> int:
        """Move every migratable actor off the draining node.

        Migratable = has restart budget left, or persisted state via
        ``__rt_save__`` just now (a stateful actor with max_restarts=0
        still resumes with state intact — the drain is planned, not a
        crash).  Non-migratable actors are exited here too: that is the
        node's death brought forward, handled by the normal worker-death
        path (serve replicas get replaced by their controller)."""
        migrated = 0
        for actor in list(self.actors.values()):
            if actor.node_id != entry.node_id or actor.state != ALIVE:
                continue
            if actor.addr is None:
                continue
            c = RpcClient(actor.addr[0], actor.addr[1], label="drain-actor")
            saved = False
            try:
                try:
                    r = await c.call("persist_actor_state", timeout=30.0)
                    saved = bool(r.get("saved"))
                except Exception:
                    pass
                if actor.restarts_left != 0 or saved:
                    # RESTARTING set BEFORE the worker exits: the
                    # agent's worker-death report then finds a restart
                    # already in flight and spends no budget
                    actor.state = RESTARTING
                    self.mark_dirty()
                    self.publish("actor_events", {
                        "actor_id": actor.actor_id, "state": "RESTARTING",
                        "name": actor.name,
                        "cause": f"node {entry.node_id[:8]} draining"})
                    actor.wake()
                    migrated += 1
                try:
                    await c.oneway("exit_worker")
                except Exception:
                    pass
            finally:
                await c.close()
            if actor.state == RESTARTING:
                self._spawn_scheduler(actor)
        rec["migrated_actors"] = migrated
        return migrated

    async def _drain_replicate_objects(self, entry: _NodeEntry,
                                       rec: Dict[str, Any],
                                       deadline: float):
        """Re-replicate every sealed live primary copy the draining node
        holds whose LAST copy would otherwise die with it.

        The sharded object directory answers "who else holds this" (a
        secondary copy elsewhere is promoted instead of re-pulled);
        sole copies are pulled over the PR-4 bulk plane onto the target
        with the most free arena bytes (PR-9 heartbeat breakdowns are
        the bin-packing input).  Pulled/promoted copies become PRIMARY
        (eviction-exempt) and are injected into the directory under the
        target's node id, so owners whose recorded holder dies resolve
        the new location through the normal alt-source path."""
        client = self._node_client(entry)
        cap = int(config.memory_summary_max_objects)
        r = await client.call("list_objects", limit=cap, timeout=30.0)
        listing = r.get("objects", ())
        if len(listing) >= cap:
            # a truncated listing could hide a sole primary copy; the
            # invariant is absolute, so fail the drain safe (the node
            # returns to service) rather than guess
            raise RuntimeError(
                f"object listing truncated at {cap}; refusing to drain "
                f"a store this large")
        objs = [o for o in listing
                if o.get("sealed") and not o.get("freed")
                and not o.get("channel") and o.get("primary")]
        if not objs:
            return
        targets = [n for n in self.nodes.values()
                   if n.node_id != entry.node_id and not n.draining]
        if not targets:
            raise RuntimeError(
                f"no live node to take {len(objs)} primary copies")
        # bin-pack against real free-arena bytes from the heartbeat
        # byte breakdowns, tracking what this drain already planned in
        planned: Dict[str, int] = {n.node_id: 0 for n in targets}

        def headroom(n: _NodeEntry) -> float:
            free = (n.memory or {}).get("arena_free")
            if free is None:
                free = config.object_store_memory_bytes
            return free - planned[n.node_id]

        # one plan per target: an existing directory-recorded secondary
        # elsewhere picks that node (ensure_local is a no-op when the
        # copy still exists and re-pulls from the source if it was
        # evicted meanwhile — the same verified path either way);
        # everything else bin-packs onto the freest store
        by_target: Dict[str, List[Tuple[str, int]]] = {}
        for o in objs:
            oid, size = o["object_id"], int(o.get("size", 0))
            others = [nid for nid in self.dir.locations(oid)
                      if nid != entry.node_id and nid in self.nodes
                      and not self.nodes[nid].draining]
            if others:
                by_target.setdefault(others[0], []).append((oid, size))
                continue
            target = max(targets, key=headroom)
            planned[target.node_id] += size
            by_target.setdefault(target.node_id, []).append((oid, size))
        moved = moved_bytes = 0

        async def source_still_holds(oid: str) -> bool:
            # the owner may free an object mid-drain — only a copy the
            # source STILL holds blocks the hand-off
            try:
                return bool(await client.call("store_contains", oid=oid,
                                              timeout=10.0))
            except Exception:
                return True  # unknown: assume it blocks (fail safe)

        for nid, items in by_target.items():
            node = self.nodes.get(nid)
            if node is None:
                raise RuntimeError(f"target {nid[:12]} died mid-drain")
            tclient = self._node_client(node)
            budget = max(5.0, deadline - time.monotonic())
            res = await tclient.call(
                "ensure_local_batch",
                items=[[oid, [entry.host, entry.port]]
                       for oid, _sz in items],
                timeout=budget)
            held: List[Tuple[str, int]] = []
            for (oid, size), item_res in zip(items,
                                             res.get("results") or ()):
                if item_res.get("ok"):
                    held.append((oid, size))
                elif await source_still_holds(oid):
                    raise RuntimeError(
                        f"sole primary copy {oid[:12]} could not be "
                        f"re-replicated: {item_res.get('error')}")
            if not held:
                continue
            reply = await tclient.call(
                "store_promote", oids=[oid for oid, _sz in held],
                timeout=30.0)
            missing = set(reply.get("missing") or ())
            for oid in missing:
                # vanished between the pull and the promote: legal only
                # if the object was freed everywhere — a copy the source
                # still holds means the hand-off failed
                if await source_still_holds(oid):
                    raise RuntimeError(
                        f"target {nid[:12]} lost copy {oid[:12]} before "
                        f"promote; drain aborted")
            handed = [(oid, sz) for oid, sz in held if oid not in missing]
            if not handed:
                continue
            # findable by every puller: small objects never ride the
            # heartbeat summaries, so the head injects the new holder
            # into the directory itself
            self.dir.apply_delta(nid, [[oid, sz] for oid, sz in handed],
                                 ())
            moved += len(handed)
            moved_bytes += sum(sz for _oid, sz in handed)
        rec["replicated_objects"] = moved
        rec["replicated_bytes"] = moved_bytes

    # ---- chaos fault injection ---------------------------------------------

    async def rpc_chaos(self, op: str, rule: Optional[Dict[str, Any]] = None,
                        seed: int = 0, sites: Optional[List[str]] = None,
                        events_per_site: int = 3, span: int = 100):
        """Cluster-wide fault injection (see fault_injection.py):
        op=inject adds one rule, op=schedule compiles a seed into a
        deterministic per-site failure schedule, op=clear disarms the
        plane, op=status reports the live rule set.  Every mutation
        applies locally (head sites) and gossips the FULL rule set to
        agents — a push for the fast path, the heartbeat reply as the
        catch-up for agents that missed it."""
        from ray_tpu._private import fault_injection

        if not config.chaos_enabled:
            raise RpcError("chaos fault injection is disabled "
                           "(chaos_enabled=False)")
        if op == "inject":
            if not rule:
                raise RpcError("chaos inject needs a rule")
            self._chaos_rules.append(
                fault_injection.ChaosRule.from_wire(rule).to_wire())
        elif op == "schedule":
            self._chaos_rules.extend(fault_injection.make_schedule(
                seed, sites or list(fault_injection.SITES),
                events_per_site=events_per_site, span=span))
        elif op == "clear":
            self._chaos_rules = []
        elif op != "status":
            raise RpcError(f"unknown chaos op {op!r}")
        if op != "status":
            self._chaos_version += 1
            # counts restart with the rule set
            self._telem.clear_chaos_fired()
            fault_injection.install(self._chaos_rules, self._chaos_version)
            self._broadcast_chaos()
            self._maybe_chaos_die()
        # aggregate cluster-wide firing counts: the head's own process
        # plus the latest per-agent heartbeat reports
        fired: Dict[str, int] = dict(fault_injection.fired_counts())
        for counts in self._telem.chaos_fired_counts().values():
            for rid, n in counts.items():
                fired[rid] = fired.get(rid, 0) + int(n)
        rules = [dict(r, fired=fired.get(r.get("rule_id", ""), 0))
                 for r in self._chaos_rules]
        return {"version": self._chaos_version, "rules": rules}

    def _maybe_chaos_die(self) -> None:
        """``head.kill`` chaos site (the agent.kill pattern applied to
        the head): SIGKILL this process after a short delay so the
        inject reply and the rule gossip flush first.  The cluster
        rides the existing GCS fault-tolerance paths — agents
        re-register on their next heartbeat against a restarted head,
        drivers retry inside gcs_reconnect_grace_s (test_gcs_ft.py)."""
        from ray_tpu._private import fault_injection

        chaos = fault_injection.decide("head.kill", key="head")
        if chaos is None or chaos.action != "kill":
            return
        import os
        import signal

        delay = max(chaos.delay_s, 0.2)
        asyncio.get_running_loop().call_later(
            delay, lambda: os.kill(os.getpid(), signal.SIGKILL))

    # ---- poison-task quarantine --------------------------------------------

    def _prune_quarantine(self) -> None:
        """Drop expired quarantines (TTL) — their kill counts restart
        from zero, so a class that still OOMs re-trips after another
        full threshold's worth of kills, not instantly.  UNTRIPPED
        watch entries expire on the same TTL measured from their LAST
        kill: "consecutive" means within a window, not ever — rare
        input-dependent kills spread over days (from short-lived
        drivers whose successes never send ok-reports) must not
        accumulate into a quarantine, and the table stays bounded."""
        now = time.time()
        ttl = float(config.poison_task_ttl_s)
        expired = [k for k, ent in self._poison.items()
                   if (ent.get("until") and now >= ent["until"])
                   or (not ent.get("until")
                       and now - ent.get("last_kill", now) >= ttl)]
        for k in expired:
            self._poison.pop(k, None)
        if expired:
            self._quarantine_version += 1
            self._set_quarantine_gauge()
            self._publish_membership()

    def _set_quarantine_gauge(self) -> None:
        from ray_tpu._private.metrics import memory_pressure_metrics

        memory_pressure_metrics()[2].set(
            sum(1 for e in self._poison.values() if e.get("until")))

    def _quarantine_payload(self) -> Dict[str, Any]:
        """The gossiped enforcement set: only TRIPPED entries (agents
        need nothing for classes still accumulating kills)."""
        return {"version": self._quarantine_version,
                "entries": {k: {"until": e["until"],
                                "detail": e["detail"],
                                "history": e["history"][-8:]}
                            for k, e in self._poison.items()
                            if e.get("until")}}

    def _quarantine_verdict(self, ent: Dict[str, Any]) -> Dict[str, Any]:
        return {"quarantined": bool(ent.get("until")),
                "until": ent.get("until", 0.0),
                "detail": ent.get("detail", ""),
                "history": ent.get("history", [])[-8:]}

    async def rpc_task_kill_report(self, key: str, kind: str = "crash",
                                   name: str = "", node_id: str = ""):
        """An owner's (or this head's, for actors) report that one
        execution of class `key` killed its worker.  Crossing
        ``poison_task_threshold`` consecutive kills trips the
        quarantine; the reply carries the verdict so the reporter can
        fail its next submissions fast without waiting for gossip."""
        self._prune_quarantine()
        ent = self._poison.get(key)
        if ent is None:
            ent = self._poison[key] = {"kills": 0, "history": [],
                                       "until": 0.0, "name": name,
                                       "detail": "", "last_kill": 0.0}
        if name:
            ent["name"] = name
        ent["kills"] += 1
        ent["last_kill"] = time.time()
        ent["history"].append(
            f"{kind} on node {node_id[:12] or '?'} at "
            f"{time.strftime('%H:%M:%S')}")
        del ent["history"][:-32]
        if not ent["until"] and ent["kills"] >= int(
                config.poison_task_threshold):
            ttl = float(config.poison_task_ttl_s)
            ent["until"] = time.time() + ttl
            ent["detail"] = (
                f"task class {ent['name'] or key[:12]!r} is quarantined: "
                f"its executions killed workers {ent['kills']} "
                f"consecutive times across the cluster "
                f"({'; '.join(ent['history'][-int(config.poison_task_threshold):])}); "
                f"expires in {ttl:.0f}s or `rtpu quarantine clear`")
            self._quarantine_version += 1
            self._set_quarantine_gauge()
            self._publish_membership()
            self.publish("error_info", {"kind": "task_quarantined",
                                        "key": key, "name": ent["name"],
                                        "detail": ent["detail"]})
        return self._quarantine_verdict(ent)

    async def rpc_task_ok_report(self, key: str):
        """A real completion of a class with kill history: the
        consecutive-kill count resets.  An ACTIVE quarantine is not
        lifted here (TTL/CLI only) — the success raced the trip."""
        ent = self._poison.get(key)
        if ent is not None and not ent.get("until"):
            self._poison.pop(key, None)
        return {"ok": True}

    async def rpc_quarantine(self, op: str = "list", key: str = ""):
        """`rtpu quarantine` backend: op=list dumps the table (tripped
        AND still-accumulating entries), op=clear lifts one key ("" =
        every tripped entry) immediately."""
        self._prune_quarantine()
        if op == "clear":
            cleared = []
            for k in ([key] if key else
                      [k for k, e in self._poison.items() if e["until"]]):
                if self._poison.pop(k, None) is not None:
                    cleared.append(k)
            if cleared:
                self._quarantine_version += 1
                self._set_quarantine_gauge()
                self._publish_membership()
            return {"cleared": cleared}
        if op != "list":
            raise RpcError(f"unknown quarantine op {op!r}")
        now = time.time()
        return {"entries": {
            k: {"name": e["name"], "kills": e["kills"],
                "quarantined": bool(e["until"]),
                "expires_in_s": round(max(0.0, e["until"] - now), 1)
                if e["until"] else 0.0,
                "history": e["history"][-8:]}
            for k, e in self._poison.items()}}

    def _chaos_payload(self) -> Dict[str, Any]:
        return {"rules": list(self._chaos_rules),
                "version": self._chaos_version}

    def _broadcast_chaos(self) -> None:
        # keep the telemetry plane's heartbeat catch-up in sync with
        # the push: the membership snapshot carries the chaos payload
        self._publish_membership()
        payload = self._chaos_payload()

        async def _push_one(conn):
            try:
                await asyncio.wait_for(conn.push("chaos_rules", payload),
                                       timeout=5.0)
            except Exception:
                pass

        for conn in list(self._node_conns):
            asyncio.ensure_future(_push_one(conn))

    def _cluster_view(self) -> Dict[str, Any]:
        """Per-node resources/labels.  Object locations ride the sharded
        directory's versioned shard updates, not this view.  Draining
        nodes are flagged so agent-side routing (spillback, pick_node)
        stops targeting them within one view push."""
        return {nid: {"addr": [n.host, n.port],
                      "res": n.resources.to_dict(),
                      "labels": n.labels, "xfer": n.xfer_port,
                      **({"draining": True} if n.draining else {}),
                      **({"pressure": n.pressure}
                         if n.pressure is not None else {})}
                for nid, n in self.nodes.items()}

    def on_peer_disconnect(self, conn) -> None:
        node_id = self._node_conns.pop(conn, None)
        if node_id is not None and node_id in self.nodes:
            asyncio.ensure_future(self._on_node_dead(node_id, "connection lost"))
        if conn in self._gapped_driver_conns:
            self._gapped_driver_conns.discard(conn)
            self.mark_dirty()

    async def _health_loop(self):
        period = config.gcs_health_check_period_ms / 1000.0
        threshold = config.gcs_health_check_failure_threshold * period
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for nid in list(self.nodes):
                n = self.nodes.get(nid)
                if n is not None and now - n.last_heartbeat > threshold:
                    await self._on_node_dead(nid, "heartbeat timeout")
            # quarantine TTL expiry used to ride the heartbeat path;
            # beats now land on the telemetry shard, which must not
            # mutate quarantine state — the core sweeps instead.
            # Agents enforce TTLs locally (_quarantined_entry), so the
            # one-period expiry-gossip latency is harmless.
            if self._poison:
                self._prune_quarantine()

    async def _on_node_dead(self, node_id: str, reason: str):
        entry = self.nodes.pop(node_id, None)
        if entry is None:
            return
        # dead node: drop its time series, chaos counts and telemetry
        self._telem.drop_node(node_id)
        self.dir.drop_node(node_id)  # its object copies died with it
        self._cluster_version += 1
        self.mark_dirty()
        self.publish("node_events", {"event": "dead", "node_id": node_id,
                                     "reason": reason})
        self._broadcast_cluster_view()
        if entry.client is not None:
            await entry.client.close()
        # restart or fail every actor that lived on that node
        for actor in list(self.actors.values()):
            if (actor.state in (PENDING, RESTARTING)
                    and actor.sched_node == node_id):
                # an in-flight creation push targets the dead node; the RPC
                # may hang forever (silent host death) — abort the attempt
                # and reschedule without spending the restart budget
                if actor.sched_task is not None:
                    actor.sched_task.cancel()
                self._spawn_scheduler(actor)
            elif actor.node_id == node_id and actor.state in (ALIVE, PENDING):
                await self._on_actor_worker_lost(
                    actor, f"node {node_id[:8]} died: {reason}")
        await self._on_pg_node_dead(node_id)

    # ---- internal KV (function table rides on this) ------------------------

    async def rpc_kv_put(self, key: str, value: bytes, overwrite: bool = True):
        if not overwrite and key in self.kv:
            return {"added": False}
        self.kv[key] = value
        self.mark_dirty()
        return {"added": True}

    async def rpc_kv_get(self, key: str):
        return {"value": self.kv.get(key)}

    async def rpc_kv_del(self, key: str):
        deleted = self.kv.pop(key, None) is not None
        if deleted:
            self.mark_dirty()
        return {"deleted": deleted}

    async def rpc_kv_keys(self, prefix: str = ""):
        return {"keys": [k for k in self.kv if k.startswith(prefix)]}

    # ---- jobs --------------------------------------------------------------

    async def rpc_register_job(self, driver_addr: Optional[List] = None,
                               _conn=None):
        jid = JobID.from_int(self._next_job_int)
        self._next_job_int += 1
        if driver_addr:
            # callback address for the memory aggregator (drivers own
            # most refs but are not pooled by any agent).  A loopback
            # callback registered from a REMOTE peer would have the head
            # dial its OWN loopback, not the driver: record nothing and
            # mark the join gapped, so the unreachable driver's refs are
            # a known gap rather than false dead_owner leaks.  Dead
            # drivers are pruned by the scan fan-out (_drop_driver); the
            # cap is a backstop against registration floods, and
            # evicting a possibly-LIVE driver poisons the ownership
            # join, so an eviction (pathological: >256 concurrent
            # drivers) likewise marks memory views partial from then on
            # — absence-of-owner can no longer be trusted as a death
            # signal.
            peer = (_conn.writer.get_extra_info("peername")
                    if _conn is not None else None)
            sock = (_conn.writer.get_extra_info("sockname")
                    if _conn is not None else None)
            # same-machine drivers CAN be dialed back on loopback even
            # when they reached the head via its LAN address — and a
            # local connection to the machine's own LAN IP bears that
            # IP on BOTH endpoints, so peer==sock host means local
            if (is_loopback(driver_addr[0]) and peer
                    and not is_loopback(peer[0])
                    and not (sock and peer[0] == sock[0])):
                # gap scoped to the driver's connection: cleared when it
                # disconnects (its refs die with it), so one remote
                # driver doesn't disable leak detection forever
                if _conn is not None:
                    self._gapped_driver_conns.add(_conn)
                else:
                    self._driver_join_gap = True
            else:
                self.driver_addrs[jid.hex()] = (driver_addr[0],
                                                driver_addr[1])
                while len(self.driver_addrs) > 256:
                    j, a = next(iter(self.driver_addrs.items()))
                    self._driver_join_gap = True
                    self._drop_driver(j, a)
        self.mark_dirty()
        return {"job_id": jid.hex()}

    # ---- actor manager -----------------------------------------------------

    async def rpc_create_actor(self, spec: Dict[str, Any], name: str = "",
                               method_num_returns: Optional[Dict] = None):
        ts = TaskSpec.from_wire(spec)
        existing = self.actors.get(ts.actor_id)
        if existing is not None:
            # duplicate submission (client retried across a dropped reply,
            # e.g. a head restart): the id is client-generated, so this is
            # the SAME actor — don't double-create
            return {"actor_id": ts.actor_id}
        if name:
            if self.named_actors.get(name) not in (None, ts.actor_id):
                raise RpcError(f"actor name {name!r} already taken")
            self.named_actors[name] = ts.actor_id
        entry = _ActorEntry(ts.actor_id, spec, name, ts.max_restarts)
        entry.method_num_returns = dict(method_num_returns or {})
        self.actors[ts.actor_id] = entry
        self.mark_dirty()
        self._spawn_scheduler(entry)
        return {"actor_id": ts.actor_id}

    async def rpc_get_actor_info(self, actor_id: str, wait: bool = False,
                                 known_instance: int = -1):
        """Resolve an actor's address; with wait=True, long-poll until the
        actor leaves PENDING/RESTARTING (or is a newer instance than the
        caller already knows about)."""
        entry = self.actors.get(actor_id)
        if entry is None:
            return {"state": DEAD, "death_cause": "no such actor"}
        deadline = time.monotonic() + config.pubsub_poll_timeout_ms / 1000.0
        while wait and time.monotonic() < deadline:
            if entry.state == DEAD:
                break
            if entry.state == ALIVE and entry.instance > known_instance:
                break
            ev = asyncio.Event()
            entry.waiters.append(ev)
            try:
                await asyncio.wait_for(ev.wait(), deadline - time.monotonic())
            except asyncio.TimeoutError:
                break
        return entry.info()

    async def rpc_get_named_actor(self, name: str):
        aid = self.named_actors.get(name)
        if aid is None:
            return {"found": False}
        entry = self.actors.get(aid)
        return {"found": True, "actor_id": aid,
                "method_num_returns":
                    entry.method_num_returns if entry else {}}

    async def rpc_list_actors(self):
        return {"actors": [a.info() for a in self.actors.values()]}

    async def rpc_kill_actor(self, actor_id: str, no_restart: bool = True):
        entry = self.actors.get(actor_id)
        if entry is None:
            return {"ok": False}
        self.mark_dirty()
        if no_restart:
            entry.restarts_left = 0
            entry.kill_requested = True
        if entry.state == ALIVE and entry.addr is not None:
            client = RpcClient(entry.addr[0], entry.addr[1], label="kill")
            try:
                await client.oneway("exit_worker")
            except Exception:
                pass
            finally:
                await client.close()
        elif entry.state in (PENDING, RESTARTING) and no_restart:
            # creation still in flight: _schedule_actor checks
            # kill_requested after the push and tears the instance down
            entry.state = DEAD
            entry.death_cause = "killed before creation completed"
            if entry.name:
                self.named_actors.pop(entry.name, None)
            entry.wake()
        return {"ok": True}

    async def rpc_worker_died(self, node_id: str, worker_id: str,
                              reason: str = "",
                              oom: Optional[Dict[str, Any]] = None):
        """Node agent reports a worker process death.  ``oom`` is the
        watchdog's kill receipt when the death was a deliberate
        memory-pressure kill: an OOM-killed ACTOR counts toward its
        class's poison quarantine here (normal tasks are counted by
        their owners, which know exactly which task was running)."""
        self.publish("error_info", {"kind": "worker_died",
                                    "node_id": node_id,
                                    "worker_id": worker_id, "reason": reason})
        for actor in list(self.actors.values()):
            if actor.worker_id == worker_id and actor.state in (ALIVE, PENDING):
                if oom is not None:
                    from ray_tpu._private.memory_monitor import \
                        is_self_poisoning

                    # same self-poisoning gate the owners apply to task
                    # kills: aggregate-pressure victims don't count
                    fid = actor.spec_wire.get("fid", "")
                    if fid and is_self_poisoning(
                            int(oom.get("rss", 0)),
                            int(oom.get("limit", 0))):
                        await self.rpc_task_kill_report(
                            key=fid, kind="oom",
                            name=actor.spec_wire.get("name", ""),
                            node_id=node_id)
                await self._on_actor_worker_lost(
                    actor, reason or f"worker {worker_id[:8]} died")
        return {"ok": True}

    async def _on_actor_worker_lost(self, actor: _ActorEntry, cause: str):
        self.mark_dirty()
        if actor.state == RESTARTING:
            # a restart is already in flight (_schedule_actor retries node
            # failures itself); a second concurrent reschedule would double
            # -decrement restarts_left and leak a live instance on a lease
            return
        if actor.restarts_left == 0:
            actor.state = DEAD
            actor.death_cause = cause
            if actor.name:
                self.named_actors.pop(actor.name, None)
            self.publish("actor_events", {
                "actor_id": actor.actor_id, "state": "DEAD",
                "name": actor.name, "cause": cause})
            actor.wake()
            return
        if actor.restarts_left > 0:
            actor.restarts_left -= 1
        actor.state = RESTARTING
        from ray_tpu._private.metrics import fault_tolerance_metrics

        fault_tolerance_metrics()[0].inc()
        self.publish("actor_events", {
            "actor_id": actor.actor_id, "state": "RESTARTING",
            "name": actor.name, "cause": cause})
        actor.wake()
        self._spawn_scheduler(actor)

    def _spawn_scheduler(self, actor: _ActorEntry):
        """Start a new scheduling attempt, invalidating any older one."""
        actor.sched_gen += 1
        actor.sched_node = ""
        asyncio.ensure_future(self._schedule_actor(actor, actor.sched_gen))

    async def _schedule_actor(self, actor: _ActorEntry, gen: int = 0):
        """Pick a node, lease a worker there, push the creation task.

        Only the coroutine holding the actor's current sched_gen may mutate
        its state — a newer attempt (spawned by worker/node death handlers)
        silently retires this one.

        Reference: gcs_actor_scheduler.h — GCS leases workers from raylets
        using the same protocol normal tasks do.
        """
        gen = gen or actor.sched_gen
        actor.sched_task = asyncio.current_task()
        ts = TaskSpec.from_wire(actor.spec_wire)
        demand = ts.resource_set()
        delay = 0.05
        if ts.placement_group_id:
            # waiting for the group to be placed must not consume the
            # creation retry budget — PGs may stay PENDING for a while
            while True:
                if (actor.kill_requested or actor.state == DEAD
                        or actor.sched_gen != gen):
                    return
                pg = self.placement_groups.get(ts.placement_group_id)
                if pg is None:
                    actor.state = DEAD
                    actor.death_cause = "placement group removed"
                    actor.wake()
                    return
                if max(ts.bundle_index, 0) >= len(pg.bundles):
                    actor.state = DEAD
                    actor.death_cause = (
                        f"bundle index {ts.bundle_index} out of range for "
                        f"{len(pg.bundles)}-bundle placement group")
                    actor.wake()
                    return
                if pg.state == PG_CREATED:
                    break
                await asyncio.sleep(delay)
                delay = min(delay * 2, 1.0)
        attempt = 0
        while attempt <= config.actor_creation_retries:
            attempt += 1
            if (actor.kill_requested or actor.state == DEAD
                    or actor.sched_gen != gen):
                return
            if ts.placement_group_id:
                pg = self.placement_groups.get(ts.placement_group_id)
                if pg is None or pg.state != PG_CREATED:
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 2.0)
                    continue
                nid = pg.placements[max(ts.bundle_index, 0)]
            else:
                # draining nodes accept no new actors — their leases are
                # being quiesced and the node is about to terminate
                cluster = {nid: n.resources for nid, n in self.nodes.items()
                           if not n.draining}
                nid = pick_node(
                    cluster, demand, local_node_id="",
                    strategy=ts.scheduling_strategy,
                    labels_by_node={nid: n.labels
                                    for nid, n in self.nodes.items()},
                    pressure_by_node={nid: n.pressure
                                      for nid, n in self.nodes.items()
                                      if n.pressure is not None},
                    pressure_threshold=float(config.memory_usage_threshold))
            if nid is None:
                from ray_tpu._private.node_agent import _is_hard_strategy

                if (not _is_hard_strategy(ts.scheduling_strategy)
                        and any(ResourceSet(s).fits(demand)
                                for s in self._scalable_shapes())):
                    # an autoscaler can launch a node this actor fits:
                    # keep the actor PENDING (visible via autoscaler_state)
                    # without spending the creation budget (reference:
                    # pending actors resolve via the autoscaler demand
                    # loop).  Hard affinity/label strategies are exempt —
                    # scale-up can never mint the specific node they name,
                    # so they burn the budget and die.
                    attempt -= 1
                # woken early by a node registration (an autoscaled
                # node arriving for exactly this demand), else backoff
                await self._wait_actor_event(delay)
                delay = min(delay * 2, 2.0)
                continue
            node = self.nodes.get(nid)
            if node is None:
                continue
            # optimistic accounting: deduct the demand from the cached
            # availability view NOW (pick_node → acquire is atomic on
            # this loop), so concurrent creations — e.g. serve deploying
            # N replicas — see each other's placements.  Without it
            # SPREAD runs against identical stale views and packs every
            # replica onto one node, which defeats fault isolation.  The
            # next heartbeat restores ground truth either way; deduction
            # is skipped for PG-bundled actors (they draw from reserved
            # bundles, not the free pool).
            deducted = (not ts.placement_group_id
                        and node.resources.acquire(demand))
            from ray_tpu._private import fault_injection

            chaos = fault_injection.decide("lease.grant",
                                           key=actor.actor_id)
            if chaos is not None and chaos.action == "delay":
                await fault_injection.sleep_async(chaos.delay_s)
            try:
                lease = await self._node_client(node).call(
                    "request_lease", spec=actor.spec_wire, grant_only=True,
                    timeout=config.worker_lease_timeout_ms / 1000.0)
            except Exception:
                if deducted:
                    node.resources.release(demand)
                await asyncio.sleep(delay)
                continue
            if "granted" not in lease:
                if deducted:
                    node.resources.release(demand)
                if lease.get("error") in ("runtime env setup failed",
                                          "poisoned"):
                    # deterministic failures: retrying other nodes cannot
                    # fix a missing env package or an actively-quarantined
                    # class — fail fast with the refusal's detail
                    actor.state = DEAD
                    actor.death_cause = lease.get(
                        "error_str", lease["error"])
                    if actor.name:
                        self.named_actors.pop(actor.name, None)
                    actor.wake()
                    return
                await asyncio.sleep(delay)
                continue
            g = lease["granted"]

            async def _drop_lease():
                try:
                    await self._node_client(node).call(
                        "return_lease", lease_id=g["lease_id"], kill_worker=True)
                except Exception:
                    pass

            # push the creation task directly to the leased worker; a
            # constructor may legitimately run for a long time (model
            # load), so use the task-push timeout, not the RPC default
            wclient = RpcClient(g["addr"][0], g["addr"][1], label="actor-create")
            actor.sched_node = nid
            try:
                reply = await wclient.call(
                    "push_task", spec=actor.spec_wire, instance=actor.instance + 1,
                    tpu_chips=g.get("tpu_chips"),
                    timeout=7 * 86400.0)
                if reply.get("error"):
                    raise RpcError(f"actor constructor failed: {reply['error_str']}")
            except asyncio.CancelledError:
                # a node-death handler aborted this attempt and respawned a
                # fresh one; the lease died with the node
                await wclient.close()
                return
            except RpcError as e:
                await wclient.close()
                await _drop_lease()
                if actor.sched_gen != gen:
                    return
                # constructor raised: do not retry onto other nodes
                actor.state = DEAD
                actor.death_cause = str(e)
                if actor.name:
                    self.named_actors.pop(actor.name, None)
                actor.wake()
                return
            except Exception:
                # transport failure: give the lease back before retrying
                await wclient.close()
                await _drop_lease()
                if actor.sched_gen != gen:
                    return
                await asyncio.sleep(delay)
                continue
            finally:
                if actor.sched_gen == gen:
                    # only the owning generation may clear the in-flight
                    # marker — a retired one would clobber the live attempt
                    actor.sched_node = ""
            await wclient.close()
            if actor.sched_gen != gen:
                # a newer scheduling attempt owns this actor now; this
                # instance is orphaned — tear it down
                await _drop_lease()
                return
            if actor.kill_requested:
                # killed while the constructor ran: tear the instance down
                actor.state = DEAD
                actor.death_cause = actor.death_cause or "killed during creation"
                if actor.name:
                    self.named_actors.pop(actor.name, None)
                actor.wake()
                await _drop_lease()
                return
            actor.state = ALIVE
            actor.instance += 1
            actor.node_id = nid
            actor.worker_id = g["worker_id"]
            actor.addr = (g["addr"][0], g["addr"][1])
            self.mark_dirty()
            self.publish("actor_events", {
                "actor_id": actor.actor_id, "state": "ALIVE",
                "name": actor.name, "node_id": nid})
            actor.wake()
            return
        actor.state = DEAD
        actor.death_cause = "actor creation failed: no feasible node"
        self.mark_dirty()
        if actor.name:
            self.named_actors.pop(actor.name, None)
        actor.wake()

    def _node_client(self, node: _NodeEntry) -> RpcClient:
        if node.client is None or node.client.dead:
            node.client = RpcClient(node.host, node.port, label=f"agent-{node.node_id[:8]}")
        return node.client

    # ---- placement groups --------------------------------------------------

    async def rpc_create_placement_group(self, bundles: List[Dict[str, float]],
                                         strategy: str = "PACK",
                                         name: str = "", pg_id: str = ""):
        from ray_tpu._private.ids import PlacementGroupID

        if pg_id and pg_id in self.placement_groups:
            # duplicate submission (client retried across a dropped
            # reply): ids are client-generated, dedup instead of leaking
            # a second group holding bundles forever
            return {"pg_id": pg_id}
        pg_id = pg_id or PlacementGroupID.from_random().hex()
        entry = _PgEntry(pg_id, bundles, strategy, name)
        self.placement_groups[pg_id] = entry
        self.mark_dirty()
        # one inline scheduling pass before replying: for the common
        # create-then-ready pattern the follow-up get_placement_group
        # then answers CREATED immediately with no waiter park/wake
        # cycle (PG churn is a benchmarked hot path); a group that
        # doesn't fit right now falls back to the event-driven loop.
        # inline=True: this pass must not block the create reply behind
        # a reservation queue wait on a saturated cluster
        await self._schedule_pg(entry, max_attempts=1, inline=True)
        if entry.state == PG_PENDING:
            asyncio.ensure_future(self._schedule_pg(entry))
        # the reply carries the full info when the inline pass already
        # committed the group: the client's ready()/wait() then answers
        # from this snapshot with ZERO further round trips — on the PG
        # churn path that removes one of the three driver RPCs
        return {"pg_id": pg_id, "info": entry.info(self.nodes)}

    async def rpc_get_placement_group(self, pg_id: str, wait: bool = False,
                                      wait_s: Optional[float] = None):
        entry = self.placement_groups.get(pg_id)
        if entry is None:
            return {"state": PG_REMOVED, "failure": "no such placement group"}
        poll = min(wait_s if wait_s is not None else 1e9,
                   config.pubsub_poll_timeout_ms / 1000.0)
        deadline = time.monotonic() + poll
        while wait and entry.state == PG_PENDING and time.monotonic() < deadline:
            ev = asyncio.Event()
            entry.waiters.append(ev)
            try:
                await asyncio.wait_for(ev.wait(), deadline - time.monotonic())
            except asyncio.TimeoutError:
                break
            finally:
                if ev in entry.waiters:  # drop unfired waiters (leak guard)
                    entry.waiters.remove(ev)
        return entry.info(self.nodes)

    async def rpc_remove_placement_group(self, pg_id: str):
        entry = self.placement_groups.pop(pg_id, None)
        if entry is None:
            return {"ok": False}
        entry.state = PG_REMOVED
        self.mark_dirty()
        entry.wake()
        # one return_bundles frame per node instead of one RPC per
        # bundle (the release half of the batched PG commit path)
        by_node: Dict[str, List[int]] = {}
        for idx, nid in enumerate(entry.placements):
            if nid is not None:
                by_node.setdefault(nid, []).append(idx)
        for nid, idxs in by_node.items():
            node = self.nodes.get(nid)
            if node is None:
                continue
            try:
                await self._node_client(node).call(
                    "return_bundles", pg_id=pg_id, indices=idxs)
            except Exception:
                pass
            # update the cached view immediately — the next PG create
            # must not wait out a heartbeat period to see the freed
            # capacity (heartbeats remain authoritative and overwrite)
            for idx in idxs:
                node.resources.release(ResourceSet(entry.bundles[idx]))
        self._wake_pending_pgs()
        return {"ok": True}

    async def rpc_list_placement_groups(self):
        return {"placement_groups": [
            e.info(self.nodes) for e in self.placement_groups.values()]}

    def _plan_pg(self, entry: _PgEntry,
                 optimistic: bool = False) -> Optional[List[str]]:
        """Choose a node per bundle per strategy, against a scratch copy of
        the cluster view (all-or-nothing; reference:
        bundle_scheduling_policy.h pack/spread/strict variants).

        ``optimistic`` plans against node totals *minus committed PG
        bundles* instead of the cached availability view: the view lags
        reality by up to a heartbeat period (freed task leases, returned
        bundles), so when no node looks available the head still targets
        a feasible node and lets the agent-side queued reservation
        (rpc_reserve_bundle wait_ms) wait out the staleness.  Committed
        bundles are permanent carve-outs, never staleness — ignoring
        them would queue unsatisfiable reservations that head-of-line
        block the node's lease queue."""
        scratch: Dict[str, NodeResources] = {
            nid: (NodeResources(n.resources.total) if optimistic
                  else NodeResources.from_dict(
                      {"total": n.resources.total.to_dict(),
                       "available": n.resources.available.to_dict()}))
            for nid, n in self.nodes.items() if not n.draining
        }
        if optimistic:
            for pg in self.placement_groups.values():
                for idx, nid in enumerate(pg.placements):
                    if nid is not None and nid in scratch:
                        scratch[nid].acquire(ResourceSet(pg.bundles[idx]))
        plan: List[Optional[str]] = []
        used_nodes: List[str] = []
        for idx, bundle in enumerate(entry.bundles):
            existing = entry.placements[idx]
            if existing is not None and existing in scratch:
                # bundle already reserved there (rescheduling after a node
                # death replaces only the lost bundles)
                plan.append(existing)
                used_nodes.append(existing)
                continue
            demand = ResourceSet(bundle)
            candidates = [(nid, nr) for nid, nr in scratch.items()
                          if nr.can_fit(demand)]
            if entry.strategy in ("STRICT_SPREAD",):
                candidates = [(nid, nr) for nid, nr in candidates
                              if nid not in used_nodes]
            if not candidates:
                return None
            if entry.strategy in ("PACK", "STRICT_PACK") and used_nodes:
                packed = [c for c in candidates if c[0] == used_nodes[-1]]
                if packed:
                    candidates = packed
                elif entry.strategy == "STRICT_PACK":
                    return None
            if entry.strategy == "SPREAD":
                # prefer nodes not already used, then least utilized
                candidates.sort(key=lambda kv: (kv[0] in used_nodes,
                                                kv[1].utilization()))
            else:
                candidates.sort(key=lambda kv: kv[1].utilization())
            nid, nr = candidates[0]
            nr.acquire(demand)
            plan.append(nid)
            used_nodes.append(nid)
        return plan

    def _wake_pending_pgs(self) -> None:
        """Resources may have freed: replan every waiting PG right now."""
        if not self._pg_wake_waiters:
            return
        waiters, self._pg_wake_waiters = self._pg_wake_waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(True)

    def _wake_pending_actors(self) -> None:
        """Fresh capacity registered: parked actor schedulers retry now."""
        if not self._actor_wake_waiters:
            return
        waiters, self._actor_wake_waiters = self._actor_wake_waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(True)

    async def _wait_actor_event(self, timeout: float) -> None:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._actor_wake_waiters.append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            if fut in self._actor_wake_waiters:
                self._actor_wake_waiters.remove(fut)

    async def _wait_pg_event(self, timeout: float) -> bool:
        """Wait for a resource-release wake, or timeout. True if woken."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pg_wake_waiters.append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            if fut in self._pg_wake_waiters:
                self._pg_wake_waiters.remove(fut)

    async def _schedule_pg(self, entry: _PgEntry, max_attempts: int = 0,
                           inline: bool = False):
        """Keep trying until reserved or removed.  Like the reference, a
        group that doesn't currently fit stays PENDING indefinitely (the
        autoscaler is what resolves persistent infeasibility).

        Retries are event-driven: a failed attempt parks on
        _wait_pg_event and is woken by heartbeats/bundle returns/node
        registrations, with sleep backoff only as the fallback.
        ``max_attempts`` > 0 bounds the passes; ``inline`` marks the
        fast path inside create, which must never block the reply — it
        reserves with no queue wait and leaves the one-shot optimistic
        full-wait budget to the event-driven loop."""
        delay = 0.05
        attempts = 0
        while entry.state == PG_PENDING \
                and self.placement_groups.get(entry.pg_id) is entry:
            attempts += 1
            plan = self._plan_pg(entry)
            # an availability-backed plan always reserves with a wait:
            # the view can be stale the other way (shows available, node
            # briefly isn't — lingering leases), and a queued reservation
            # grants the moment the agent reclaims them
            wait_ms = 0 if inline else int(config.pg_reserve_wait_ms)
            if plan is None:
                # the availability view may simply be stale (lingering
                # leases just returned, heartbeat not in yet): target
                # feasible nodes and let the reservation queue there —
                # but a totals-based plan can also target genuinely
                # occupied capacity, so only the FIRST such attempt may
                # block the node's lease queue for the full wait
                plan = self._plan_pg(entry, optimistic=True)
                if inline or entry.opt_wait_used:
                    wait_ms = 0
                elif plan is not None:
                    entry.opt_wait_used = True
            ok = False
            if plan is not None:
                ok, newly = await self._reserve_pg(entry, plan, wait_ms)
                if ok:
                    removed = entry.state != PG_PENDING
                    # a plan node may have died between the last reserve
                    # RPC and now — committing CREATED then would strand
                    # the group (the death event is already consumed)
                    lost_node = any(nid not in self.nodes for nid in plan)
                    if removed or lost_node:
                        for idx, nid in enumerate(plan):
                            node = self.nodes.get(nid)
                            if node is not None:
                                try:
                                    await self._node_client(node).call(
                                        "return_bundle", pg_id=entry.pg_id,
                                        bundle_index=idx)
                                except Exception:
                                    pass
                        if removed:
                            return
                        entry.placements = [None] * len(entry.bundles)
                        continue  # replan from scratch
                    # reflect the reservation in the cached view at once
                    # (heartbeats remain authoritative and overwrite);
                    # only bundles reserved by THIS attempt — pre-existing
                    # ones were accounted when first committed
                    for idx in newly:
                        node = self.nodes.get(plan[idx])
                        if node is not None:
                            node.resources.acquire(
                                ResourceSet(entry.bundles[idx]))
                    entry.placements = plan
                    entry.state = PG_CREATED
                    self.mark_dirty()
                    entry.wake()
                    return
            if max_attempts and attempts >= max_attempts:
                return
            woke = await self._wait_pg_event(delay)
            delay = 0.05 if woke else min(delay * 2, 1.0)

    async def _reserve_pg(self, entry: _PgEntry, plan: List[str],
                          wait_ms: int = 0):
        """Reserve every bundle; roll back on any failure (all-or-nothing —
        the TPU-slice gang atomicity guarantee).  Returns
        (ok, newly_reserved_bundle_indices).

        All of a node's bundles ride ONE reserve_bundles frame: an
        N-host slice costs O(nodes) commit round trips, not O(bundles)
        (ISSUE 8 satellite — PG commits batch along the lease-frame
        path)."""
        newly_reserved: List[int] = []
        ok = True
        by_node: List[Tuple[str, List[int]]] = []
        for idx, nid in enumerate(plan):
            if by_node and by_node[-1][0] == nid:
                by_node[-1][1].append(idx)
            else:
                by_node.append((nid, [idx]))
        for nid, idxs in by_node:
            if not ok:
                break
            node = self.nodes.get(nid)
            if node is None:
                ok = False
                break
            try:
                r = await self._node_client(node).call(
                    "reserve_bundles", pg_id=entry.pg_id,
                    items=[[i, entry.bundles[i]] for i in idxs],
                    wait_ms=wait_ms)
                results = list(r.get("results") or [])
            except Exception:
                results = []
                # the RPC failed on OUR side (connection drop) but the
                # agent-side handler may still be waiting — or may grant
                # later; make sure nothing stays carved out for an
                # attempt we are abandoning (best-effort: the agent also
                # rolls back grants whose caller connection closed)
                for i in idxs:
                    asyncio.ensure_future(self._abort_bundle_reservation(
                        nid, entry.pg_id, i))
            results += [{"ok": False}] * (len(idxs) - len(results))
            for i, rr in zip(idxs, results):
                if not rr.get("ok"):
                    ok = False
                    break
                if not rr.get("already"):
                    # only bundles reserved by THIS attempt may be rolled
                    # back; pre-existing ones carry live workloads
                    newly_reserved.append(i)
        if ok:
            return True, newly_reserved
        rollback: Dict[str, List[int]] = {}
        for idx in newly_reserved:
            rollback.setdefault(plan[idx], []).append(idx)
        for nid, idxs in rollback.items():
            node = self.nodes.get(nid)
            if node is not None:
                try:
                    await self._node_client(node).call(
                        "return_bundles", pg_id=entry.pg_id, indices=idxs)
                except Exception:
                    pass
        return False, []

    async def _abort_bundle_reservation(self, nid: str, pg_id: str,
                                        bundle_index: int):
        node = self.nodes.get(nid)
        if node is None:
            return
        try:
            await self._node_client(node).call(
                "cancel_bundle_reservation", pg_id=pg_id,
                bundle_index=bundle_index)
        except Exception:
            pass

    async def _on_pg_node_dead(self, node_id: str):
        """Bundles on a dead node are re-reserved elsewhere (non-strict) or
        the whole group goes back to PENDING."""
        for entry in self.placement_groups.values():
            if entry.state == PG_CREATED and node_id in entry.placements:
                entry.state = PG_PENDING
                self.mark_dirty()
                for idx, nid in enumerate(entry.placements):
                    if nid == node_id:
                        entry.placements[idx] = None
                asyncio.ensure_future(self._schedule_pg(entry))

    # ---- metrics + task events (observability plane) -----------------------

    async def _start_metrics(self, host: str) -> None:
        """Prometheus endpoint with control-plane gauges
        (reference: stats/metric_defs.cc via the reporter agent)."""
        from ray_tpu._private.metrics import (Gauge, Histogram,
                                              default_registry,
                                              start_metrics_http_server)

        nodes_g = Gauge("rt_head_nodes", "live nodes in the cluster")
        actors_g = Gauge("rt_head_actors", "actors by state")
        pgs_g = Gauge("rt_head_placement_groups", "placement groups by state")
        tasks_g = Gauge("rt_head_task_events", "task event records held")
        traces_g = Gauge("rt_head_traces", "traces held in the trace store")
        # per-phase task latency derived from the task-event timestamps:
        # queued (submitted→leased), leased (leased→running, i.e. the
        # push/dispatch leg), running (running→finished) — the breakdown
        # the MPMD-pipeline papers need for diagnosing stage stalls
        self._sched_hist = Histogram(
            "ray_tpu_task_sched_latency_seconds",
            "task scheduling latency by phase",
            boundaries=[0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1,
                        5, 30])
        # fed by the task-event plane on its own loop (Histogram is
        # internally locked, so cross-thread observes are safe)
        self._ev_plane.sched_hist = self._sched_hist

        from ray_tpu._private.metrics import autoscaler_metrics

        as_nodes_g, _as_events, _as_drain = autoscaler_metrics()

        def collect():
            nodes_g.set(len(self.nodes))
            draining = sum(1 for n in self.nodes.values() if n.draining)
            as_nodes_g.set(len(self.nodes) - draining,
                           tags={"state": "running"})
            as_nodes_g.set(draining, tags={"state": "draining"})
            as_nodes_g.set(
                float(self._autoscaler_status.get("pending_launches", 0)),
                tags={"state": "pending_launch"})
            # seed every state with 0 so a series whose count drops to
            # zero reports 0 instead of its stale last value
            states = {s: 0 for s in (PENDING, ALIVE, RESTARTING, DEAD)}
            for a in self.actors.values():
                states[a.state] = states.get(a.state, 0) + 1
            for s, n in states.items():
                actors_g.set(n, tags={"state": s})
            pstates = {s: 0 for s in (PG_PENDING, PG_CREATED, PG_REMOVED)}
            for p in self.placement_groups.values():
                pstates[p.state] = pstates.get(p.state, 0) + 1
            for s, n in pstates.items():
                pgs_g.set(n, tags={"state": s})
            ev = self._ev_plane.stats.payload
            tasks_g.set(ev["num_events"])
            traces_g.set(ev["num_traces"])

        # keep the handle so stop() can deregister: the closure pins the
        # whole head in the process-lifetime registry otherwise (the
        # in-process test harnesses would leak every head ever started)
        self._metrics_collector = collect
        default_registry.add_collector(collect)
        try:
            from ray_tpu._private import dashboard as _dash

            # /api/stack and /api/profile fan out over RPC: async route
            # handlers awaited by the server, with the query string
            # passed through (wants_query)
            def stack_route(query: str = ""):
                return self._http_stack(query)

            stack_route.wants_query = True

            def profile_route(query: str = ""):
                return self._http_profile(query)

            profile_route.wants_query = True

            def memory_route(query: str = ""):
                return self._http_memory(query)

            memory_route.wants_query = True
            self._metrics_server, self.metrics_port = \
                await start_metrics_http_server(
                    default_registry, host,
                    extra_routes={
                        "/": lambda: ("text/html",
                                      _dash.APP_HTML.encode()),
                        "/app.js": lambda: ("application/javascript",
                                            _dash.APP_JS.encode()),
                        "/api/state": self._render_state_json,
                        "/api/snapshot": self._render_snapshot_json,
                        "/api/timeline": self._render_timeline_json,
                        "/api/traces": self._render_traces_json,
                        # trailing slash = prefix route: the suffix is
                        # passed in (/api/traces/<trace_id>)
                        "/api/traces/": self._render_one_trace_json,
                        "/api/timeseries": self._render_timeseries_json,
                        "/api/stack": stack_route,
                        "/api/profile": profile_route,
                        "/api/memory": memory_route,
                        "/api/summary": self._render_summary_json,
                        "/api/autoscaler": self._render_autoscaler_json,
                    })
            self._dash_task = asyncio.ensure_future(self._dash_sample_loop())
        except Exception:
            self.metrics_port = 0  # observability must never block boot

    def _state_snapshot(self) -> Dict[str, Any]:
        actors = {}
        for a in self.actors.values():
            actors[a.state] = actors.get(a.state, 0) + 1
        return {
            "nodes": [n.table_entry() for n in self.nodes.values()],
            "actors_by_state": actors,
            "num_placement_groups": len(self.placement_groups),
            "num_task_events": self._ev_plane.stats.payload["num_events"],
            "kv_keys": len(self.kv),
        }

    def _render_state_json(self):
        import json as _json

        return "application/json", _json.dumps(self._state_snapshot(),
                                               default=str).encode()

    # ---- dashboard SPA data plane (reference: dashboard/ API routes
    # consumed by the React client; here /api/snapshot feeds the
    # single-file app in _private/dashboard.py) ---------------------------

    def _cpu_totals(self) -> Tuple[float, float]:
        avail = total = 0.0
        for n in self.nodes.values():
            total += n.resources.total.get("CPU")
            avail += n.resources.available.get("CPU")
        return avail, total

    def _tasks_finished_total(self) -> int:
        # monotonic terminal-transition count published by the task-
        # event plane — unlike the old store walk it cannot dip when
        # old records roll off the cap
        return int(self._ev_plane.stats.payload["finished_total"])

    async def _dash_sample_loop(self):
        """Every 2s append one sample to the sparkline ring (~5 min),
        and fold the head's own gauges into the time-series store next
        to the per-agent heartbeat summaries."""
        last_finished = self._tasks_finished_total()
        while True:
            await asyncio.sleep(2.0)
            try:
                avail, total = self._cpu_totals()
                finished = self._tasks_finished_total()
                task_rate = max(0, finished - last_finished)
                self._dash_series.append({
                    "ts": time.time(),
                    "nodes": len(self.nodes),
                    "cpus_avail": avail,
                    "actors_alive": sum(1 for a in self.actors.values()
                                        if a.state == ALIVE),
                    "task_rate": task_rate,
                })
                last_finished = finished
                now = time.time()
                ts = self._telem.ts_record
                ts("head", "loop_lag_seconds", self._head_loop_lag, now)
                ts("head", "nodes", len(self.nodes), now)
                ts("head", "cpus_avail", avail, now)
                ts("head", "task_rate", task_rate, now)
                if self.shards is not None and self.shards.sharded:
                    # per-shard ingest-loop lag beside the head's own:
                    # `rtpu status --watch` sparklines show which plane
                    # is hot without a metrics scrape
                    ts("head", "shard_lag_task_events",
                       self.shards.task_events.loop_lag, now)
                    ts("head", "shard_lag_telemetry",
                       self.shards.telemetry.loop_lag, now)
            except Exception:
                pass

    async def _render_snapshot_json(self):
        import json as _json

        # record/trace copies are made ON the task-event plane's loop
        # (run_sync) — its merge mutates records in place, so reading
        # live dicts from this loop could tear mid-serialization
        recent, traces = await self.shards.task_events.run_sync(
            lambda: (self._ev_plane.recent_records(200),
                     self._ev_plane.trace_store.summaries(50)))
        jobs = []
        try:
            idx = self.kv.get("job:index")
            for job_id in _json.loads(idx) if idx else []:
                raw = self.kv.get(f"job:{job_id}:status")
                if raw:
                    jobs.append(_json.loads(raw))
        except Exception:
            pass
        avail, total = self._cpu_totals()
        snap = {
            "nodes": [n.table_entry() for n in self.nodes.values()],
            "actors": [a.info() for a in self.actors.values()],
            "tasks": recent,
            "placement_groups": [p.info(self.nodes)
                                 for p in self.placement_groups.values()],
            "jobs": jobs,
            "traces": traces,
            "series": list(self._dash_series),
            "autoscaler": self._autoscaler_view(),
            "shards": self._shard_info(),
            "summary": {
                "cpus_avail": round(avail, 2), "cpus_total": round(total, 2),
                "actors_alive": sum(1 for a in self.actors.values()
                                    if a.state == ALIVE),
                "task_rate": (self._dash_series[-1]["task_rate"]
                              if self._dash_series else 0),
            },
        }
        return "application/json", _json.dumps(snap, default=str).encode()

    def _shard_info(self) -> Dict[str, Any]:
        """Shard topology + per-loop lag for the dashboard and `rtpu
        status`: which ingest planes exist, whether they run on their
        own threads, and how laggy each loop currently is."""
        if self.shards is None:
            return {"count": 0, "planes": {}}
        ev = self._ev_plane.stats.payload
        return {
            "count": self.shards.count,
            "planes": {
                "task_events": {
                    "own_thread": self.shards.task_events.own_thread,
                    "lag_s": round(self.shards.task_events.loop_lag, 4),
                    "events": ev["num_events"],
                    "dropped": ev["dropped_total"],
                },
                "telemetry": {
                    "own_thread": self.shards.telemetry.own_thread,
                    "lag_s": round(self.shards.telemetry.loop_lag, 4),
                    "dir_version_total": self.dir.version_total(),
                },
            },
        }

    async def _render_timeline_json(self):
        """Chrome-trace events straight off the task-event store (same
        shape as util.state.timeline / `rtpu timeline`): duration
        slices, submit→execute flow arrows, and instant events for
        queue-time failures."""
        import json as _json

        from ray_tpu.util.state.api import task_timeline_events

        records = await self.shards.task_events.run_sync(
            self._ev_plane.all_records)
        events = task_timeline_events(records)
        return "application/json", _json.dumps(events).encode()

    async def rpc_task_events(self, events: List[Dict[str, Any]]):
        """Workers flush task state transitions here in batches
        (reference: task_event_buffer.h -> gcs_task_manager.h).

        Routed to the task-event shard's loop (rpc_op_loops): frames
        land in the plane's inbox and merge ONCE per loop tick — with
        many clients flushing a burst simultaneously, the merge +
        cap-trim + latency-histogram pass runs over all of them
        together, and none of it touches the scheduling loop."""
        self._ev_plane.ingest(events)
        return {"ok": True}

    async def rpc_list_tasks(self, state: str = "", name: str = "",
                             limit: int = 1000):
        # routed to the task-event shard: reads see a store no merge is
        # concurrently mutating, and the walk costs the scheduling loop
        # nothing
        return {"tasks": self._ev_plane.list_tasks(state, name, limit)}

    # ---- distributed-trace store (see tracing.TraceStore, owned by the
    # task-event plane; reference: ray.util.tracing exports spans to an
    # external collector — here a bounded in-head store queryable via
    # RPC, HTTP and CLI) ---------------------------------------------------

    async def rpc_trace_spans(self, spans: List[Dict[str, Any]]):
        """Workers flush finished spans here alongside task events
        (routed to the same shard loop, so span ingest and event merge
        never interleave mid-structure)."""
        self._ev_plane.ingest_spans(spans)
        return {"ok": True}

    async def rpc_list_traces(self, limit: int = 100):
        store = self._ev_plane.trace_store
        return {"traces": store.summaries(limit),
                "spans_dropped": store.spans_dropped}

    async def rpc_get_trace(self, trace_id: str):
        trace = self._ev_plane.trace_store.detail(trace_id)
        if trace is None:
            return {"found": False}
        return {"found": True, "trace": trace}

    async def _render_traces_json(self):
        import json as _json

        traces = await self.shards.task_events.run_sync(
            lambda: self._ev_plane.trace_store.summaries(100))
        return "application/json", _json.dumps(
            traces, default=str).encode()

    async def _render_one_trace_json(self, trace_id: str = ""):
        import json as _json

        tid = trace_id.strip("/")
        trace = await self.shards.task_events.run_sync(
            lambda: self._ev_plane.trace_store.detail(tid))
        if trace is None:
            body = _json.dumps({"error": f"no trace {trace_id!r}"})
            return "application/json", body.encode()
        return "application/json", _json.dumps(trace, default=str).encode()

    # ---- live introspection (see _private/profiling.py): cluster-wide
    # stack dumps, routed sampling profiles, and the head time-series
    # ring behind /api/timeseries (reference roles: `ray stack`,
    # profile_manager.py, and the dashboard's node-stats timeline) ---------

    # (the time-series ring lives on the telemetry plane — see
    # head_shards.TelemetryPlane.ts_record/ts_tail/timeseries_payload;
    # rpc_timeseries is routed to that plane's loop)

    async def rpc_timeseries(self):
        return self._telem.timeseries_payload()

    def _render_timeseries_json(self):
        import json as _json

        # the ring is internally locked: safe to render from this loop
        return "application/json", _json.dumps(
            self._telem.timeseries_payload()).encode()

    async def rpc_cluster_stack(self, target: str = "",
                                timeout_s: float = 5.0):
        """Live stack dumps across the cluster: the head process plus
        every agent's node_stacks fan-out (agent + its pooled workers).
        ``target`` filters to one node by id prefix, or to "head"."""
        from ray_tpu._private.profiling import proc_stack_payload

        out: Dict[str, Any] = {"nodes": {}}
        if not target or target == "head":
            out["head"] = proc_stack_payload()
        if target == "head":
            return out

        async def one(node: _NodeEntry):
            try:
                out["nodes"][node.node_id] = await self._node_client(
                    node).call("node_stacks", timeout_s=timeout_s,
                               timeout=timeout_s + 5.0)
            except Exception as e:
                out["nodes"][node.node_id] = {
                    "error": f"{type(e).__name__}: {e}"}

        nodes = list(self.nodes.values())
        if target:
            matched = [n for n in nodes if n.node_id.startswith(target)]
            # a worker-id target matches no node: fan out everywhere and
            # let the caller filter its workers by id prefix
            nodes = matched or nodes
        await asyncio.gather(*(one(n) for n in nodes))
        return out

    async def rpc_profile_target(self, target: str = "head", hz: float = 0,
                                 duration_s: float = 2.0,
                                 fmt: str = "collapsed"):
        """Route a sampling-profiler run to a process: "head", a node id
        prefix (profiles that node's agent), or a worker id prefix
        (proxied by the agent that pools it).  Blocks for the duration
        and returns the collapsed/speedscope output."""
        duration_s = min(float(duration_s),
                         float(config.profiler_max_duration_s))
        if not target or target == "head":
            return await self.rpc_profile(op="run", hz=hz,
                                          duration_s=duration_s, fmt=fmt)
        node = next((n for n in self.nodes.values()
                     if n.node_id.startswith(target)), None)
        if node is not None:
            return await self._node_client(node).call(
                "profile", op="run", hz=hz, duration_s=duration_s, fmt=fmt,
                timeout=duration_s + 30.0)
        for n in list(self.nodes.values()):
            try:
                reply = await self._node_client(n).call(
                    "profile_worker", worker=target, hz=hz,
                    duration_s=duration_s, fmt=fmt,
                    timeout=duration_s + 35.0)
            except Exception:
                continue
            if reply.get("found"):
                reply["node_id"] = n.node_id
                return reply
        return {"ok": False,
                "error": f"no process matches target {target!r} "
                         f"(expected \"head\", a node id prefix, or a "
                         f"worker id prefix)"}

    @staticmethod
    def _query_params(query: str) -> Dict[str, str]:
        from urllib.parse import parse_qs

        return {k: v[-1] for k, v in parse_qs(query or "").items()}

    async def _http_stack(self, query: str = ""):
        import json as _json

        p = self._query_params(query)
        out = await self.rpc_cluster_stack(target=p.get("target", ""))
        return "application/json", _json.dumps(out, default=str).encode()

    async def _http_profile(self, query: str = ""):
        import json as _json

        p = self._query_params(query)
        fmt = p.get("format", "speedscope")
        out = await self.rpc_profile_target(
            target=p.get("target", "head"),
            hz=float(p.get("hz", 0) or 0),
            duration_s=float(p.get("duration", 2.0)),
            fmt=fmt)
        if out.get("ok") and fmt == "speedscope":
            # the profile field already IS speedscope JSON: serve it
            # directly so a browser download opens in speedscope.app
            return "application/json", out["profile"].encode()
        return "application/json", _json.dumps(out, default=str).encode()

    async def rpc_metrics_port(self):
        return {"port": self.metrics_port}

    async def rpc_list_objects(self, limit: int = 1000):
        """Fan out to every agent's plasma store (reference:
        state_aggregator.py querying raylets via GetObjectsInfo)."""
        async def one(node):
            try:
                r = await self._node_client(node).call(
                    "list_objects", limit=limit, timeout=10.0)
            except Exception:
                return []
            objs = r.get("objects", [])
            for o in objs:
                o["node_id"] = node.node_id
            return objs

        # concurrent fan-out: one slow/unreachable agent bounds latency,
        # it doesn't sum across nodes
        results = await asyncio.gather(
            *(one(n) for n in list(self.nodes.values())))
        out: List[Dict[str, Any]] = [o for objs in results for o in objs]
        return {"objects": out[:limit]}

    # ---- memory & object accounting (rtpu memory / rtpu summary;
    # reference: `ray memory` + `ray summary` — state_aggregator.py
    # joining per-worker ownership dumps with per-raylet store stats) -------

    def _driver_client(self, addr: Tuple[str, int]) -> RpcClient:
        addr = (addr[0], addr[1])
        c = self._driver_clients.get(addr)
        if c is None or c.dead:
            if c is not None:
                asyncio.ensure_future(c.close())
            c = RpcClient(addr[0], addr[1], label=f"driver-{addr[1]}")
            self._driver_clients[addr] = c
        return c

    def _drop_driver(self, jid: str, addr: Tuple[str, int]) -> None:
        """Forget a driver whose process is gone: its callback address
        and pooled client.  Its still-pinned primary bytes now have no
        claiming owner — the dead-owner tripwire's job."""
        self.driver_addrs.pop(jid, None)
        c = self._driver_clients.pop((addr[0], addr[1]), None)
        if c is not None:
            asyncio.ensure_future(c.close())

    async def _memory_view(self, top_n: int = 0,
                           limit: int = 0) -> Dict[str, Any]:
        """Single-flight wrapper over the cluster fan-out: the 5s scan
        loop, dashboard viewers and CLI/state callers all want the same
        join — concurrent requests with the same bounds share ONE
        in-flight fan-out instead of each dialing every agent, worker
        and driver (callers treat the returned view as read-only)."""
        key = (int(top_n), int(limit))
        fut = self._memview_inflight.get(key)
        if fut is None:
            fut = asyncio.ensure_future(
                self._memory_view_fanout(top_n=top_n, limit=limit))
            self._memview_inflight[key] = fut
            fut.add_done_callback(
                lambda _f, k=key: self._memview_inflight.pop(k, None))
        return await asyncio.shield(fut)

    async def _memory_view_fanout(self, top_n: int = 0,
                                  limit: int = 0) -> Dict[str, Any]:
        """Join the cluster's memory accounting into one view: per-node
        store byte breakdowns + object tables (agent fan-out, each agent
        adding its pooled workers' reference summaries) + registered
        drivers' reference summaries, then run the leak tripwires over
        the join.  Bounded everywhere: `limit` refs per owner, `top_n`
        objects in the joined table."""
        top_n = int(top_n) or int(config.memory_view_top_n)
        limit = int(limit) or int(config.memory_summary_max_refs)
        ttl = float(config.object_leak_ttl_s)
        node_payloads: Dict[str, Dict[str, Any]] = {}
        owner_summaries: List[Dict[str, Any]] = []
        fanout_errors: List[str] = []

        async def one_node(node: _NodeEntry):
            try:
                node_payloads[node.node_id] = await self._node_client(
                    node).call("node_memory", limit=limit, timeout=15.0)
            except Exception as e:
                fanout_errors.append(f"node {node.node_id[:12]}: {e}")

        from ray_tpu._private.rpc import ConnectionLost

        async def one_driver(jid: str, addr: Tuple[str, int]):
            try:
                try:
                    s = await self._driver_client(addr).call(
                        "memory_summary", limit=limit, timeout=5.0)
                except ConnectionLost:
                    # the POOLED connection died — which also happens
                    # when a transient reset severs a socket under a
                    # live driver.  Verify death with one fresh dial
                    # before trusting it as a death signal.
                    old = self._driver_clients.pop((addr[0], addr[1]),
                                                   None)
                    if old is not None:
                        asyncio.ensure_future(old.close())
                    s = await self._driver_client(addr).call(
                        "memory_summary", limit=limit, timeout=5.0)
                s["job_id"] = jid
                owner_summaries.append(s)
            except asyncio.TimeoutError:
                # a TIMEOUT is a busy driver, not a death signal — and on
                # 3.11+ TimeoutError subclasses OSError, so it must be
                # caught BEFORE the process-GONE branch below or a slow
                # driver gets permanently dropped and its objects flagged
                fanout_errors.append(f"driver {jid[:12]}: timeout")
            except (ConnectionLost, ConnectionRefusedError):
                # process GONE — a refused or severed FRESH dial is
                # real death evidence: its owned objects now have no
                # live owner, exactly what the dead-owner tripwire
                # flags.  Drop it so churned drivers don't accumulate.
                self._drop_driver(jid, addr)
            except OSError as e:
                # any other OSError is a LOCAL dial failure (fd
                # pressure, ENOBUFS) and says nothing about the driver:
                # a gap, never a death signal
                fanout_errors.append(f"driver {jid[:12]}: {e!r:.60}")
            except Exception as e:
                # alive but not answering (busy loop, slow box): its
                # refs are a GAP, not a death signal — the join is
                # partial and absence-of-owner must not be trusted
                fanout_errors.append(f"driver {jid[:12]}: {e!r:.60}")

        await asyncio.gather(
            *(one_node(n) for n in list(self.nodes.values())),
            *(one_driver(j, a) for j, a in list(self.driver_addrs.items())))
        for p in node_payloads.values():
            for wid, s in (p.get("workers") or {}).items():
                if isinstance(s, dict) and not s.get("error"):
                    owner_summaries.append(s)
                else:
                    fanout_errors.append(f"worker {wid[:12]}")

        # owner join: oid -> owning worker + call-site.  `complete` means
        # every reachable owner reported an untruncated table, every
        # node reported its full object list, and no agent/worker/driver
        # fan-out failed — only then can "no owner claims this object"
        # be trusted as a death signal rather than a gap.
        if self._gapped_driver_conns:
            fanout_errors.append(
                f"{len(self._gapped_driver_conns)} driver(s) with "
                f"unreachable loopback callback")
        complete = not fanout_errors and not self._driver_join_gap
        for nid, p in node_payloads.items():
            total = (p.get("breakdown") or {}).get("num_objects", 0)
            if total > len(p.get("objects") or ()):
                complete = False
                fanout_errors.append(
                    f"node {nid[:12]}: object list truncated "
                    f"({len(p['objects'])}/{total})")
        owned_by_oid: Dict[str, Dict[str, Any]] = {}
        live_channels: set = set()
        for s in owner_summaries:
            if s.get("truncated"):
                complete = False
            for r in s.get("owned") or ():
                owned_by_oid[r["oid"]] = {
                    "worker_id": s.get("worker_id", ""),
                    "kind": s.get("kind", ""),
                    "call_site": r.get("call_site", ""),
                    "name": r.get("name", ""),
                    "size": r.get("size", 0),
                }
            live_channels.update(s.get("channels") or ())

        objects: List[Dict[str, Any]] = []
        leaks: Dict[str, Any] = {"dead_owner": [], "borrowed_ttl": [],
                                 "channel_slots": [],
                                 "partial": not complete,
                                 "ttl_s": ttl}
        store_object_bytes = attributed_bytes = 0
        size_by_oid: Dict[str, int] = {}
        # TTL clocks run from first-seen-unclaimed, tracked only across
        # COMPLETE scans (absence-of-owner means nothing on a partial
        # one, and a partial blip must not reset a running clock)
        now = time.time()
        seen_unclaimed: set = set()

        def unclaimed_past_ttl(oid: str) -> Tuple[bool, float]:
            t0 = self._unclaimed_since.setdefault(oid, now)
            seen_unclaimed.add(oid)
            return now - t0 > ttl, now - t0

        for nid, p in node_payloads.items():
            for o in p.get("objects") or ():
                o = dict(o)
                o["node_id"] = nid
                size_by_oid[o["object_id"]] = o.get("size", 0)
                own = owned_by_oid.get(o["object_id"])
                if own is not None:
                    o["owner"] = {k: own[k] for k in
                                  ("worker_id", "kind", "call_site", "name")}
                objects.append(o)
                if o.get("freed") or not o.get("sealed"):
                    continue
                if o.get("channel"):
                    if complete and o["object_id"] not in live_channels:
                        past, unclaimed_s = unclaimed_past_ttl(
                            o["object_id"])
                        if past:
                            leaks["channel_slots"].append({
                                "object_id": o["object_id"],
                                "node_id": nid, "size": o["size"],
                                "age_s": o["age_s"],
                                "unclaimed_s": round(unclaimed_s, 1)})
                    continue
                store_object_bytes += o["size"]
                if own is not None:
                    attributed_bytes += o["size"]
                elif complete and o.get("primary"):
                    # primary bytes no live owner claims: nobody will
                    # ever send the store_free for them
                    past, unclaimed_s = unclaimed_past_ttl(o["object_id"])
                    if past:
                        leaks["dead_owner"].append({
                            "object_id": o["object_id"], "node_id": nid,
                            "size": o["size"], "age_s": o["age_s"],
                            "unclaimed_s": round(unclaimed_s, 1),
                            "pins": o.get("pins", 0)})
        if complete:
            # an oid freed or claimed again resets its clock; pruning
            # only on complete scans keeps the map bounded by the live
            # unclaimed population
            self._unclaimed_since = {
                k: v for k, v in self._unclaimed_since.items()
                if k in seen_unclaimed}
        for s in owner_summaries:
            for r in s.get("borrowed") or ():
                if r.get("age_s", 0) > ttl:
                    own = owned_by_oid.get(r["oid"])
                    # borrowers don't know sizes — backfill from the
                    # store entry or the owner's own table
                    size = (r.get("size") or size_by_oid.get(r["oid"])
                            or (own or {}).get("size", 0))
                    leaks["borrowed_ttl"].append({
                        "object_id": r["oid"],
                        "worker_id": s.get("worker_id", ""),
                        "size": size, "age_s": r["age_s"],
                        "owner_known": own is not None})
        # an object can trip more than one wire (dead owner AND a stale
        # borrow) — count its bytes once
        leaked_by_oid: Dict[str, int] = {}
        for kind in ("dead_owner", "borrowed_ttl", "channel_slots"):
            for e in leaks[kind]:
                leaked_by_oid[e["object_id"]] = max(
                    leaked_by_oid.get(e["object_id"], 0), e["size"])
        leaks["leaked_bytes"] = sum(leaked_by_oid.values())
        objects.sort(key=lambda o: -o.get("size", 0))
        owners = [{"worker_id": s.get("worker_id", ""),
                   "kind": s.get("kind", ""),
                   "node_id": s.get("node_id", ""),
                   "job_id": s.get("job_id", ""),
                   "num_owned": s.get("num_owned", 0),
                   "num_borrowed": s.get("num_borrowed", 0),
                   "owned_bytes": s.get("owned_bytes", 0)}
                  for s in owner_summaries]
        return {
            "nodes": {nid: p.get("breakdown", {})
                      for nid, p in node_payloads.items()},
            "objects": objects[:top_n],
            "num_objects": len(objects),
            "store_object_bytes": store_object_bytes,
            "attributed_bytes": attributed_bytes,
            "owners": owners,
            "leaks": leaks,
            "errors": fanout_errors,
            "ts": time.time(),
        }

    async def rpc_memory_view(self, top_n: int = 0, limit: int = 0):
        return await self._memory_view(top_n=top_n, limit=limit)

    async def _memory_scan_loop(self):
        """Leak tripwire: periodically run the joined memory view and
        publish per-kind leaked bytes as ray_tpu_object_leaked_bytes.
        The gauge is re-set every scan, so cleaned-up leaks drop it back
        to 0 within one interval."""
        from ray_tpu._private.metrics import (memory_scan_partial_gauge,
                                              object_leaked_bytes_gauge)

        gauge = object_leaked_bytes_gauge()
        partial_gauge = memory_scan_partial_gauge()
        kinds = {"dead_owner": "dead_owner", "borrowed_ttl": "borrowed_ttl",
                 "channel_slots": "channel_slot"}
        while True:
            await asyncio.sleep(
                max(0.1, float(config.memory_scan_interval_s)))
            try:
                view = await self._memory_view()
            except Exception:
                continue
            leaks = view.get("leaks") or {}
            partial = bool(leaks.get("partial"))
            # partialness is its own signal: while 1, leak detection is
            # suspended and the held leak values below are stale
            partial_gauge.set(1.0 if partial else 0.0)
            # EVERY kind can false-all-clear on a partial join:
            # dead_owner/channel_slots are emptied by the complete-gate,
            # and an unreachable BORROWER empties its borrowed_ttl
            # records — hold the last COMPLETE values (gauge and
            # summary banner alike) rather than dropping a live alert
            # to 0
            if partial:
                prev = self._last_memory_scan
                self._last_memory_scan = {
                    "ts": view.get("ts"), "partial": True,
                    "leaked_bytes": prev.get("leaked_bytes", 0),
                    "counts": prev.get("counts",
                                       {k: 0 for k in kinds}),
                }
                continue
            for key, label in kinds.items():
                gauge.set(
                    sum(e.get("size", 0) for e in leaks.get(key, ())),
                    tags={"kind": label})
            self._last_memory_scan = {
                "ts": view.get("ts"),
                "partial": False,
                "leaked_bytes": leaks.get("leaked_bytes", 0),
                "counts": {k: len(leaks.get(k, ())) for k in kinds},
            }

    @staticmethod
    def _percentiles(vals: List[float]) -> Optional[Dict[str, Any]]:
        if not vals:
            return None
        s = sorted(vals)
        n = len(s)
        return {"count": n,
                "p50_ms": round(s[n // 2] * 1000, 3),
                "p99_ms": round(s[min(n - 1, int(n * 0.99))] * 1000, 3),
                "mean_ms": round(sum(s) / n * 1000, 3),
                "max_ms": round(s[-1] * 1000, 3)}

    async def _cluster_summary(self) -> Dict[str, Any]:
        """`rtpu summary`: per-function task aggregates (state counts +
        queued/running percentiles, computed by the task-event plane on
        its own loop), actor counts + per-method call counts, and the
        per-node object-store rollup from heartbeat breakdowns.  No
        cluster fan-out — cheap enough to poll."""
        tasks, methods = await self.shards.task_events.run_sync(
            self._ev_plane.summarize_tasks)
        kind_names = {NORMAL_TASK: "task", ACTOR_CREATION_TASK:
                      "actor_creation", ACTOR_TASK: "actor_method"}
        out_tasks = {
            name: {"kind": kind_names.get(row["kind"], str(row["kind"])),
                   "states": row["states"],
                   "queued": self._percentiles(row["queued_s"]),
                   "running": self._percentiles(row["running_s"])}
            for name, row in tasks.items()}
        actor_states: Dict[str, int] = {}
        for a in self.actors.values():
            actor_states[a.state] = actor_states.get(a.state, 0) + 1
        node_mem = {nid: dict(n.memory) for nid, n in self.nodes.items()
                    if n.memory}
        objects = {
            "nodes": node_mem,
            "total_arena_used": sum(m.get("arena_used", 0)
                                    for m in node_mem.values()),
            "total_pinned_bytes": sum(m.get("pinned_bytes", 0)
                                      for m in node_mem.values()),
            "total_spilled_bytes": sum(m.get("spilled_bytes", 0)
                                       for m in node_mem.values()),
            "total_channel_bytes": sum(m.get("channel_bytes", 0)
                                       for m in node_mem.values()),
            "total_objects": sum(m.get("num_objects", 0)
                                 for m in node_mem.values()),
        }
        return {"tasks": out_tasks,
                "actors": {"by_state": actor_states,
                           "num_actors": len(self.actors),
                           "methods": methods},
                "objects": objects,
                "last_leak_scan": dict(self._last_memory_scan),
                "ts": time.time()}

    async def rpc_cluster_summary(self):
        return await self._cluster_summary()

    async def _http_memory(self, query: str = ""):
        import json as _json

        p = self._query_params(query)
        out = await self._memory_view(top_n=int(p.get("top", 0) or 0))
        return "application/json", _json.dumps(out, default=str).encode()

    async def _render_summary_json(self):
        import json as _json

        return "application/json", _json.dumps(
            await self._cluster_summary(), default=str).encode()

    # ---- autoscaler --------------------------------------------------------

    def _scalable_shapes(self) -> List[Dict[str, float]]:
        """Resource totals of node types the autoscaler can still launch
        (lets agents park infeasible-but-scalable demands instead of
        failing them; reference: autoscaler hints in load_metrics)."""
        shapes: List[Dict[str, float]] = []
        for t in self._autoscaler_types.values():
            shapes.append(dict(t.get("resources", {})))
        return shapes

    async def rpc_register_autoscaler(self, node_types: Dict[str, Any]):
        """An autoscaler announces the node types it can launch
        (reference: monitor.py registering with GCS).  Idempotent — the
        autoscaler re-registers every pass, so a restarted head relearns
        the types within one update period."""
        if dict(node_types) == self._autoscaler_types:
            return {"ok": True, "epoch": self.dir.epoch}
        self._autoscaler_types = dict(node_types)
        self._cluster_version += 1
        self.mark_dirty()
        self._broadcast_cluster_view()
        return {"ok": True, "epoch": self.dir.epoch}

    async def rpc_autoscaler_state(self):
        """Aggregate demand + supply snapshot for the autoscaler loop
        (reference: gcs_autoscaler_state_manager.h GetClusterResourceState)."""
        pending_pg_bundles: List[Dict[str, Any]] = []
        for pg in self.placement_groups.values():
            if pg.state == PG_PENDING:
                for idx, nid in enumerate(pg.placements):
                    if nid is None:
                        pending_pg_bundles.append(
                            {"pg_id": pg.pg_id, "strategy": pg.strategy,
                             "resources": pg.bundles[idx]})
        pending_actors: List[Dict[str, float]] = []
        for actor in self.actors.values():
            if actor.state in (PENDING, RESTARTING):
                try:
                    ts = TaskSpec.from_wire(actor.spec_wire)
                    pending_actors.append(ts.resource_set().to_dict())
                except Exception:
                    pass
        return {
            "nodes": [
                {"node_id": n.node_id, "is_head_node": n.is_head_node,
                 "total": n.resources.total.to_dict(),
                 "available": n.resources.available.to_dict(),
                 "pending": n.pending_demands,
                 "draining": n.draining,
                 "heartbeat_age_s": time.monotonic() - n.last_heartbeat}
                for n in self.nodes.values()],
            "pending_pg_bundles": pending_pg_bundles,
            "pending_actors": pending_actors,
        }

    async def rpc_autoscaler_snapshot(self):
        """The v2 autoscaler input: the v1 demand/supply state plus the
        signals prior subsystems built — lease-queue-depth trends from
        the PR-6 time-series ring (hysteresis input), scheduler-latency
        p99 from the task-event store (SLO pressure), per-node store
        byte breakdowns from PR-9 memory accounting (drain-victim
        bin-packing), Serve/LLM queue pressure from the heartbeat gauge
        summaries, and live drain records.  ``epoch`` is the head's
        boot token: a change tells the autoscaler to re-register its
        node types (the DeltaReporter epoch-handshake pattern).

        Assembled from shard-published state: demand/supply from the
        scheduling core's OWN tables (this loop owns them), the SLO p99
        from the task-event plane's versioned stats snapshot, and ring
        trends through the telemetry plane's locked ts_tail — the old
        walk over the live task-event store from this loop is gone."""
        snap = await self.rpc_autoscaler_state()
        by_id = {n.node_id: n for n in self.nodes.values()}
        for n_out in snap["nodes"]:
            n = by_id.get(n_out["node_id"])
            if n is not None:
                mem = n.memory or {}
                n_out["memory"] = {
                    "arena_used": mem.get("arena_used", 0),
                    "arena_free": mem.get("arena_free", 0),
                    "num_objects": mem.get("num_objects", 0),
                }
        snap["epoch"] = self.dir.epoch
        ev_version, ev_stats = self._ev_plane.stats.read()
        ts_tail = self._telem.ts_tail
        snap["signals"] = {
            "lease_queue_depth": ts_tail("lease_queue_depth"),
            "sched_queued_p99_ms": ev_stats["queued_p99_ms"],
            "task_events_version": ev_version,
            "tasks_finished_total": ev_stats["finished_total"],
            "serve": {
                "llm_queue_depth": ts_tail("llm_queue_depth", k=5),
                "llm_tokens_per_step": ts_tail("llm_tokens_per_step",
                                               k=5),
            },
        }
        snap["shards"] = self._shard_info()
        snap["drains"] = {nid: dict(rec)
                          for nid, rec in self._drains.items()}
        return snap

    async def rpc_autoscaler_report(self, status: Optional[Dict[str, Any]]
                                    = None):
        """The autoscaler's per-pass status push: pending launches,
        nodes it is draining, the last decision and why — stored for
        /api/autoscaler and `rtpu status`, with scale-event deltas
        folded into ray_tpu_autoscaler_scale_events_total."""
        st = dict(status or {})
        st["ts"] = time.time()
        deltas = st.pop("events_delta", None) or {}
        try:
            from ray_tpu._private.metrics import autoscaler_metrics

            _g, events_c, _h = autoscaler_metrics()
            for kind in ("up", "down"):
                n = int(deltas.get(kind, 0))
                if n > 0:
                    events_c.inc(n, tags={"kind": kind})
        except Exception:
            pass
        self._autoscaler_status = st
        return {"ok": True, "epoch": self.dir.epoch}

    def _autoscaler_view(self) -> Dict[str, Any]:
        """Shared payload behind rpc_autoscaler_status, /api/autoscaler
        and the `rtpu status` pane — the debuggability surface for
        scale events."""
        return {
            "report": dict(self._autoscaler_status),
            "registered_types": {k: dict(v) for k, v
                                 in self._autoscaler_types.items()},
            "draining": [n.node_id for n in self.nodes.values()
                         if n.draining],
            "drains": {nid: dict(rec)
                       for nid, rec in self._drains.items()},
            "ts": time.time(),
        }

    async def rpc_autoscaler_status(self):
        return self._autoscaler_view()

    def _render_autoscaler_json(self):
        import json as _json

        return "application/json", _json.dumps(
            self._autoscaler_view(), default=str).encode()

    # ---- misc --------------------------------------------------------------

    async def rpc_ping(self):
        return {"pong": True, "time": time.time()}

    async def rpc_cluster_resources(self):
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for n in self.nodes.values():
            for k, v in n.resources.total.to_dict().items():
                total[k] = total.get(k, 0) + v
            for k, v in n.resources.available.to_dict().items():
                avail[k] = avail.get(k, 0) + v
        return {"total": total, "available": avail}

    async def rpc_shutdown_cluster(self):
        async def _bye():
            for n in list(self.nodes.values()):
                try:
                    await self._node_client(n).oneway("shutdown_node")
                except Exception:
                    pass
            await asyncio.sleep(0.05)
            self._shutdown.set()

        asyncio.ensure_future(_bye())
        return {"ok": True}


def main():
    """Entry point: `python -m ray_tpu._private.head --port-file PATH`."""
    import argparse
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--port-file", default="")
    ap.add_argument("--state-path", default="",
                    help="persist head tables here; reloaded on restart")
    args = ap.parse_args()

    async def run():
        svc = HeadService(state_path=args.state_path)
        port = await svc.start(args.host, args.port)
        if args.port_file:
            tmp = args.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(port))
            import os
            os.replace(tmp, args.port_file)
        sys.stdout.write(f"ray_tpu head listening on {args.host}:{port}\n")
        sys.stdout.flush()
        await svc.wait_for_shutdown()
        await svc.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
