"""ObjectRef: the user-facing future/handle to a distributed object.

Equivalent of the reference's ObjectRef
(reference: python/ray/includes/object_ref.pxi, ownership semantics in
src/ray/core_worker/reference_count.h): a ref pins the object while any
Python reference exists; serializing a ref into task args or other
objects transfers a *borrow* which is registered with the owner on
deserialization.

Pickling protocol: `__reduce__` routes through `_deserialize_ref`, which
(a) registers the materializing process as a borrower with the owner and
(b) reports the ref into the active serialization context so a submitter
can pin args until the task completes.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

_ctx = threading.local()


class SerializationContext:
    """Collects ObjectRefs encountered while (de)serializing a value."""

    def __init__(self):
        self.refs: List["ObjectRef"] = []

    def __enter__(self):
        stack = getattr(_ctx, "stack", None)
        if stack is None:
            stack = _ctx.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        _ctx.stack.pop()

    @staticmethod
    def current() -> Optional["SerializationContext"]:
        stack = getattr(_ctx, "stack", None)
        return stack[-1] if stack else None


class ObjectRef:
    __slots__ = ("_oid", "_owner_addr", "_node_addr", "_worker", "__weakref__")

    def __init__(self, oid: str, owner_addr: Optional[Tuple[str, int]] = None,
                 node_addr: Optional[Tuple[str, int]] = None,
                 _register: bool = True):
        self._oid = oid
        self._owner_addr = tuple(owner_addr) if owner_addr else None
        self._node_addr = tuple(node_addr) if node_addr else None
        self._worker = None
        if _register:
            from ray_tpu._private.worker import global_worker_or_none

            w = global_worker_or_none()
            if w is not None:
                self._worker = w
                w.register_local_ref(self)

    @property
    def oid(self) -> str:
        return self._oid

    @property
    def owner_addr(self) -> Optional[Tuple[str, int]]:
        return self._owner_addr

    @property
    def node_addr(self) -> Optional[Tuple[str, int]]:
        return self._node_addr

    def hex(self) -> str:
        return self._oid

    def __reduce__(self):
        ctx = SerializationContext.current()
        if ctx is not None:
            ctx.refs.append(self)
        return (_deserialize_ref, (self._oid, self._owner_addr, self._node_addr))

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._oid == self._oid

    def __hash__(self):
        return hash(self._oid)

    def __repr__(self):
        return f"ObjectRef({self._oid[:16]}…)"

    def __del__(self):
        w = self._worker
        if w is not None:
            try:
                w.unregister_local_ref(self)
            except Exception:
                pass

    # Direct (sync) iteration stays blocked so a for-loop over a ref
    # fails loudly; `await ref` resolves through the owner-loop
    # completion path (worker.get_async) without parking a thread.
    def __iter__(self):
        raise TypeError(
            "ObjectRef is not iterable; use ray_tpu.get(ref) to fetch the value")

    async def _resolve_async(self):
        from ray_tpu._private.worker import global_worker_or_none

        w = self._worker or global_worker_or_none()
        if w is None:
            raise RuntimeError(
                "ray_tpu is not initialized; cannot await an ObjectRef")
        return (await w.get_async([self]))[0]

    def __await__(self):
        return self._resolve_async().__await__()

    def future(self):
        """Schedule resolution on the running event loop; returns an
        asyncio.Task resolving to the value (reference: ObjectRef.future
        / as_future in the asyncio integration).  Must be called from a
        coroutine or loop callback."""
        import asyncio

        return asyncio.ensure_future(self._resolve_async())


def _deserialize_ref(oid: str, owner_addr, node_addr) -> ObjectRef:
    ref = ObjectRef(oid, owner_addr, node_addr)
    ctx = SerializationContext.current()
    if ctx is not None:
        ctx.refs.append(ref)
    return ref
