"""Bulk object-transfer plane: raw binary streams between node agents.

Equivalent role to the reference's object manager data plane
(reference: src/ray/object_manager/object_manager.h Push/Pull +
object_buffer_pool.h chunked reads): the survey is explicit that in Ray
"bulk data rides the object plane, never RPC" (rpc.py:8).  Control stays
on the msgpack RPC connection (obj_info pin/size lookup, obj_unpin);
object BYTES move here, on a dedicated listener with its own socket
pool, so a 256 MB pull can never head-of-line-block leases, heartbeats
or task pushes.

Wire protocol (one stream, any number of requests):

  request  (puller -> holder):  <u16 oid_len><u64 offset><u64 length>
                                <oid_len bytes of hex oid>
  response (holder -> puller):  <u8 status><u64 length>
                                <length raw payload bytes>   (status 0)

Status 0 = ok, 1 = object not found/unsealed (payload absent).
Responses come back in request order per stream, so a puller keeps
``object_transfer_window`` chunk requests in flight on one stream
(pipelined, no per-chunk round trip) and objects at or above
``object_transfer_parallel_threshold`` are striped across up to
``object_transfer_max_streams`` pooled connections.

Zero-copy discipline: the holder ``sendall``s straight from the arena
``memoryview`` (or an mmap of a disk-fallback file); the puller
``recv_into``s the pre-created plasma allocation (or an mmap of the
fallback file).  No intermediate ``bytes`` object exists on either side;
the only copies are the kernel's socket copies.

Thread model: the byte-moving loops run on plain BLOCKING sockets in
dedicated threads (holder: accept thread + thread per stream; puller:
executor threads, one per stripe).  Measured on this box, one blocking
stream moves ~5x what a non-blocking loop.sock_* implementation does —
every asyncio recv costs an epoll_ctl/epoll_wait round on top of the
recv itself, and syscalls dominate bulk transfer here.  It also means a
multi-hundred-MB transfer adds ZERO work to the node agent's event
loop, which keeps serving leases and heartbeats.
"""

from __future__ import annotations

import asyncio
import mmap
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import fault_injection

_REQ = struct.Struct("<HQQ")   # oid_len (| _WRITE_FLAG), offset, length
_RSP = struct.Struct("<BQ")    # status, length
_OK, _NOT_FOUND = 0, 1
_MAX_REQ_OID = 256
# high bit of oid_len marks a WRITE request: `length` payload bytes
# follow the oid and are stored at [offset, offset+length) of the named
# object.  Writes are only honored for channel slots (compiled-DAG
# mutable channels, see dag/channel.py) — immutable objects stay
# immutable on the wire.
_WRITE_FLAG = 0x8000
_IO_TIMEOUT_S = 60.0  # per socket op; a wedged peer must not pin a thread
_POOL_IDLE_S = 30.0   # drop pooled streams before the holder's idle
# timeout (_IO_TIMEOUT_S on its recv) can close them under us


class TransferError(Exception):
    """The holder could not serve a requested range (object vanished,
    stream died mid-transfer)."""


class _Rejected(Exception):
    """In-protocol refusal (status != OK) at a clean frame boundary —
    the stream stays usable and the request must NOT be retried."""


def _tune(sock: socket.socket) -> None:
    from ray_tpu._private.config import config

    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    buf = int(config.object_transfer_sock_buf_bytes)
    # syscalls bound throughput on this plane; big kernel buffers keep
    # the bytes moved per send()/recv() call large
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            sock.setsockopt(socket.SOL_SOCKET, opt, buf)
        except OSError:
            pass
    sock.settimeout(_IO_TIMEOUT_S)


def _recv_into(sock: socket.socket, view: memoryview) -> None:
    pos = 0
    while pos < len(view):
        n = sock.recv_into(view[pos:])
        if n == 0:
            raise TransferError("transfer stream closed mid-payload")
        pos += n


def _recv_exact(sock: socket.socket, size: int,
                eof_ok: bool = False) -> Optional[bytearray]:
    """Read exactly `size` bytes; None on clean EOF at a frame boundary
    (eof_ok), TransferError on EOF mid-frame."""
    buf = bytearray(size)
    view = memoryview(buf)
    pos = 0
    while pos < size:
        n = sock.recv_into(view[pos:])
        if n == 0:
            if pos == 0 and eof_ok:
                return None
            raise TransferError("transfer stream closed mid-frame")
        pos += n
    return buf


def _discard(sock: socket.socket, length: int) -> None:
    """Read and drop exactly `length` payload bytes through a small
    fixed scratch buffer."""
    scratch = bytearray(min(length, 256 * 1024))
    view = memoryview(scratch)
    left = length
    while left > 0:
        n = sock.recv_into(view[:min(left, len(scratch))])
        if n == 0:
            raise TransferError("transfer stream closed mid-payload")
        left -= n


class _MappedFile:
    """A read/write mmap of a disk-fallback object file, so disk objects
    move through the same view-based path as arena objects."""

    def __init__(self, path: str, size: int, writable: bool):
        self.last_used = time.monotonic()
        with open(path, "r+b" if writable else "rb") as f:
            prot = mmap.PROT_READ | (mmap.PROT_WRITE if writable else 0)
            self._mm = mmap.mmap(f.fileno(), size, mmap.MAP_SHARED, prot)
        self.view = memoryview(self._mm)

    def close(self) -> None:
        try:
            self.view.release()
        except Exception:
            pass
        try:
            self._mm.close()
        except Exception:
            pass


class ObjectTransferServer:
    """Holder side: serves ranges of sealed local objects.

    The puller pins the object over control RPC (obj_info with pin_for)
    before the first range request, so entries cannot be dropped or
    spilled out from under an in-flight send; the store's entry fields
    are therefore stable for the duration and safe to read from the
    serving threads.
    """

    def __init__(self, store):
        self.store = store
        self.port = 0
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: Dict[int, socket.socket] = {}
        self._lock = threading.Lock()
        self._stopped = False
        # disk-fallback objects mmap'd once per pull, not per chunk
        self._maps: Dict[str, _MappedFile] = {}
        self.bytes_out = 0

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((host, port))
        sock.listen(64)
        self._sock = sock
        self.port = sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rt-xfer-accept", daemon=True)
        self._accept_thread.start()
        return self.port

    async def stop(self) -> None:
        self._stopped = True
        if self._sock is not None:
            # shutdown BEFORE close: the accept thread blocked in
            # accept() holds the socket alive past close(), so the port
            # would keep accepting; shutdown wakes it deterministically
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        with self._lock:
            for m in self._maps.values():
                m.close()
            self._maps.clear()

    def _accept_loop(self):
        while not self._stopped:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if self._stopped:  # raced a stop() that landed mid-accept
                try:
                    conn.close()
                except OSError:
                    pass
                return
            _tune(conn)
            with self._lock:
                self._conns[conn.fileno()] = conn
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="rt-xfer-serve", daemon=True).start()

    def object_view(self, oid: str, offset: int,
                    length: int) -> Optional[memoryview]:
        """A memoryview over [offset, offset+length) of a sealed local
        object, or None if it cannot be served.  Disk-fallback objects
        are served from an mmap cached across the pull (dropped on
        obj_unpin via release(), LRU-trimmed otherwise) — shared by the
        bulk streams AND the legacy obj_chunk RPC path."""
        entry = self.store.objects.get(oid)
        if entry is None or not entry.sealed:
            return None
        if offset < 0 or length < 0 or offset + length > entry.size:
            return None
        entry.last_used = time.monotonic()
        if entry.location == "shm":
            base = entry.offset
            return self.store.arena.view[base + offset:base + offset + length]
        with self._lock:
            m = self._maps.get(oid)
            if m is None:
                try:
                    m = _MappedFile(entry.path, entry.size, writable=False)
                except OSError:
                    return None
                self._maps[oid] = m
                self._trim_maps()
            m.last_used = time.monotonic()
            return m.view[offset:offset + length]

    def _trim_maps(self, keep: int = 8) -> None:
        # caller holds self._lock
        while len(self._maps) > keep:
            oid = min(self._maps, key=lambda o: self._maps[o].last_used)
            self._maps.pop(oid).close()

    def cache_stats(self) -> Dict[str, int]:
        """Footprint of the across-pull disk mmap cache — one line of
        the node's memory breakdown (`rtpu memory`)."""
        with self._lock:
            return {"files": len(self._maps),
                    "bytes": sum(m.view.nbytes for m in self._maps.values())}

    def release(self, oid: str) -> None:
        """Pull finished (obj_unpin): drop any held disk mapping."""
        with self._lock:
            m = self._maps.pop(oid, None)
        if m is not None:
            m.close()

    def channel_view(self, oid: str, offset: int,
                     length: int) -> Optional[memoryview]:
        """A writable view over a CHANNEL slot — the only entries the
        push path may mutate.  Channels are permanently pinned and live
        in shm, so the arena range is stable for the write."""
        entry = self.store.objects.get(oid)
        if entry is None or not getattr(entry, "channel", False) \
                or entry.location != "shm":
            return None
        if offset < 0 or length < 0 or offset + length > entry.size:
            return None
        base = entry.offset
        return self.store.arena.view[base + offset:base + offset + length]

    def _serve_conn(self, sock: socket.socket):
        fd = sock.fileno()
        try:
            while True:
                hdr = _recv_exact(sock, _REQ.size, eof_ok=True)
                if hdr is None:
                    return
                oid_len, offset, length = _REQ.unpack(hdr)
                is_write = bool(oid_len & _WRITE_FLAG)
                oid_len &= ~_WRITE_FLAG
                if oid_len == 0 or oid_len > _MAX_REQ_OID:
                    raise TransferError(f"bad oid length {oid_len}")
                oid = bytes(_recv_exact(sock, oid_len)).decode()
                if is_write:
                    view = self.channel_view(oid, offset, length)
                    if view is None:
                        # drain the payload (bounded scratch, never an
                        # allocation of the peer-supplied length) to
                        # keep stream framing sane
                        _discard(sock, length)
                        sock.sendall(_RSP.pack(_NOT_FOUND, 0))
                        continue
                    _recv_into(sock, view)
                    sock.sendall(_RSP.pack(_OK, 0))
                    continue
                view = self.object_view(oid, offset, length)
                if view is None:
                    sock.sendall(_RSP.pack(_NOT_FOUND, 0))
                    continue
                chaos = fault_injection.decide("xfer.send", key=oid)
                if chaos is not None:
                    if chaos.action == "delay":
                        fault_injection.sleep_sync(chaos.delay_s)
                    elif chaos.action == "sever":
                        raise TransferError("chaos: stream severed")
                    elif chaos.action == "truncate":
                        # promise the full range, deliver half, die —
                        # the puller hits EOF mid-payload (TransferError)
                        # exactly as if the holder crashed mid-stripe
                        sock.sendall(_RSP.pack(_OK, length))
                        sock.sendall(view[:length // 2])
                        raise TransferError("chaos: truncated mid-stripe")
                    elif chaos.action == "corrupt":
                        # flip bytes in a COPY (never the arena itself)
                        buf = bytearray(view)
                        for i in range(0, len(buf), 997):
                            buf[i] ^= 0xFF
                        sock.sendall(_RSP.pack(_OK, length))
                        sock.sendall(buf)
                        self.bytes_out += length
                        continue
                sock.sendall(_RSP.pack(_OK, length))
                sock.sendall(view)
                self.bytes_out += length
        except (TransferError, OSError, socket.timeout):
            pass
        finally:
            with self._lock:
                self._conns.pop(fd, None)
            try:
                sock.close()
            except OSError:
                pass


class ObjectTransferClient:
    """Puller side: a small pool of streams to ONE holder's transfer
    server; concurrent fetches check sockets out of the pool.  The
    blocking per-stripe loops run on executor threads so the calling
    event loop never blocks."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._free: List[Tuple[socket.socket, float]] = []  # (sock, checkin)
        self._lock = threading.Lock()
        self.closed = False

    def _checkout(self) -> Tuple[socket.socket, bool]:
        """A stream to the holder: (socket, fresh).  Pooled sockets past
        the idle horizon are discarded — the holder has likely timed
        them out already."""
        from ray_tpu._private.config import config

        now = time.monotonic()
        with self._lock:
            # sweep the WHOLE pool, not just popped entries: an old
            # socket pinned under a frequently-reused one would
            # otherwise sit in CLOSE_WAIT forever once the holder's
            # idle timeout closes its end
            fresh_enough = []
            stale = []
            for sock, ts in self._free:
                (stale if now - ts > _POOL_IDLE_S else
                 fresh_enough).append((sock, ts))
            self._free = fresh_enough
            picked = self._free.pop() if self._free else None
        for sock, _ts in stale:
            try:
                sock.close()
            except OSError:
                pass
        if picked is not None:
            return picked[0], False
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        _tune(sock)
        sock.settimeout(float(config.rpc_connect_timeout_s))
        try:
            sock.connect((self.host, self.port))
        except OSError:
            sock.close()
            raise
        sock.settimeout(_IO_TIMEOUT_S)
        return sock, True

    def _checkin(self, sock: socket.socket) -> None:
        with self._lock:
            if not self.closed:
                self._free.append((sock, time.monotonic()))
                return
        sock.close()

    def close(self) -> None:
        with self._lock:
            self.closed = True
            free, self._free = self._free, []
        for sock, _ts in free:
            try:
                sock.close()
            except OSError:
                pass

    def _sync_round(self, request_fn):
        """One blocking request/response round on a pooled stream,
        retrying on a fresh stream when a POOLED one turns out dead
        (channel range reads/writes are idempotent).  `request_fn(sock)`
        returns the result, raising _Rejected for a clean in-protocol
        refusal (frame boundary intact, stream reusable)."""
        while True:
            sock, fresh = self._checkout()
            try:
                result = request_fn(sock)
                self._checkin(sock)
                return result
            except _Rejected as e:
                self._checkin(sock)
                raise TransferError(str(e)) from None
            except (TransferError, OSError) as e:
                try:
                    sock.close()
                except OSError:
                    pass
                if fresh:
                    if isinstance(e, socket.timeout):
                        raise TransferError(f"transfer stalled: {e}") from e
                    raise
                # stale pooled stream: loop onto a fresher connection

    def write_range(self, oid: str, offset: int, payload) -> None:
        """Blocking channel push: store `payload` at [offset, ...) of a
        CHANNEL slot on the holder (compiled-DAG mutable channels)."""
        oid_b = oid.encode()

        def round_(sock):
            sock.sendall(_REQ.pack(len(oid_b) | _WRITE_FLAG, offset,
                                   len(payload)) + oid_b)
            sock.sendall(payload)
            status, _n = _RSP.unpack(_recv_exact(sock, _RSP.size))
            if status != _OK:
                raise _Rejected(f"channel write to {oid[:16]} rejected "
                                f"by {self.host}:{self.port}")

        self._sync_round(round_)

    def read_range(self, oid: str, offset: int, length: int) -> bytearray:
        """Blocking single-range read (channel cursor words etc.)."""
        oid_b = oid.encode()

        def round_(sock):
            sock.sendall(_REQ.pack(len(oid_b), offset, length) + oid_b)
            status, n = _RSP.unpack(_recv_exact(sock, _RSP.size))
            if status != _OK:
                raise _Rejected(f"range of {oid[:16]} not served by "
                                f"{self.host}:{self.port}")
            if n != length:
                raise TransferError(
                    f"short range reply for {oid[:16]}: {n} != {length}")
            return _recv_exact(sock, length)

        return self._sync_round(round_)

    async def fetch_into(self, oid: str, dest: memoryview) -> None:
        """Pull the whole object into `dest` (len(dest) == object size):
        striped across parallel streams when large, windowed chunk
        pipeline within each stream."""
        from ray_tpu._private.config import config

        size = len(dest)
        chunk = max(64 * 1024, int(config.object_transfer_chunk_bytes))
        window = max(1, int(config.object_transfer_window))
        streams = 1
        if size >= int(config.object_transfer_parallel_threshold):
            streams = max(1, min(int(config.object_transfer_max_streams),
                                 (size + chunk - 1) // chunk))
        loop = asyncio.get_running_loop()
        if streams == 1:
            await loop.run_in_executor(
                None, self._fetch_range, oid, dest, 0, size, chunk, window)
            return
        stripe = ((size // streams) // chunk + 1) * chunk
        jobs = []
        start = 0
        while start < size:
            end = min(size, start + stripe)
            jobs.append(loop.run_in_executor(
                None, self._fetch_range, oid, dest, start, end, chunk,
                window))
            start = end
        # return_exceptions: ALL stripe threads must finish before this
        # raises — the caller aborts the store allocation on failure,
        # and a still-running blocking thread writing into a freed
        # (and possibly re-allocated) arena range would corrupt
        # whatever object lands there next
        results = await asyncio.gather(*jobs, return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r

    def _fetch_range(self, oid: str, dest: memoryview,
                     start: int, end: int, chunk: int, window: int) -> None:
        """Blocking: fetch [start, end) of oid into dest, retrying on a
        fresh stream when a POOLED one turns out dead (the holder may
        have closed it between uses; object bytes are immutable, so
        refetching the range is idempotent).  A failure on a fresh
        stream is a real failure and propagates."""
        while True:
            sock, fresh = self._checkout()
            try:
                self._fetch_range_on(sock, oid, dest, start, end, chunk,
                                     window)
                return
            except (TransferError, OSError) as e:
                try:
                    sock.close()
                except OSError:
                    pass
                if fresh:
                    if isinstance(e, socket.timeout):
                        raise TransferError(f"transfer stalled: {e}") from e
                    raise
                # stale pooled stream: loop — the pool drains toward a
                # fresh connection, so this terminates

    def _fetch_range_on(self, sock: socket.socket, oid: str,
                        dest: memoryview, start: int, end: int, chunk: int,
                        window: int) -> None:
        """One attempt on one stream, keeping `window` chunk requests in
        flight (requests are ~50 bytes — they can never fill the send
        buffer, so writing ahead of the reads cannot deadlock)."""
        oid_b = oid.encode()
        offsets = iter(range(start, end, chunk))
        pending: List[Tuple[int, int]] = []

        def send_next() -> None:
            off = next(offsets, None)
            if off is None:
                return
            n = min(chunk, end - off)
            sock.sendall(_REQ.pack(len(oid_b), off, n) + oid_b)
            pending.append((off, n))

        for _ in range(window):
            send_next()
        while pending:
            off, n = pending.pop(0)
            hdr = _recv_exact(sock, _RSP.size)
            status, length = _RSP.unpack(hdr)
            if status != _OK:
                raise TransferError(
                    f"object {oid[:16]} not served by "
                    f"{self.host}:{self.port}")
            if length != n:
                raise TransferError(
                    f"short range reply for {oid[:16]}: {length} != {n}")
            _recv_into(sock, dest[off:off + n])
            send_next()
        # clean completion at a frame boundary: the stream is reusable
        self._checkin(sock)


def dest_view(store, loc: dict) -> Tuple[memoryview, Optional[_MappedFile]]:
    """Writable view over a just-created (unsealed) store allocation.

    Returns (view, mapped_file): the caller closes mapped_file (disk
    fallback destinations) after the transfer; shm destinations write
    straight into the arena and return None."""
    size = loc["size"]
    if loc["location"] == "shm":
        off = loc["offset"]
        return store.arena.view[off:off + size], None
    m = _MappedFile(loc["path"], size, writable=True)
    return m.view[:size], m


# ------------------------------------------------- KV-page shipping format
# Disaggregated LLM prefill (serve/llm.py) ships finished KV pages from
# prefill replicas to decode replicas as ordinary sealed store objects —
# the pull itself rides the bulk plane above with the seal-time CRC32 +
# alternate-holder retry machinery.  The pack format adds its OWN crc
# over the payload as defense in depth: a decode replica attaching pages
# into live pools must detect corruption even when object-level
# checksums are disabled (object_checksums=False) or the bytes came
# from a local, never-transferred copy.

_KV_MAGIC = b"RTKV"
_KV_HDR = struct.Struct("<4sII")  # magic, crc32(payload), payload length


def pack_kv_pages(meta: Dict, rows: Dict) -> bytes:
    """Serialize one sequence's prefilled KV rows + metadata into a
    self-checksummed blob.  ``meta`` is a small picklable dict (request
    id, prompt tokens, first generated token, slot count, page size);
    ``rows`` is {"k": [per-layer host arrays], "v": [...]} as returned
    by models.llama.gather_kv_slots."""
    import pickle
    import zlib

    payload = pickle.dumps({"meta": dict(meta), "rows": rows},
                           protocol=pickle.HIGHEST_PROTOCOL)
    return _KV_HDR.pack(_KV_MAGIC, zlib.crc32(payload),
                        len(payload)) + payload


def unpack_kv_pages(buf: bytes) -> Tuple[Dict, Dict]:
    """Parse and byte-verify a pack_kv_pages blob -> (meta, rows).
    Raises TransferError on a bad magic, length, or crc — the caller
    (decode-replica attach) falls back to a local re-prefill rather
    than scattering corrupt rows into live KV pools."""
    import pickle
    import zlib

    if len(buf) < _KV_HDR.size:
        raise TransferError(f"kv pack too short ({len(buf)} bytes)")
    magic, crc, length = _KV_HDR.unpack_from(buf)
    payload = bytes(buf[_KV_HDR.size:])
    if magic != _KV_MAGIC or len(payload) != length:
        raise TransferError("kv pack header mismatch")
    actual = zlib.crc32(payload)
    if actual != crc:
        raise TransferError(
            f"kv pack checksum mismatch: payload crc {actual:#010x} "
            f"!= packed crc {crc:#010x}")
    d = pickle.loads(payload)
    return d["meta"], d["rows"]
