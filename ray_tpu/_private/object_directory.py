"""Sharded cluster object directory (head-side).

Equivalent role to the reference's GCS-backed ObjectDirectory
(reference: src/ray/object_manager/ownership_object_directory.h plus the
object-location half of gcs tables), rebuilt for head scale-out: one
monolithic per-node snapshot map was the ceiling once several agents
heartbeat large object sets while every lease request scores locality
against it (ROADMAP open item 3).

Design:
  - entries are partitioned into ``object_directory_shards`` buckets by
    oid hash; each shard carries its own lock and version counter, so
    heartbeat applies, location lookups, and gossip reads on different
    shards never serialize on one structure;
  - agents report DELTAS (added/removed [oid, size] pairs vs what they
    last acked), not full snapshots — a steady-state heartbeat with no
    object churn costs O(1) regardless of how many objects a node
    holds.  An epoch token handshakes resets: when the head restarts
    (or first hears from a node), the agent re-sends its full summary;
  - consumers (agents doing locality scoring / alt-source pulls) hold a
    per-shard mirror refreshed by shard version: the heartbeat reply
    carries only shards whose version moved past what the agent has
    seen, each as a full replacement map (idempotent, self-healing).

The per-shard ``threading.Lock`` makes every entry point safe from any
thread; on the head's single event loop it is uncontended and cheap.
"""

from __future__ import annotations

import threading
import zlib
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple


def _shard_index(oid: str, num_shards: int) -> int:
    """Deterministic cross-process shard index.  Python's hash() is
    process-salted — the head and each agent would disagree on which
    shard an oid lives in, silently breaking every mirror lookup."""
    return zlib.crc32(oid.encode()) % num_shards


class _Shard:
    __slots__ = ("lock", "version", "holders", "crcs")

    def __init__(self):
        self.lock = threading.Lock()
        self.version = 0
        # oid -> {node_id: size}
        self.holders: Dict[str, Dict[str, int]] = {}
        # oid -> seal-fixed CRC32 (checksummed transfers): a property of
        # the object's bytes, not of any holder — one slot per oid,
        # dropped with the last holder
        self.crcs: Dict[str, int] = {}


class ShardedObjectDirectory:
    """Head-side object location registry, sharded by oid CRC.

    Thread contract: every mutation and read takes the owning shard's
    lock (plus ``_node_lock`` for the per-node reverse index), so the
    structure is safe to write from the head's telemetry ingest loop
    (heartbeat delta application) while the scheduling core reads
    ``locations()``/``updates_since()`` from its own loop — no
    cross-loop hop needed for locality lookups."""

    def __init__(self, num_shards: int = 16, epoch: str = ""):
        self.num_shards = max(1, int(num_shards))
        self._shards = [_Shard() for _ in range(self.num_shards)]
        # handshake token: agents echo it with their deltas; a mismatch
        # (head restart, first contact) makes them re-send everything
        self.epoch = epoch
        # node_id -> oids it holds (for O(node's objects) death cleanup)
        self._node_oids: Dict[str, Set[str]] = {}
        self._node_lock = threading.Lock()

    def _shard_of(self, oid: str) -> _Shard:
        return self._shards[_shard_index(oid, self.num_shards)]

    # ---- writes (heartbeat deltas) -------------------------------------

    def apply_delta(self, node_id: str, added: Iterable[List[Any]],
                    removed: Iterable[str], full: bool = False) -> None:
        """Fold one agent's report in.  ``full`` marks a complete
        re-send (epoch mismatch): entries this node reported before but
        not now are dropped first, so a desynced agent converges in one
        beat."""
        added = [list(ent) for ent in added]
        with self._node_lock:
            known = self._node_oids.setdefault(node_id, set())
            stale = known - {ent[0] for ent in added} if full else set()
        if stale:
            self._drop_entries(node_id, stale)
        touched: Set[int] = set()
        for ent in added:
            # [oid, size] (pre-checksum agents) or [oid, size, crc]
            oid, size = ent[0], ent[1]
            crc = ent[2] if len(ent) > 2 else None
            shard = self._shard_of(oid)
            with shard.lock:
                shard.holders.setdefault(oid, {})[node_id] = int(size)
                if crc is not None:
                    shard.crcs[oid] = int(crc)
            touched.add(id(shard))
            with self._node_lock:
                self._node_oids.setdefault(node_id, set()).add(oid)
        removed = list(removed)
        if removed:
            self._drop_entries(node_id, removed)
        for shard in self._shards:
            if id(shard) in touched:
                with shard.lock:
                    shard.version += 1

    def _drop_entries(self, node_id: str, oids: Iterable[str]) -> None:
        for oid in oids:
            shard = self._shard_of(oid)
            with shard.lock:
                ent = shard.holders.get(oid)
                if ent is not None and ent.pop(node_id, None) is not None:
                    if not ent:
                        shard.holders.pop(oid, None)
                        shard.crcs.pop(oid, None)
                    shard.version += 1
            with self._node_lock:
                known = self._node_oids.get(node_id)
                if known is not None:
                    known.discard(oid)

    def drop_node(self, node_id: str) -> None:
        """Node died: every location it held is gone."""
        with self._node_lock:
            oids = self._node_oids.pop(node_id, set())
        self._drop_entries(node_id, oids)

    # ---- reads ---------------------------------------------------------

    def locations(self, oid: str) -> Dict[str, int]:
        shard = self._shard_of(oid)
        with shard.lock:
            return dict(shard.holders.get(oid) or {})

    def checksum(self, oid: str) -> Optional[int]:
        """The directory-recorded seal CRC32 for oid (None when no
        checksum-reporting holder has advertised it)."""
        shard = self._shard_of(oid)
        with shard.lock:
            return shard.crcs.get(oid)

    def versions(self) -> List[int]:
        return [s.version for s in self._shards]

    def version_total(self) -> int:
        """Sum of shard versions: a cheap single-number change signal
        (monotonic while this head lives) for status surfaces."""
        return sum(s.version for s in self._shards)

    def updates_since(self, seen: Optional[List[int]]
                      ) -> Dict[int, Dict[str, Any]]:
        """Shards whose version moved past ``seen`` (None = everything),
        each as a full replacement {"v": version, "holders": {...}} —
        the mirror protocol's idempotent unit."""
        out: Dict[int, Dict[str, Any]] = {}
        for idx, shard in enumerate(self._shards):
            last = seen[idx] if seen is not None and idx < len(seen) else -1
            with shard.lock:
                if shard.version > last:
                    out[idx] = {"v": shard.version,
                                "holders": {oid: dict(h) for oid, h
                                            in shard.holders.items()}}
        return out

    def node_entries(self, node_id: str) -> Dict[str, int]:
        """One node's {oid: size} view (introspection/tests)."""
        out: Dict[str, int] = {}
        with self._node_lock:
            oids = set(self._node_oids.get(node_id) or ())
        for oid in oids:
            shard = self._shard_of(oid)
            with shard.lock:
                ent = shard.holders.get(oid)
                if ent is not None and node_id in ent:
                    out[oid] = ent[node_id]
        return out


class DirectoryMirror:
    """Agent-side replica of the sharded directory, refreshed from the
    versioned shard updates piggybacked on heartbeat replies.  Lookups
    are O(1) per oid — locality scoring stops scanning every node's
    object map per argument."""

    def __init__(self, num_shards: int = 16):
        self.num_shards = max(1, int(num_shards))
        self._shards: Dict[int, Dict[str, Dict[str, int]]] = {}
        self._seen: List[int] = [-1] * self.num_shards

    def seen_versions(self) -> List[int]:
        return list(self._seen)

    def apply_updates(self, updates: Optional[Dict[Any, Dict[str, Any]]]
                      ) -> None:
        if not updates:
            return
        for idx, payload in updates.items():
            idx = int(idx)
            if idx >= self.num_shards:
                # head reconfigured with more shards: resync from scratch
                self.num_shards = idx + 1
                self._seen.extend([-1] * (idx + 1 - len(self._seen)))
            self._shards[idx] = payload.get("holders") or {}
            self._seen[idx] = int(payload.get("v", self._seen[idx]))

    def holders(self, oid: str) -> Dict[str, int]:
        shard = self._shards.get(_shard_index(oid, self.num_shards))
        if not shard:
            return {}
        return shard.get(oid) or {}

    def reset(self) -> None:
        """Forget everything (head restart: the new head's shard
        versions restart at 0, so stale high seen-versions would
        suppress updates — and its directory content is new anyway)."""
        self._shards.clear()
        self._seen = [-1] * self.num_shards


class DeltaReporter:
    """Agent-side bookkeeping: turns successive full store summaries
    into (added, removed) deltas against what the head last acked, with
    the epoch handshake forcing a full re-send after a head restart."""

    def __init__(self):
        self._acked: Dict[str, int] = {}
        self._epoch: Optional[str] = None

    def build(self, summary: List[List[Any]],
              head_epoch: Optional[str]) -> Dict[str, Any]:
        # summary entries: [oid, size] or [oid, size, crc]
        current = {ent[0]: (int(ent[1]), ent[2] if len(ent) > 2 else None)
                   for ent in summary}
        full = head_epoch is None or head_epoch != self._epoch
        base = {} if full else self._acked
        added = [[oid, size, crc] for oid, (size, crc) in current.items()
                 if base.get(oid) != (size, crc)]
        removed = [oid for oid in base if oid not in current]
        self._pending = (current, head_epoch)
        return {"add": added, "remove": removed, "full": full,
                "epoch": head_epoch or ""}

    def ack(self) -> None:
        """The heartbeat carrying the last-built delta was answered."""
        pending = getattr(self, "_pending", None)
        if pending is not None:
            self._acked, self._epoch = pending
            self._pending = None
