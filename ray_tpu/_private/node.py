"""Cluster bootstrap: spawn and manage the head + node-agent daemons.

Equivalent of the reference's Node
(reference: python/ray/_private/node.py — start_head_processes :1323,
start_ray_processes :1352): `ray_tpu.init()` on a fresh machine spawns
the head service and one node agent as real processes, then connects the
driver to them.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, Optional, Tuple


class ProcessHandle:
    def __init__(self, name: str, proc: subprocess.Popen):
        self.name = name
        self.proc = proc

    def terminate(self, timeout: float = 3.0):
        if self.proc.poll() is not None:
            return
        try:
            self.proc.terminate()
            self.proc.wait(timeout=timeout)
        except Exception:
            try:
                self.proc.kill()
                self.proc.wait(timeout=timeout)
            except Exception:
                pass


def _wait_for_file(path: str, timeout: float = 30.0) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                content = f.read()
            if content:
                return content
        time.sleep(0.01)
    raise TimeoutError(f"daemon did not write {path} within {timeout}s")


def new_session_dir() -> str:
    base = os.environ.get("RT_TMPDIR", "/tmp/ray_tpu")
    path = os.path.join(base, f"session_{int(time.time() * 1000)}_{os.getpid()}")
    os.makedirs(os.path.join(path, "logs"), exist_ok=True)
    return path


def default_resources(num_cpus: Optional[float] = None,
                      resources: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    out: Dict[str, float] = {}
    out["CPU"] = float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))
    try:
        from ray_tpu._private.accelerators import detect_accelerators

        out.update(detect_accelerators())
    except Exception:
        pass
    if resources:
        out.update(resources)
    return out


def start_head(session_dir: str, env: Optional[Dict[str, str]] = None,
               port: int = 0) -> Tuple[ProcessHandle, Tuple[str, int]]:
    from ray_tpu._private.spawn import fast_python_cmd

    port_file = os.path.join(session_dir, f"head-{time.monotonic_ns()}.port")
    state_path = os.path.join(session_dir, "head.state")
    log = open(os.path.join(session_dir, "logs", "head.log"), "ab")
    penv = dict(os.environ)
    if env:
        penv.update(env)
    cmd, env_up = fast_python_cmd(
        "ray_tpu._private.head",
        ["--port-file", port_file, "--state-path", state_path,
         "--port", str(port)])
    penv.update(env_up)
    proc = subprocess.Popen(
        cmd, stdout=log, stderr=subprocess.STDOUT, env=penv, start_new_session=True)
    log.close()
    bound = int(_wait_for_file(port_file))
    return ProcessHandle("head", proc), ("127.0.0.1", bound)


def start_node_agent(session_dir: str, head_addr: Tuple[str, int],
                     resources: Dict[str, float],
                     object_store_memory: Optional[int] = None,
                     is_head_node: bool = False,
                     env: Optional[Dict[str, str]] = None,
                     labels: Optional[Dict[str, str]] = None,
                     tag: str = "agent") -> Tuple[ProcessHandle, Dict[str, Any]]:
    from ray_tpu._private.spawn import fast_python_cmd

    port_file = os.path.join(session_dir, f"{tag}-{os.getpid()}-{time.monotonic_ns()}.port")
    log = open(os.path.join(session_dir, "logs", f"{tag}.log"), "ab")
    penv = dict(os.environ)
    if env:
        penv.update(env)
    argv = ["--head-host", head_addr[0], "--head-port", str(head_addr[1]),
            "--session-dir", session_dir,
            "--resources", json.dumps(resources),
            "--port-file", port_file]
    if object_store_memory:
        argv += ["--capacity", str(object_store_memory)]
    if is_head_node:
        argv += ["--is-head-node"]
    if labels:
        argv += ["--labels", json.dumps(labels)]
    cmd, env_up = fast_python_cmd("ray_tpu._private.node_agent", argv)
    penv.update(env_up)
    proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            env=penv, start_new_session=True)
    log.close()
    port_s, node_id, arena_path = _wait_for_file(port_file).split("\n")
    info = {"addr": ("127.0.0.1", int(port_s)), "node_id": node_id,
            "arena_path": arena_path}
    return ProcessHandle(tag, proc), info
