"""Distributed tracing: spans, context propagation, sampled buffering.

Equivalent of the reference's ``ray.util.tracing`` OpenTelemetry
integration (reference: python/ray/util/tracing/tracing_helper.py —
trace context is injected into the task spec on submission and
extracted worker-side so execute spans parent to the caller's submit
span), without the OpenTelemetry dependency: a span here is a plain
dict-able record with W3C-style ids.

Model:
  - trace_id (32 hex) / span_id (16 hex) / parent_id, name, kind
    (CLIENT for submit-side, SERVER for execute/ingress, INTERNAL
    otherwise), start/end wall timestamps, attributes, status.
  - The ACTIVE context rides a contextvar: every thread (and, via
    ``run_coroutine_threadsafe``'s context copy, every async task body)
    sees the span it is running under; nested ``.remote()`` submissions
    inherit it, which is what chains driver → task → subtask into one
    trace.
  - Sampling is decided once at the root span (``trace_sampling_ratio``)
    and propagated as a flag; unsampled requests pay nothing (no span
    objects, no wire field).
  - Finished spans land in a bounded per-process buffer drained by the
    CoreWorker's task-event flush (worker → head) into the head's trace
    store; overflow increments ``rt_trace_spans_dropped`` instead of
    growing without bound.

W3C trace-context interop: `parse_traceparent` / `format_traceparent`
implement the ``00-<trace>-<span>-<flags>`` header format so Serve's
HTTP ingress can continue traces started by external callers.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private.config import config

KIND_INTERNAL = "INTERNAL"
KIND_CLIENT = "CLIENT"
KIND_SERVER = "SERVER"

_UNSET = object()  # distinguishes "no parent given" from "explicitly root"

_current: "contextvars.ContextVar[Optional[SpanContext]]" = \
    contextvars.ContextVar("rt_trace_ctx", default=None)

_buf_lock = threading.Lock()
_buffer: List[Dict[str, Any]] = []
_counts = {"sampled": 0, "dropped": 0, "flushes": 0}
_pushed = {"sampled": 0, "dropped": 0, "flushes": 0}  # synced to Counters
_metrics = None
_metrics_lock = threading.Lock()

# config snapshot, refreshed on a short TTL: config attribute access
# costs ~3µs (env-var lookup per read) which is real money at 2+ reads
# per span on the submit hot path; a 0.2s-stale sampling ratio is
# invisible in practice (toggles take effect within one warm-up)
_cfg_cache = {"at": -1.0, "enabled": True, "ratio": 1.0, "buf": 4096}


def _cfg() -> Dict[str, Any]:
    now = time.monotonic()
    if now - _cfg_cache["at"] > 0.2:
        _cfg_cache["enabled"] = bool(config.tracing_enabled)
        _cfg_cache["ratio"] = float(config.trace_sampling_ratio)
        _cfg_cache["buf"] = int(config.trace_buffer_size)
        _cfg_cache["at"] = now
    return _cfg_cache


def _get_metrics():
    """Tracing self-metrics on the process's default registry (workers
    push it to their node agent; daemons expose it directly)."""
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from ray_tpu._private.metrics import Counter

                _metrics = {
                    "sampled": Counter("rt_trace_spans_sampled",
                                       "spans recorded by this process"),
                    "dropped": Counter("rt_trace_spans_dropped",
                                       "spans lost to buffer overflow or "
                                       "flush failure"),
                    "flushes": Counter("rt_trace_flush_batches",
                                       "span batches flushed to the head"),
                }
    return _metrics


class SpanContext:
    """What propagates: ids + the sampling decision."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def to_wire(self) -> Dict[str, str]:
        return {"tid": self.trace_id, "sid": self.span_id}

    def __repr__(self):
        return f"SpanContext({self.trace_id[:8]}…/{self.span_id[:8]}…)"


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "start", "end_ts", "attributes", "status")

    def __init__(self, trace_id: str, span_id: str, parent_id: str,
                 name: str, kind: str,
                 attributes: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.start = time.time()
        self.end_ts = 0.0
        self.attributes = attributes
        self.status = ""  # "" = OK; else the error string

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, True)

    def set_attribute(self, key: str, value: Any) -> None:
        if self.attributes is None:
            self.attributes = {}
        self.attributes[key] = value

    def end(self, error: str = "") -> None:
        self.end_ts = time.time()
        if error:
            self.status = str(error)[:200]
        _record(self.to_wire())

    def to_wire(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "kind": self.kind, "start": self.start, "end": self.end_ts,
        }
        if self.status:
            d["status"] = self.status
        if self.attributes:
            d["attrs"] = self.attributes
        return d


# ------------------------------------------------------------------ ids


def new_trace_id() -> str:
    return f"{random.getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{random.getrandbits(64):016x}"


# ------------------------------------------------------------- context


def current_context() -> Optional[SpanContext]:
    return _current.get()


def activate(ctx: Optional[SpanContext]):
    """Make `ctx` the active trace context on this thread/coroutine;
    returns a token for `restore`."""
    return _current.set(ctx)


def restore(token) -> None:
    _current.reset(token)


_NOT_SAMPLED = SpanContext("", "", sampled=False)


class suppressed:
    """Context manager marking this thread's work as never-sampled —
    for internal control loops (health probes, metrics pushes) whose
    submissions would otherwise mint a root trace every tick and churn
    real traces out of the bounded head store."""

    def __enter__(self):
        self._token = _current.set(_NOT_SAMPLED)
        return self

    def __exit__(self, *exc):
        _current.reset(self._token)
        return False


# wire marker for a NEGATIVE sampling decision: the executing worker
# must inherit "this tree is unsampled" or nested submissions would
# re-roll sampling mid-call-tree (minting partial orphan root traces)
_NS_WIRE = {"ns": 1}


def ctx_from_wire(d: Optional[Dict[str, Any]]) -> Optional[SpanContext]:
    """Inverse of the wire context: {"tid","sid"} for a sampled parent,
    {"ns":1} for a propagated not-sampled decision, None for untraced."""
    if not d:
        return None
    if d.get("ns"):
        return _NOT_SAMPLED
    tid, sid = d.get("tid"), d.get("sid")
    if not tid or not sid:
        return None
    return SpanContext(tid, sid, True)


def begin_submit(name: str, kind: str = KIND_CLIENT
                 ) -> tuple:
    """Span + wire context for a task/actor submission: returns
    (span | None, wire_ctx | None).  Unlike start_span, a negative
    decision (root sampled out, unsampled or suppressed parent) still
    yields the not-sampled wire marker so the whole downstream tree
    honors the decision made once at the root."""
    cfg = _cfg()
    if not cfg["enabled"]:
        return None, None
    parent = _current.get()
    if parent is None:
        if random.random() >= cfg["ratio"]:
            return None, _NS_WIRE
        span = Span(new_trace_id(), new_span_id(), "", name, kind)
        return span, span.context().to_wire()
    if not parent.sampled:
        return None, _NS_WIRE
    span = Span(parent.trace_id, new_span_id(), parent.span_id, name, kind)
    return span, span.context().to_wire()


# -------------------------------------------------------------- spans


def start_span(name: str, kind: str = KIND_INTERNAL, parent=_UNSET,
               attributes: Optional[Dict[str, Any]] = None
               ) -> Optional[Span]:
    """Open a span. Returns None when tracing is disabled or the trace
    is unsampled — callers treat None as "do nothing" so the unsampled
    hot path allocates nothing.

    parent: omitted → the active context; None → force a new root;
    a SpanContext → that parent (e.g. extracted from a traceparent
    header or a TaskSpec)."""
    cfg = _cfg()
    if not cfg["enabled"]:
        return None
    if parent is _UNSET:
        parent = _current.get()
    if parent is None:
        if random.random() >= cfg["ratio"]:
            return None
        trace_id, parent_id = new_trace_id(), ""
    else:
        if not parent.sampled:
            return None
        trace_id, parent_id = parent.trace_id, parent.span_id
    return Span(trace_id, new_span_id(), parent_id, name, kind, attributes)


def _record(wire_span: Dict[str, Any]) -> None:
    # hot path: buffer append + plain-int accounting only; the Counter
    # objects are synced from _counts on the drain cadence (~1/s)
    with _buf_lock:
        if len(_buffer) >= _cfg_cache["buf"]:
            _counts["dropped"] += 1
            return
        _buffer.append(wire_span)
        _counts["sampled"] += 1


def _sync_metrics() -> None:
    """Push accumulated counts into the registry Counters (cheap to do
    once per drain; too expensive per span on this hot path).  No-op
    until something was actually counted, so an untraced process never
    registers the counters (registering would flip has_samples() and
    start the worker→agent metrics push for nothing)."""
    with _buf_lock:
        deltas = {k: _counts[k] - _pushed[k] for k in _counts}
        if not any(deltas.values()):
            return
        _pushed.update(_counts)
    m = _get_metrics()
    for k, d in deltas.items():
        if d:
            m[k].inc(d)


def drain() -> List[Dict[str, Any]]:
    """Take every buffered span (called by the flush loop)."""
    global _buffer
    with _buf_lock:
        batch, _buffer = _buffer, []
    _sync_metrics()
    return batch


def count_flush() -> None:
    with _buf_lock:
        _counts["flushes"] += 1


def count_dropped(n: int) -> None:
    """Spans lost after drain (e.g. the flush RPC failed)."""
    with _buf_lock:
        _counts["dropped"] += n


def stats() -> Dict[str, int]:
    with _buf_lock:
        return dict(_counts, buffered=len(_buffer))


# ------------------------------------------------- head-side store


class TraceStore:
    """The head's bounded trace store: trace_id -> {spans, start, end,
    root}, insertion-ordered so the oldest traces fall off at the cap
    (the task-event store pattern applied to spans).

    Owned by the head's task-event ingest plane (head_shards.py): every
    method runs on that plane's loop, and cross-loop readers (dashboard
    HTTP, CLI RPCs) reach it via the plane's run_sync routing — the
    store itself needs no lock."""

    def __init__(self, max_traces: int, max_spans: int):
        self.traces: Dict[str, Dict[str, Any]] = {}
        self.max_traces = int(max_traces)
        self.max_spans = int(max_spans)
        self.spans_dropped = 0

    def ingest(self, spans: List[Dict[str, Any]]) -> None:
        for s in spans:
            trace_id = s.get("trace_id")
            if not trace_id:
                continue
            ent = self.traces.get(trace_id)
            if ent is None:
                while len(self.traces) >= self.max_traces:
                    self.traces.pop(next(iter(self.traces)))
                ent = self.traces[trace_id] = {
                    "trace_id": trace_id, "spans": [],
                    "start": s.get("start", 0.0), "end": 0.0, "root": "",
                }
            if len(ent["spans"]) >= self.max_spans:
                self.spans_dropped += 1
                continue
            ent["spans"].append(s)
            start = s.get("start") or 0.0
            if start and (not ent["start"] or start < ent["start"]):
                ent["start"] = start
            ent["end"] = max(ent["end"], s.get("end") or 0.0)
            if not s.get("parent_id"):
                ent["root"] = s.get("name", "")

    @staticmethod
    def _summary(ent: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "trace_id": ent["trace_id"],
            "num_spans": len(ent["spans"]),
            "root": ent.get("root", ""),
            "start": ent.get("start", 0.0),
            "end": ent.get("end", 0.0),
            "duration_s": max(0.0, (ent.get("end") or 0.0)
                              - (ent.get("start") or 0.0)),
        }

    def summaries(self, limit: int) -> List[Dict[str, Any]]:
        """Newest-first summaries (shared by the RPC, HTTP and dashboard
        surfaces so they can't drift apart)."""
        out = [self._summary(e)
               for e in reversed(list(self.traces.values()))]
        return out[:max(0, limit)]

    def detail(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Summary + start-sorted spans for one trace, or None."""
        ent = self.traces.get(trace_id)
        if ent is None:
            return None
        trace = self._summary(ent)
        trace["spans"] = sorted(ent["spans"],
                                key=lambda s: s.get("start", 0.0))
        return trace


# ------------------------------------------------- W3C trace-context


def format_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-" \
           f"{'01' if ctx.sampled else '00'}"


def _is_hex(s: str) -> bool:
    try:
        int(s, 16)
        return True
    except ValueError:
        return False


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a W3C ``traceparent`` header; malformed input returns None
    (the request proceeds untraced — never an error)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or not _is_hex(version) or version == "ff":
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) \
            or trace_id == "0" * 32:
        return None
    if len(span_id) != 16 or not _is_hex(span_id) or span_id == "0" * 16:
        return None
    if len(flags) != 2 or not _is_hex(flags):
        return None
    sampled = bool(int(flags, 16) & 0x01)
    return SpanContext(trace_id, span_id, sampled)
