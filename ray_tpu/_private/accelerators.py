"""Accelerator detection: TPU chips/slices as first-class resources.

Equivalent of the reference's accelerator plugin layer
(reference: python/ray/_private/accelerators/accelerator.py:5 ABC;
tpu.py:75 TPUAcceleratorManager — /dev/accel* detection :110,
TPU_VISIBLE_CHIPS :30, GCE/GKE metadata :52, pod-slice custom resources
TPU-{type}-head and slice-name resources :335-398).

Detection is cheap (no jax import): device files + env vars + GCE
metadata when present.  A node on a pod slice additionally advertises
  - "TPU-<accel_type>-head": 1   on worker 0 of the slice (gang anchor)
  - "TPU-<slice_name>": 4        so a placement group can target a slice
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Optional

TPU_RESOURCE = "TPU"


def num_tpu_chips() -> int:
    env = os.environ.get("TPU_VISIBLE_CHIPS")
    if env is not None:
        return 0 if env in ("", "none") else len(env.split(","))
    # PCI accel device files (reference: tpu.py:110 _glob_tpu_acclerator_devices)
    devices = glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*")
    return len(devices)


_metadata_cache: Dict[str, Optional[str]] = {}


def _gce_metadata_http(key: str) -> Optional[str]:
    """GCE/GKE metadata-server lookup (reference: tpu.py:52
    _get_tpu_metadata — GKE TPU pods expose accelerator-type and
    agent-worker-number through the instance metadata server).  Cached;
    fails fast off-GCP."""
    if key in _metadata_cache:
        return _metadata_cache[key]
    value: Optional[str] = None
    try:
        import urllib.request

        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/"
            f"instance/attributes/{key}",
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=0.5) as r:
            value = r.read().decode().strip()
    except Exception:
        value = None
    _metadata_cache[key] = value
    return value


def tpu_metadata(key: str) -> Optional[str]:
    """TPU slice metadata: env vars first (GKE injects them; tests set
    them), then the GCE metadata server, else None."""
    env_map = {
        "accelerator-type": "TPU_ACCELERATOR_TYPE",
        "agent-worker-number": "TPU_WORKER_ID",
        "instance-id": "TPU_NAME",
    }
    env = env_map.get(key)
    if env and os.environ.get(env) is not None:
        return os.environ.get(env)
    if os.environ.get("RT_DISABLE_METADATA_SERVER") or not _on_gce():
        return None  # off-GCP: keep the zero-egress guarantee
    return _gce_metadata_http(key)


def _on_gce() -> bool:
    """Detect GCE/GKE via DMI — no network, so off-GCP hosts never pay
    a DNS stall for metadata.google.internal."""
    try:
        with open("/sys/class/dmi/id/product_name") as f:
            return "Google" in f.read()
    except OSError:
        return False


def num_gpus() -> int:
    """NVIDIA GPU count via CUDA_VISIBLE_DEVICES / device files
    (reference: accelerators/nvidia_gpu.py — TPU is the primary target
    here, but mixed clusters schedule GPUs as ordinary resources)."""
    env = os.environ.get("CUDA_VISIBLE_DEVICES")
    if env is not None:
        ids = [d for d in env.split(",") if d.strip() not in ("", "-1")]
        return 0 if env.strip() in ("", "none", "NoDevFiles", "-1") \
            else len(ids)
    return len([p for p in glob.glob("/dev/nvidia[0-9]*")
                if p[len("/dev/nvidia"):].isdigit()])


def detect_accelerators() -> Dict[str, float]:
    out: Dict[str, float] = {}
    gpus = num_gpus()
    if gpus > 0:
        out["GPU"] = float(gpus)
    chips = num_tpu_chips()
    if chips <= 0:
        return out
    out[TPU_RESOURCE] = float(chips)
    accel_type = tpu_metadata("accelerator-type")  # e.g. "v5e-256"
    worker_id = tpu_metadata("agent-worker-number")
    slice_name = tpu_metadata("instance-id")
    if accel_type:
        if worker_id == "0":
            out[f"TPU-{accel_type}-head"] = 1.0
    if slice_name:
        out[f"TPU-{slice_name}"] = float(chips)
    return out
