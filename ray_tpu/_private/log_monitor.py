"""Driver log streaming: tail worker logs on the node agent, push to
subscribed drivers.

Equivalent role to the reference's log monitor
(reference: python/ray/_private/log_monitor.py:103 — a per-node daemon
tailing ``/logs`` and publishing increments over GCS pubsub, printed by
the driver with ``(pid=..., ip=...)`` prefixes).  Here the monitor runs
inside the node agent's event loop and streams over the existing RPC
push path: a driver (or ``rtpu logs``) calls ``subscribe_logs`` on an
agent and receives ``log_lines`` oneway pushes on that same connection —
no extra daemon, no polling from the driver side.

Each agent tails only the files of workers IT spawned (several agents
may share one session ``logs/`` dir in tests), so a driver subscribed to
every agent sees each line exactly once.  While nobody is subscribed the
monitor does no IO at all; the first subscriber gets an optional
tail-backlog and streaming starts from the then-current end of file.
Files registered while subscribers exist (fresh workers) stream from
byte 0, so a worker's first ``print()`` is never lost.
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, Dict, List, Optional


class _TailedFile:
    __slots__ = ("path", "pid", "worker_id", "offset", "partial", "missing",
                 "dead")

    def __init__(self, path: str, pid: int, worker_id: str,
                 offset: Optional[int]):
        self.path = path
        self.pid = pid
        self.worker_id = worker_id
        # None = "seek to end when streaming starts" (pre-subscription
        # history is served via the tail backlog, not replayed)
        self.offset = offset
        self.partial = b""  # trailing bytes of an incomplete last line
        self.missing = False
        # worker reaped: the file is drained one last time (the death
        # message is usually its final lines) and then evicted, so
        # _files doesn't grow — and poll doesn't stat — one entry per
        # dead worker forever under churn
        self.dead = False


def _tail_lines(path: str, n: int) -> List[str]:
    """Last ``n`` decoded lines of a file (bounded read from the end)."""
    if n <= 0:
        return []
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 64 * 1024 * max(1, n // 256 + 1)))
            data = f.read()
    except OSError:
        return []
    lines = data.decode(errors="replace").splitlines()
    return lines[-n:]


class LogMonitor:
    """Tails registered files, fanning line increments out to
    subscribed RPC connections as ``log_lines`` pushes."""

    def __init__(self, node_id: str):
        self.node_id = node_id
        self._files: Dict[str, _TailedFile] = {}
        self._subs: Dict[int, Any] = {}  # id(conn) -> RpcServerConnection
        self._task: Optional[asyncio.Task] = None
        self.lines_streamed = 0  # observability for node_info/tests

    # ---- registration ------------------------------------------------------

    def add_file(self, path: str, pid: int, worker_id: str = "") -> None:
        """Register a worker's log file.  With live subscribers the file
        streams from its beginning (it's brand new); otherwise content
        up to the first subscription is backlog only."""
        if path in self._files:
            return
        self._files[path] = _TailedFile(
            path, pid, worker_id, offset=0 if self._subs else None)

    def mark_dead(self, worker_id: str) -> None:
        """The worker was reaped: schedule its file for drain-then-evict
        (idle files — nobody ever subscribed — evict on the first poll
        after a subscription sets their offset to EOF)."""
        for tf in self._files.values():
            if tf.worker_id == worker_id:
                tf.dead = True

    def subscribe(self, conn, tail: int = 0) -> List[Dict[str, Any]]:
        """Add a push target; returns up to ``tail`` backlog lines per
        file.  Streaming for previously idle files starts at EOF."""
        for tf in self._files.values():
            if tf.offset is None:
                try:
                    tf.offset = os.path.getsize(tf.path)
                except OSError:
                    tf.offset = 0
        self._subs[id(conn)] = conn
        self._ensure_task()
        backlog: List[Dict[str, Any]] = []
        if tail > 0:
            for tf in self._files.values():
                lines = _tail_lines(tf.path, tail)
                if lines:
                    backlog.append({"pid": tf.pid,
                                    "worker_id": tf.worker_id[:12],
                                    "lines": lines})
        return backlog

    def unsubscribe(self, conn) -> None:
        self._subs.pop(id(conn), None)

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # ---- tail loop ---------------------------------------------------------

    def _ensure_task(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        from ray_tpu._private.config import config

        period = max(0.05, config.log_monitor_poll_ms / 1000.0)
        while True:
            await asyncio.sleep(period)
            if not self._subs:
                continue  # idle: no stat/read syscalls at all
            batch = self._poll_once()
            if batch:
                await self._push(batch)

    def _poll_once(self) -> List[Dict[str, Any]]:
        from ray_tpu._private.config import config

        cap = int(config.log_monitor_max_read_bytes)
        batch: List[Dict[str, Any]] = []
        evict: List[str] = []
        for tf in self._files.values():
            if tf.missing or tf.offset is None:
                if tf.missing or tf.dead:
                    evict.append(tf.path)
                continue
            try:
                size = os.path.getsize(tf.path)
            except OSError:
                tf.missing = True
                evict.append(tf.path)
                continue
            if size < tf.offset:
                tf.offset = 0  # truncated/rotated: start over
                tf.partial = b""
            lines_b: List[bytes] = []
            if size > tf.offset:
                try:
                    with open(tf.path, "rb") as f:
                        f.seek(tf.offset)
                        data = f.read(cap)
                except OSError:
                    continue
                tf.offset += len(data)
                data = tf.partial + data
                lines_b = data.split(b"\n")
                tf.partial = lines_b.pop()  # incomplete last piece
            if tf.dead and tf.offset >= size:
                # fully drained after death: flush any unterminated tail
                # and drop the entry (bounds _files under worker churn)
                if tf.partial:
                    lines_b.append(tf.partial)
                    tf.partial = b""
                evict.append(tf.path)
            if not lines_b:
                continue
            lines = [ln.decode(errors="replace") for ln in lines_b]
            self.lines_streamed += len(lines)
            batch.append({"pid": tf.pid, "worker_id": tf.worker_id[:12],
                          "lines": lines})
        for path in evict:
            self._files.pop(path, None)
        return batch

    async def _push(self, batch: List[Dict[str, Any]]) -> None:
        payload = {"node_id": self.node_id, "batch": batch}
        for key, conn in list(self._subs.items()):
            try:
                await conn.push("log_lines", payload)
            except Exception:
                # connection gone: drop the subscriber (the agent's
                # on_peer_disconnect usually beats us to it)
                self._subs.pop(key, None)

    # ---- one-shot reads ----------------------------------------------------

    def tail(self, lines: int = 100) -> List[Dict[str, Any]]:
        """Last N lines of every tracked file (no subscription)."""
        out: List[Dict[str, Any]] = []
        for tf in self._files.values():
            got = _tail_lines(tf.path, lines)
            if got:
                out.append({"pid": tf.pid, "worker_id": tf.worker_id[:12],
                            "lines": got})
        return out
