"""Native (C) hot-path helpers, compiled in the background at first use.

The compute path is JAX/XLA; this package natively accelerates the
*runtime* around it, starting with the object-store copy path
(reference: the C++ plasma client, src/ray/object_manager/plasma/
client.cc).  The C source lives next to this file; it is compiled once
per host into a content-addressed cache and loaded via ctypes.  Every
entry point has a pure-Python fallback, so a missing toolchain only
costs speed, never correctness — and compilation happens on a
background thread so the first put never stalls behind the compiler.

Env: RT_DISABLE_NATIVE=1 forces the Python fallbacks (used by tests to
cover both paths).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False
_build_thread: Optional[threading.Thread] = None

# thread count for copies: bounded by the host's hardware threads;
# 8 measured fastest on the dev host even with 1 schedulable core
# (SMT + memory-level parallelism)
_COPY_THREADS = min(8, (os.cpu_count() or 1) * 2)


def _build_and_load() -> Optional[ctypes.CDLL]:
    src_path = os.path.join(_HERE, "copyfast.c")
    with open(src_path, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    cache_dir = os.environ.get(
        "RT_NATIVE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "ray_tpu_native"))
    so_path = os.path.join(cache_dir, f"copyfast-{tag}.so")
    if not os.path.exists(so_path):
        os.makedirs(cache_dir, exist_ok=True)
        tmp = so_path + f".tmp{os.getpid()}"
        for cc in ("cc", "gcc", "clang"):
            try:
                subprocess.run(
                    [cc, "-O3", "-shared", "-fPIC", "-pthread",
                     src_path, "-o", tmp],
                    check=True, capture_output=True, timeout=60)
                os.replace(tmp, so_path)
                break
            except (OSError, subprocess.SubprocessError):
                try:
                    os.unlink(tmp)  # partial output from a failed compile
                except OSError:
                    pass
                continue
        else:
            return None
    lib = ctypes.CDLL(so_path)
    lib.parallel_copy.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_size_t, ctypes.c_int]
    lib.parallel_copy.restype = None
    lib.parallel_touch.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                   ctypes.c_int]
    lib.parallel_touch.restype = None
    lib.parallel_touch_write.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                         ctypes.c_int]
    lib.parallel_touch_write.restype = None
    lib.fl_new.argtypes = [ctypes.c_size_t]
    lib.fl_new.restype = ctypes.c_void_p
    lib.fl_destroy.argtypes = [ctypes.c_void_p]
    lib.fl_destroy.restype = None
    lib.fl_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.fl_alloc.restype = ctypes.c_size_t
    lib.fl_free.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                            ctypes.c_size_t]
    lib.fl_free.restype = ctypes.c_int
    lib.fl_allocated.argtypes = [ctypes.c_void_p]
    lib.fl_allocated.restype = ctypes.c_size_t
    lib.fl_largest.argtypes = [ctypes.c_void_p]
    lib.fl_largest.restype = ctypes.c_size_t
    return lib


def _background_build() -> None:
    global _lib, _load_failed
    try:
        lib = _build_and_load()
    except Exception:
        lib = None
    with _lock:
        _lib = lib
        _load_failed = lib is None


def _get_lib() -> Optional[ctypes.CDLL]:
    """Non-blocking: returns the loaded library, or None while the
    background build runs (callers fall back to Python meanwhile)."""
    global _build_thread
    if _lib is not None or _load_failed:
        return _lib
    if os.environ.get("RT_DISABLE_NATIVE"):
        return None
    with _lock:
        if _lib is None and not _load_failed and _build_thread is None:
            _build_thread = threading.Thread(
                target=_background_build, name="rt-native-build", daemon=True)
            _build_thread.start()
    return _lib


def warm_up() -> None:
    """Start the background compile (idempotent); call at process start
    so the library is ready before the first large copy."""
    _get_lib()


def available(wait: bool = True) -> bool:
    """True when the native library is loaded.  With wait=True, blocks
    for the in-flight background build (used by tests/benchmarks that
    must exercise the native path)."""
    _get_lib()
    t = _build_thread
    if wait and t is not None:
        t.join(timeout=120)
    return _get_lib() is not None


def _addr_len(buf, writable: bool):
    """(address, nbytes) of a buffer-protocol object without copying.
    numpy handles readonly buffers, which ctypes.from_buffer cannot."""
    import numpy as np

    arr = np.frombuffer(buf, dtype=np.uint8)
    if writable and not arr.flags.writeable:
        raise ValueError("destination buffer is read-only")
    return arr.ctypes.data, arr.nbytes


def copy_into(dst, src) -> None:
    """dst[:] = src at multithreaded-memcpy speed; falls back to a
    plain memoryview copy while the native library is unavailable.
    Raises ValueError for a read-only destination or a length mismatch
    on BOTH paths."""
    dst_addr, dst_n = _addr_len(dst, writable=True)
    src_addr, src_n = _addr_len(src, writable=False)
    if dst_n != src_n:
        raise ValueError(f"length mismatch: dst {dst_n} != src {src_n}")
    lib = _get_lib()
    if lib is None:
        memoryview(dst)[:] = src
        return
    lib.parallel_copy(dst_addr, src_addr, dst_n, _COPY_THREADS)


class NativeFreeListAllocator:
    """C first-fit free-list allocator with coalescing; same contract as
    object_store.FreeListAllocator (reference: plasma/malloc.cc is the
    reference's native arena allocator).  Construct via make_allocator,
    which returns None when the native library is unavailable."""

    __slots__ = ("_h", "capacity")

    def __init__(self, handle, capacity: int):
        self._h = handle
        self.capacity = capacity

    @property
    def allocated(self) -> int:
        return _get_lib().fl_allocated(self._h)

    def alloc(self, size: int):
        off = _get_lib().fl_alloc(self._h, size)
        return None if off == ctypes.c_size_t(-1).value else off

    def free(self, offset: int, size: int) -> None:
        if _get_lib().fl_free(self._h, offset, size) != 0:
            # fl_free mutates nothing on failure; losing arena bytes
            # silently is worse than surfacing the (tiny) realloc failure
            raise MemoryError("free-list block array allocation failed")

    def largest_free(self) -> int:
        return _get_lib().fl_largest(self._h)

    def __del__(self):
        try:
            lib = _lib  # skip rebuild during interpreter teardown
            if lib is not None and self._h:
                lib.fl_destroy(self._h)
        except Exception:
            pass


def make_allocator(capacity: int, wait_s: float = 0.0):
    """Native allocator instance, or None (caller falls back to the
    behaviorally-identical Python FreeListAllocator).  By default this
    NEVER waits for the background compile — a cold cache costs one run
    on the Python allocator, not a startup stall.  Tests pass wait_s to
    guarantee the native path."""
    lib = _get_lib()
    if lib is None and wait_s > 0:
        t = _build_thread
        if t is not None:
            t.join(timeout=wait_s)
        lib = _get_lib()
    if lib is None:
        return None
    handle = lib.fl_new(capacity)
    if not handle:
        return None
    return NativeFreeListAllocator(handle, capacity)


def touch_pages(view) -> None:
    """Read-fault one byte per page (parallel when native is loaded)."""
    lib = _get_lib()
    if lib is None:
        bytes(memoryview(view)[::4096])
        return
    addr, n = _addr_len(view, writable=False)
    lib.parallel_touch(addr, n, _COPY_THREADS)


def touch_pages_write(view) -> None:
    """WRITE-fault one byte per page (content-preserving): installs
    writable PTEs in one pass, for regions the caller owns and is about
    to overwrite (plasma put).  Parallel when native is loaded."""
    lib = _get_lib()
    if lib is None:
        mv = memoryview(view)
        sl = mv[::4096]
        sl[:] = bytes(sl)  # read + write back the same bytes
        return
    addr, n = _addr_len(view, writable=True)
    lib.parallel_touch_write(addr, n, _COPY_THREADS)
