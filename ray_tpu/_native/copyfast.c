/* Native hot-path helpers for the shared-memory object store.
 *
 * Equivalent of the reference's C++ plasma client copy path
 * (reference: src/ray/object_manager/plasma/client.cc — WriteObject
 * uses multithreaded memcpy for large objects; ray_config_def.h
 * object_store_memcpy_threads).  A single-threaded Python memoryview
 * copy tops out around 4.6 GB/s on this host; splitting the copy
 * across threads reaches ~8 GB/s, and read-touching fresh PTEs in
 * parallel removes most page-fault stalls.
 *
 * Built at first import by ray_tpu/_native/__init__.py:
 *   cc -O3 -shared -fPIC -pthread copyfast.c -o <cache>/copyfast.so
 */

#include <pthread.h>
#include <stddef.h>
#include <string.h>

typedef struct {
    char *dst;
    const char *src;
    size_t n;
} copy_job_t;

static void *copy_run(void *arg) {
    copy_job_t *j = (copy_job_t *)arg;
    memcpy(j->dst, j->src, j->n);
    return 0;
}

/* Copy n bytes using up to nthreads threads (page-aligned chunks).
 * Small copies stay single-threaded: thread spawn costs ~30us. */
void parallel_copy(char *dst, const char *src, size_t n, int nthreads) {
    if (nthreads < 2 || n < (size_t)(1 << 21)) {
        memcpy(dst, src, n);
        return;
    }
    if (nthreads > 64)
        nthreads = 64;
    pthread_t threads[64];
    copy_job_t jobs[64];
    size_t chunk = (n + (size_t)nthreads - 1) / (size_t)nthreads;
    chunk = (chunk + 4095) & ~(size_t)4095;
    int started = 0;
    for (int i = 0; i < nthreads; i++) {
        size_t off = (size_t)i * chunk;
        if (off >= n)
            break;
        size_t len = n - off < chunk ? n - off : chunk;
        jobs[started].dst = dst + off;
        jobs[started].src = src + off;
        jobs[started].n = len;
        if (pthread_create(&threads[started], 0, copy_run,
                           &jobs[started]) != 0) {
            /* thread spawn failed: finish inline */
            memcpy(dst + off, src + off, n - off);
            break;
        }
        started++;
    }
    for (int i = 0; i < started; i++)
        pthread_join(threads[i], 0);
}

typedef struct {
    const volatile char *p;
    size_t n;
} touch_job_t;

static void *touch_run(void *arg) {
    touch_job_t *j = (touch_job_t *)arg;
    volatile char sink = 0;
    for (size_t off = 0; off < j->n; off += 4096)
        sink ^= j->p[off];
    (void)sink;
    return 0;
}

/* Read-fault one byte per page so a following write runs at memcpy
 * speed instead of write-fault speed (PTE setup for already-resident
 * tmpfs pages). */
void parallel_touch(const char *p, size_t n, int nthreads) {
    if (nthreads < 2 || n < (size_t)(1 << 22)) {
        touch_job_t j = {p, n};
        touch_run(&j);
        return;
    }
    if (nthreads > 64)
        nthreads = 64;
    pthread_t threads[64];
    touch_job_t jobs[64];
    size_t chunk = (n + (size_t)nthreads - 1) / (size_t)nthreads;
    chunk = (chunk + 4095) & ~(size_t)4095;
    int started = 0;
    for (int i = 0; i < nthreads; i++) {
        size_t off = (size_t)i * chunk;
        if (off >= n)
            break;
        jobs[started].p = p + off;
        jobs[started].n = n - off < chunk ? n - off : chunk;
        if (pthread_create(&threads[started], 0, touch_run,
                           &jobs[started]) != 0) {
            touch_job_t j = {p + off, n - off};
            touch_run(&j);
            break;
        }
        started++;
    }
    for (int i = 0; i < started; i++)
        pthread_join(threads[i], 0);
}
