/* Native hot-path helpers for the shared-memory object store.
 *
 * Equivalent of the reference's C++ plasma client copy path
 * (reference: src/ray/object_manager/plasma/client.cc — WriteObject
 * uses multithreaded memcpy for large objects; ray_config_def.h
 * object_store_memcpy_threads).  A single-threaded Python memoryview
 * copy tops out around 4.6 GB/s on this host; splitting the copy
 * across threads reaches ~8 GB/s, and read-touching fresh PTEs in
 * parallel removes most page-fault stalls.
 *
 * Built at first import by ray_tpu/_native/__init__.py:
 *   cc -O3 -shared -fPIC -pthread copyfast.c -o <cache>/copyfast.so
 */

#include <pthread.h>
#include <stddef.h>
#include <string.h>

typedef struct {
    char *dst;
    const char *src;
    size_t n;
} copy_job_t;

static void *copy_run(void *arg) {
    copy_job_t *j = (copy_job_t *)arg;
    memcpy(j->dst, j->src, j->n);
    return 0;
}

/* Copy n bytes using up to nthreads threads (page-aligned chunks).
 * Small copies stay single-threaded: thread spawn costs ~30us. */
void parallel_copy(char *dst, const char *src, size_t n, int nthreads) {
    if (nthreads < 2 || n < (size_t)(1 << 21)) {
        memcpy(dst, src, n);
        return;
    }
    if (nthreads > 64)
        nthreads = 64;
    pthread_t threads[64];
    copy_job_t jobs[64];
    size_t chunk = (n + (size_t)nthreads - 1) / (size_t)nthreads;
    chunk = (chunk + 4095) & ~(size_t)4095;
    int started = 0;
    for (int i = 0; i < nthreads; i++) {
        size_t off = (size_t)i * chunk;
        if (off >= n)
            break;
        size_t len = n - off < chunk ? n - off : chunk;
        jobs[started].dst = dst + off;
        jobs[started].src = src + off;
        jobs[started].n = len;
        if (pthread_create(&threads[started], 0, copy_run,
                           &jobs[started]) != 0) {
            /* thread spawn failed: finish inline */
            memcpy(dst + off, src + off, n - off);
            break;
        }
        started++;
    }
    for (int i = 0; i < started; i++)
        pthread_join(threads[i], 0);
}

typedef struct {
    const volatile char *p;
    size_t n;
} touch_job_t;

static void *touch_run(void *arg) {
    touch_job_t *j = (touch_job_t *)arg;
    volatile char sink = 0;
    for (size_t off = 0; off < j->n; off += 4096)
        sink ^= j->p[off];
    (void)sink;
    return 0;
}

typedef struct {
    volatile char *p;
    size_t n;
} touchw_job_t;

static void *touchw_run(void *arg) {
    touchw_job_t *j = (touchw_job_t *)arg;
    for (size_t off = 0; off < j->n; off += 4096)
        j->p[off] = j->p[off]; /* volatile: not elided; preserves bytes */
    return 0;
}

/* WRITE-fault one byte per page: installs writable PTEs in one pass.
 * A read-touch maps pages read-only and the following store still pays
 * a write-protect upgrade fault per page; callers that are about to
 * overwrite a region they own (plasma put) want this variant. */
void parallel_touch_write(char *p, size_t n, int nthreads) {
    if (nthreads < 2 || n < (size_t)(1 << 22)) {
        touchw_job_t j = {p, n};
        touchw_run(&j);
        return;
    }
    if (nthreads > 64)
        nthreads = 64;
    pthread_t threads[64];
    touchw_job_t jobs[64];
    size_t chunk = (n + (size_t)nthreads - 1) / (size_t)nthreads;
    chunk = (chunk + 4095) & ~(size_t)4095;
    int started = 0;
    for (int i = 0; i < nthreads; i++) {
        size_t off = (size_t)i * chunk;
        if (off >= n)
            break;
        jobs[started].p = p + off;
        jobs[started].n = n - off < chunk ? n - off : chunk;
        if (pthread_create(&threads[started], 0, touchw_run,
                           &jobs[started]) != 0) {
            touchw_job_t j = {p + off, n - off};
            touchw_run(&j);
            break;
        }
        started++;
    }
    for (int i = 0; i < started; i++)
        pthread_join(threads[i], 0);
}

/* Read-fault one byte per page so a following write runs at memcpy
 * speed instead of write-fault speed (PTE setup for already-resident
 * tmpfs pages). */
void parallel_touch(const char *p, size_t n, int nthreads) {
    if (nthreads < 2 || n < (size_t)(1 << 22)) {
        touch_job_t j = {p, n};
        touch_run(&j);
        return;
    }
    if (nthreads > 64)
        nthreads = 64;
    pthread_t threads[64];
    touch_job_t jobs[64];
    size_t chunk = (n + (size_t)nthreads - 1) / (size_t)nthreads;
    chunk = (chunk + 4095) & ~(size_t)4095;
    int started = 0;
    for (int i = 0; i < nthreads; i++) {
        size_t off = (size_t)i * chunk;
        if (off >= n)
            break;
        jobs[started].p = p + off;
        jobs[started].n = n - off < chunk ? n - off : chunk;
        if (pthread_create(&threads[started], 0, touch_run,
                           &jobs[started]) != 0) {
            touch_job_t j = {p + off, n - off};
            touch_run(&j);
            break;
        }
        started++;
    }
    for (int i = 0; i < started; i++)
        pthread_join(threads[i], 0);
}

/* ---------------------------------------------------------------------
 * First-fit free-list allocator with coalescing (the object-store
 * arena allocator; reference: src/ray/object_manager/plasma/malloc.cc
 * is likewise native).  Offsets and sizes are 64-byte aligned, matching
 * the Python FreeListAllocator it replaces.
 */

#include <stdlib.h>

typedef struct {
    size_t off;
    size_t size;
} fl_block_t;

typedef struct {
    size_t capacity;
    size_t allocated;
    fl_block_t *blocks; /* sorted by offset */
    size_t n;
    size_t cap_blocks;
} fl_t;

static size_t fl_align(size_t n) {
    n = n ? n : 1;
    return (n + 63) & ~(size_t)63;
}

void *fl_new(size_t capacity) {
    fl_t *f = (fl_t *)malloc(sizeof(fl_t));
    if (!f)
        return 0;
    f->capacity = capacity;
    f->allocated = 0;
    f->cap_blocks = 16;
    f->blocks = (fl_block_t *)malloc(f->cap_blocks * sizeof(fl_block_t));
    if (!f->blocks) {
        free(f);
        return 0;
    }
    f->blocks[0].off = 0;
    f->blocks[0].size = capacity;
    f->n = 1;
    return f;
}

void fl_destroy(void *h) {
    fl_t *f = (fl_t *)h;
    if (f) {
        free(f->blocks);
        free(f);
    }
}

size_t fl_allocated(void *h) { return ((fl_t *)h)->allocated; }

/* returns the offset, or (size_t)-1 when no block fits */
size_t fl_alloc(void *h, size_t size) {
    fl_t *f = (fl_t *)h;
    size = fl_align(size);
    for (size_t i = 0; i < f->n; i++) {
        if (f->blocks[i].size >= size) {
            size_t off = f->blocks[i].off;
            if (f->blocks[i].size == size) {
                for (size_t j = i + 1; j < f->n; j++)
                    f->blocks[j - 1] = f->blocks[j];
                f->n--;
            } else {
                f->blocks[i].off += size;
                f->blocks[i].size -= size;
            }
            f->allocated += size;
            return off;
        }
    }
    return (size_t)-1;
}

static int fl_grow(fl_t *f) {
    if (f->n < f->cap_blocks)
        return 1;
    size_t ncap = f->cap_blocks * 2;
    fl_block_t *nb =
        (fl_block_t *)realloc(f->blocks, ncap * sizeof(fl_block_t));
    if (!nb)
        return 0;
    f->blocks = nb;
    f->cap_blocks = ncap;
    return 1;
}

/* returns 0 on success, -1 on internal allocation failure (in which
 * case NO state was mutated — the caller may retry the free) */
int fl_free(void *h, size_t offset, size_t size) {
    fl_t *f = (fl_t *)h;
    size = fl_align(size);
    /* reserve block-array capacity BEFORE mutating anything: a failed
     * realloc must not lose the region nor skew `allocated` */
    if (!fl_grow(f))
        return -1;
    f->allocated -= size;
    /* binary search for insertion point (blocks sorted by offset) */
    size_t lo = 0, hi = f->n;
    while (lo < hi) {
        size_t mid = (lo + hi) / 2;
        if (f->blocks[mid].off < offset)
            lo = mid + 1;
        else
            hi = mid;
    }
    /* try to coalesce with the previous / next block without inserting */
    int merged = 0;
    if (lo > 0 &&
        f->blocks[lo - 1].off + f->blocks[lo - 1].size == offset) {
        f->blocks[lo - 1].size += size;
        merged = 1;
        /* may now touch the next block too */
        if (lo < f->n &&
            f->blocks[lo - 1].off + f->blocks[lo - 1].size ==
                f->blocks[lo].off) {
            f->blocks[lo - 1].size += f->blocks[lo].size;
            for (size_t j = lo + 1; j < f->n; j++)
                f->blocks[j - 1] = f->blocks[j];
            f->n--;
        }
    } else if (lo < f->n && offset + size == f->blocks[lo].off) {
        f->blocks[lo].off = offset;
        f->blocks[lo].size += size;
        merged = 1;
    }
    if (!merged) {
        for (size_t j = f->n; j > lo; j--)
            f->blocks[j] = f->blocks[j - 1];
        f->blocks[lo].off = offset;
        f->blocks[lo].size = size;
        f->n++;
    }
    return 0;
}

size_t fl_largest(void *h) {
    fl_t *f = (fl_t *)h;
    size_t best = 0;
    for (size_t i = 0; i < f->n; i++)
        if (f->blocks[i].size > best)
            best = f->blocks[i].size;
    return best;
}
