"""Parallelism primitives: device meshes, sharding specs, collectives.

TPU-native replacement for the reference's NCCL/process-group layer
(reference: python/ray/util/collective/, python/ray/train/torch/config.py
_setup_torch_process_group): instead of NCCL rings bootstrapped over RPC,
parallelism is expressed as a `jax.sharding.Mesh` with named axes and
GSPMD shardings; XLA inserts the collectives over ICI/DCN.
"""

from ray_tpu.parallel.mesh import (MeshSpec, make_mesh, mesh_axes_for,
                                   shard_batch, shard_params)

__all__ = ["MeshSpec", "make_mesh", "mesh_axes_for", "shard_batch",
           "shard_params"]
