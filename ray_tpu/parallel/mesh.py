"""Device mesh construction and sharding helpers.

The mesh axis vocabulary follows the scaling-book convention:
  - "dp":   pure data parallelism (params replicated, batch sharded)
  - "fsdp": fully-sharded data parallelism (params + batch sharded)
  - "tp":   tensor parallelism (heads / mlp-hidden sharded)
  - "sp":   sequence/context parallelism (sequence dim sharded; ring
            attention carries the KV rotation over ICI)
  - "pp":   pipeline stages

The reference has no equivalent — torch DDP/FSDP wrap modules
(reference: python/ray/train/torch/train_loop_utils.py:158 prepare_model);
here a `MeshSpec` lowers to a `jax.sharding.Mesh` + `PartitionSpec` rules
and XLA/GSPMD does the rest.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout; -1 on at most one axis means 'fill'."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        fills = [a for a, v in sizes.items() if v == -1]
        if len(fills) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if fills:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[fills[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return MeshSpec(**sizes)

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def nontrivial_axes(self) -> List[str]:
        return [a for a in AXIS_ORDER if getattr(self, a) > 1]


def mesh_axes_for(n_devices: int, spec: Optional[MeshSpec] = None
                  ) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
    spec = (spec or MeshSpec(dp=-1)).resolve(n_devices)
    sizes = spec.axis_sizes()
    return tuple(AXIS_ORDER), tuple(sizes[a] for a in AXIS_ORDER)


def make_mesh(spec: Optional[MeshSpec] = None, devices: Optional[Sequence] = None):
    """Build a Mesh over the given (default: all) devices.

    Axes are laid out in AXIS_ORDER so that the innermost axes (tp, sp)
    map to the most tightly ICI-coupled device neighbourhoods — XLA's
    device assignment for TPU slices keeps later mesh dims closer.
    """
    import jax
    import numpy as np

    devices = list(devices if devices is not None else jax.devices())
    names, sizes = mesh_axes_for(len(devices), spec)
    dev_array = np.array(devices).reshape(sizes)
    return jax.sharding.Mesh(dev_array, names)


def batch_pspec():
    """PartitionSpec for an activation batch dim: sharded over dp+fsdp."""
    from jax.sharding import PartitionSpec as P

    return P(("dp", "fsdp"))


def shard_batch(mesh, batch):
    """NamedSharding a pytree of host arrays: dim 0 over (dp, fsdp),
    dim 1 (sequence) over sp when present."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        if getattr(x, "ndim", 0) >= 2 and mesh.shape.get("sp", 1) > 1:
            spec = P(("dp", "fsdp"), "sp")
        elif getattr(x, "ndim", 0) >= 1:
            spec = P(("dp", "fsdp"))
        else:
            spec = P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, batch)


def shard_params(mesh, params, rules: Optional[Dict[str, Any]] = None):
    """Apply fsdp sharding to a parameter pytree: the largest dim of each
    leaf is sharded over 'fsdp' (plus explicit per-path rules for tp).

    This is the generic fallback; models ship precise PartitionSpec rules
    (see ray_tpu/models/llama.py param_pspecs) that this function accepts
    via `rules` keyed by joined path.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    fsdp = mesh.shape.get("fsdp", 1)

    def spec_for(path, x) -> "P":
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        if rules:
            for pat, spec in rules.items():
                if pat in key:
                    return spec
        if fsdp > 1 and getattr(x, "ndim", 0) >= 1:
            dims = list(x.shape)
            best = max(range(len(dims)), key=lambda i: dims[i])
            if dims[best] % fsdp == 0:
                spec = [None] * len(dims)
                spec[best] = "fsdp"
                return P(*spec)
        return P()

    def put(path, x):
        return jax.device_put(x, NamedSharding(mesh, spec_for(path, x)))

    return jax.tree_util.tree_map_with_path(put, params)
