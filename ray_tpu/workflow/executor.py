"""Workflow executor: durable, resumable DAG execution.

Equivalent of the reference's workflow executor
(reference: python/ray/workflow/workflow_executor.py:1,
task_executor.py — each step's output is checkpointed to storage before
its dependents run; resume replays the DAG, skipping checkpointed
steps; a step may return ``continuation(dag)`` to extend the workflow
dynamically).

Execution model: steps run as regular cluster tasks, submitted eagerly
(independent steps run in parallel); results are fetched and persisted
in deterministic topological order.  A driver crash between persists
loses only unpersisted steps — resume re-submits exactly those.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.dag.nodes import (ClassMethodNode, ClassNode, DAGNode,
                               FunctionNode, InputNode, MultiOutputNode)
from ray_tpu.workflow.storage import WorkflowStorage


class Continuation:
    """Wrapper a step returns to hand the workflow off to a new DAG."""

    def __init__(self, dag: DAGNode):
        if not isinstance(dag, DAGNode):
            raise TypeError("continuation(...) takes a bound DAG node")
        self.dag = dag


def _check_supported(dag: DAGNode) -> None:
    for node in dag.topological():
        if isinstance(node, (ClassNode, ClassMethodNode)):
            raise TypeError(
                "workflows are task-based: actor nodes are not durable "
                "(reference drops virtual actors); use tasks or run the "
                "actor inside a step")
        if isinstance(node, InputNode):
            raise TypeError("workflows capture their inputs at .bind() "
                            "time; InputNode is for compiled DAGs")


def _step_keys(dag: DAGNode, prefix: str) -> Dict[int, str]:
    """Deterministic step key per node: topological index + task name.
    Stable across resume because topological() is deterministic for a
    given (unpickled) DAG structure."""
    keys = {}
    for i, node in enumerate(dag.topological()):
        if isinstance(node, FunctionNode):
            keys[id(node)] = f"{prefix}{i:04d}-{node.name}"
    return keys


class WorkflowExecutor:
    def __init__(self, storage: WorkflowStorage, workflow_id: str):
        self.storage = storage
        self.workflow_id = workflow_id

    def run_dag(self, dag: DAGNode, key_prefix: str = "") -> Any:
        """Execute one DAG level; recurses into continuations.

        Wave scheduler: a step is submitted once every dependency has a
        *persisted* value (never a raw ref — an upstream step may return
        a continuation, whose durable value only exists after the
        continuation DAG ran).  Independent steps still run in parallel.
        """
        import ray_tpu

        _check_supported(dag)
        keys = _step_keys(dag, key_prefix)
        memo: Dict[int, Any] = {}     # node id -> durable value
        in_flight: Dict[Any, DAGNode] = {}  # ref -> node
        order = dag.topological()
        done = set()

        def deps_ready(node: DAGNode) -> bool:
            return all(id(c) in memo for c in node._children())

        while len(done) < len(order):
            progressed = False
            for node in order:
                if id(node) in memo or node in (n for n in in_flight.values()):
                    continue
                if not deps_ready(node):
                    continue
                if isinstance(node, FunctionNode):
                    key = keys[id(node)]
                    if self.storage.has_step(self.workflow_id, key):
                        memo[id(node)] = self.storage.load_step(
                            self.workflow_id, key)
                        done.add(id(node))
                        self.storage.log_event(
                            self.workflow_id,
                            {"event": "step_cached", "step": key})
                    else:
                        args, kwargs = node._resolved_args(memo)
                        in_flight[node._remote_fn.remote(*args, **kwargs)] = \
                            node
                        self.storage.log_event(
                            self.workflow_id,
                            {"event": "step_started", "step": key})
                    progressed = True
                elif isinstance(node, MultiOutputNode):
                    memo[id(node)] = [memo[id(n)] for n in node._outputs]
                    done.add(id(node))
                    progressed = True
                else:  # nested constants / structures
                    memo[id(node)] = node._apply(memo, (), {})
                    done.add(id(node))
                    progressed = True
            if in_flight:
                ready, _ = ray_tpu.wait(list(in_flight), num_returns=1)
                for ref in ready:
                    node = in_flight.pop(ref)
                    key = keys[id(node)]
                    value = ray_tpu.get(ref)
                    if isinstance(value, Continuation):
                        self.storage.log_event(
                            self.workflow_id,
                            {"event": "continuation", "step": key})
                        value = self.run_dag(value.dag, key_prefix=key + ".")
                    self.storage.save_step(self.workflow_id, key, value)
                    self.storage.log_event(
                        self.workflow_id,
                        {"event": "step_finished", "step": key})
                    memo[id(node)] = value
                    done.add(id(node))
            elif not progressed:
                raise RuntimeError("workflow DAG made no progress "
                                   "(cycle or unsupported node)")
        return memo[id(dag)]

    def run(self, dag: DAGNode) -> Any:
        self.storage.set_status(self.workflow_id, "RUNNING")
        try:
            result = self.run_dag(dag)
        except BaseException:
            self.storage.set_status(self.workflow_id, "FAILED")
            raise
        self.storage.save_result(self.workflow_id, result)
        self.storage.set_status(self.workflow_id, "SUCCEEDED")
        return result
