"""Durable workflow storage: filesystem layout + atomic writes.

Equivalent of the reference's workflow storage
(reference: python/ray/workflow/workflow_storage.py:1 — step results,
DAG snapshot, and status live under a per-workflow directory; writes
are atomic so a crash mid-write never corrupts completed state).

Layout:
    <root>/<workflow_id>/dag.pkl          the bound DAG (cloudpickle)
    <root>/<workflow_id>/status           json: {"status": ..., ts}
    <root>/<workflow_id>/result.pkl       final output when SUCCEEDED
    <root>/<workflow_id>/steps/<key>.pkl  durable per-step results
    <root>/<workflow_id>/log.jsonl        append-only step event log
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, List, Optional, Tuple

_DEFAULT_ROOT = os.path.join(
    os.environ.get("RT_WORKFLOW_STORAGE",
                   os.path.expanduser("~/.ray_tpu/workflows")))


def _atomic_write(path: str, data: bytes) -> None:
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class WorkflowStorage:
    def __init__(self, root: Optional[str] = None):
        self.root = root or _DEFAULT_ROOT

    def _wf_dir(self, workflow_id: str) -> str:
        if not workflow_id or "/" in workflow_id or workflow_id.startswith("."):
            raise ValueError(f"invalid workflow id: {workflow_id!r}")
        return os.path.join(self.root, workflow_id)

    # ----------------------------------------------------------------- DAG

    def save_dag(self, workflow_id: str, dag: Any) -> None:
        import cloudpickle

        _atomic_write(os.path.join(self._wf_dir(workflow_id), "dag.pkl"),
                      cloudpickle.dumps(dag))

    def load_dag(self, workflow_id: str) -> Any:
        import cloudpickle

        path = os.path.join(self._wf_dir(workflow_id), "dag.pkl")
        with open(path, "rb") as f:
            return cloudpickle.loads(f.read())

    # -------------------------------------------------------------- status

    def set_status(self, workflow_id: str, status: str) -> None:
        _atomic_write(os.path.join(self._wf_dir(workflow_id), "status"),
                      json.dumps({"status": status,
                                  "ts": time.time()}).encode())

    def get_status(self, workflow_id: str) -> Optional[str]:
        path = os.path.join(self._wf_dir(workflow_id), "status")
        try:
            with open(path) as f:
                return json.load(f)["status"]
        except (OSError, ValueError, KeyError):
            return None

    def list_all(self) -> List[Tuple[str, str]]:
        try:
            ids = sorted(os.listdir(self.root))
        except OSError:
            return []
        out = []
        for wid in ids:
            status = self.get_status(wid)
            if status is not None:
                out.append((wid, status))
        return out

    def delete(self, workflow_id: str) -> None:
        import shutil

        shutil.rmtree(self._wf_dir(workflow_id), ignore_errors=True)

    # --------------------------------------------------------------- steps

    def _step_path(self, workflow_id: str, key: str) -> str:
        safe = key.replace("/", "_").replace("..", "_")
        if len(safe) > 100:
            # deep continuation chains produce unbounded keys; the digest
            # stays deterministic because the key itself is
            import hashlib

            safe = safe[:60] + "-" + hashlib.sha256(safe.encode()).hexdigest()
        return os.path.join(self._wf_dir(workflow_id), "steps", safe + ".pkl")

    def has_step(self, workflow_id: str, key: str) -> bool:
        return os.path.exists(self._step_path(workflow_id, key))

    def save_step(self, workflow_id: str, key: str, value: Any) -> None:
        import cloudpickle

        _atomic_write(self._step_path(workflow_id, key),
                      cloudpickle.dumps(value))

    def load_step(self, workflow_id: str, key: str) -> Any:
        import cloudpickle

        with open(self._step_path(workflow_id, key), "rb") as f:
            return cloudpickle.loads(f.read())

    # -------------------------------------------------------------- result

    def save_result(self, workflow_id: str, value: Any) -> None:
        import cloudpickle

        _atomic_write(os.path.join(self._wf_dir(workflow_id), "result.pkl"),
                      cloudpickle.dumps(value))

    def load_result(self, workflow_id: str) -> Any:
        import cloudpickle

        path = os.path.join(self._wf_dir(workflow_id), "result.pkl")
        with open(path, "rb") as f:
            return cloudpickle.loads(f.read())

    # ----------------------------------------------------------------- log

    def log_event(self, workflow_id: str, event: dict) -> None:
        path = os.path.join(self._wf_dir(workflow_id), "log.jsonl")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps({**event, "ts": time.time()}) + "\n")

    def read_log(self, workflow_id: str) -> List[dict]:
        path = os.path.join(self._wf_dir(workflow_id), "log.jsonl")
        try:
            with open(path) as f:
                return [json.loads(line) for line in f if line.strip()]
        except OSError:
            return []
