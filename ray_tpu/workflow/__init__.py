"""Durable workflows: run task DAGs whose step outputs are checkpointed,
so a crashed run resumes where it left off.

Equivalent of the reference's ``ray.workflow``
(reference: python/ray/workflow/api.py:1 — run/run_async/resume/
get_status/get_output/list_all/delete + continuation).

Usage:
    @ray_tpu.remote
    def fetch(x): ...

    wf = process.bind(fetch.bind(1), fetch.bind(2))
    workflow.run(wf, workflow_id="etl-2026-07-30")
    # after a crash:
    workflow.resume("etl-2026-07-30")
"""

from __future__ import annotations

import threading
import uuid
from concurrent.futures import Future
from typing import Any, List, Optional, Tuple

from ray_tpu.dag.nodes import DAGNode
from ray_tpu.workflow.executor import Continuation, WorkflowExecutor
from ray_tpu.workflow.storage import WorkflowStorage

_storage: Optional[WorkflowStorage] = None
_lock = threading.Lock()


def init(storage: Optional[str] = None) -> None:
    """Set the storage root (defaults to RT_WORKFLOW_STORAGE or
    ~/.ray_tpu/workflows)."""
    global _storage
    with _lock:
        _storage = WorkflowStorage(storage)


def _get_storage() -> WorkflowStorage:
    global _storage
    with _lock:
        if _storage is None:
            _storage = WorkflowStorage()
        return _storage


def continuation(dag: DAGNode) -> Continuation:
    """Return this from a step to continue the workflow with a new DAG;
    the step's durable result becomes the continuation's output."""
    return Continuation(dag)


def run(dag: DAGNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute a DAG durably; blocks until the result is available.
    Re-running a finished workflow_id returns the stored result."""
    storage = _get_storage()
    workflow_id = workflow_id or f"wf-{uuid.uuid4().hex[:12]}"
    if storage.get_status(workflow_id) == "SUCCEEDED":
        return storage.load_result(workflow_id)
    storage.save_dag(workflow_id, dag)
    return WorkflowExecutor(storage, workflow_id).run(dag)


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None) -> Future:
    """Like run(), returning a concurrent.futures.Future."""
    fut: Future = Future()

    def body():
        try:
            fut.set_result(run(dag, workflow_id=workflow_id))
        except BaseException as exc:  # noqa: BLE001 — delivered via future
            fut.set_exception(exc)

    threading.Thread(target=body, daemon=True,
                     name=f"workflow-{workflow_id}").start()
    return fut


def resume(workflow_id: str) -> Any:
    """Re-drive a FAILED/RUNNING(orphaned) workflow from its snapshot;
    checkpointed steps are skipped."""
    storage = _get_storage()
    status = storage.get_status(workflow_id)
    if status is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    if status == "SUCCEEDED":
        return storage.load_result(workflow_id)
    dag = storage.load_dag(workflow_id)
    return WorkflowExecutor(storage, workflow_id).run(dag)


def resume_async(workflow_id: str) -> Future:
    fut: Future = Future()

    def body():
        try:
            fut.set_result(resume(workflow_id))
        except BaseException as exc:  # noqa: BLE001
            fut.set_exception(exc)

    threading.Thread(target=body, daemon=True,
                     name=f"workflow-resume-{workflow_id}").start()
    return fut


def get_status(workflow_id: str) -> str:
    status = _get_storage().get_status(workflow_id)
    if status is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    return status


def get_output(workflow_id: str) -> Any:
    """Stored result of a SUCCEEDED workflow."""
    storage = _get_storage()
    status = storage.get_status(workflow_id)
    if status != "SUCCEEDED":
        raise ValueError(
            f"workflow {workflow_id!r} has no output (status={status})")
    return storage.load_result(workflow_id)


def list_all() -> List[Tuple[str, str]]:
    return _get_storage().list_all()


def delete(workflow_id: str) -> None:
    _get_storage().delete(workflow_id)


__all__ = ["init", "run", "run_async", "resume", "resume_async",
           "get_status", "get_output", "list_all", "delete", "continuation"]
