"""Pallas TPU paged attention for single-token decode with GQA.

Decode attention over the serving tier's paged KV cache
(serve/llm.py): instead of gathering the whole ``[B, L]`` slot-table
context out of the flat pools and softmaxing over ``-1e30``-masked
garbage (``models/llama.py cached_attention``), the kernel walks each
sequence's **used pages only** — the grid's sequential page dimension
carries flash-style online-softmax scratch (running max / denominator)
so no dense context copy or score matrix ever materializes.

Page indirection happens in the BlockSpec index maps via scalar
prefetch: the block table and context lengths arrive as
``PrefetchScalarGridSpec`` scalar operands, so the KV block fetched at
grid step ``(b, p)`` is the *physical* page ``block_tables[b, p]``
read straight from the flat pool.  Pages past a sequence's used count
are clamped to its last used page — the same index as the previous
grid step, which Pallas recognizes and skips the redundant copy — and
their compute is predicated off with ``pl.when``.  All KV heads ride
in one block (the grid is ``(B, W)``, not ``(B * Hkv, W)``): one page
fetch serves every head, and the per-head attention math batches over
the leading head dim inside the kernel.  Prefix-shared and CoW-split
pages need no special handling: the kernel only ever addresses
physical pages through the table, exactly like the dense gather it
replaces.

Compiled on TPU, ``interpret=True`` on CPU (same numerics, pure jax)
so tier-1 validates the kernel path end to end.

Layout: q [B, 1, H, D]; pools [T, Hkv, D] flat slot pools with
T = num_pages * page_size; block_tables [B, W] physical page ids
(unused entries may point anywhere valid, e.g. the garbage page 0);
context_lens [B] tokens of live context per lane (0 = inactive lane,
output is zeros).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _paged_kernel(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, page_size: int,
                  scale: float):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    pi = pl.program_id(1)
    n_p = pl.num_programs(1)
    ctx = cl_ref[b]
    used = (ctx + page_size - 1) // page_size

    @pl.when(pi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(pi < used)
    def _update():
        q = q_ref[0].astype(jnp.float32)               # [Hkv, G, D]
        k = k_ref[0].transpose(1, 0, 2).astype(jnp.float32)  # [Hkv, P, D]
        v = v_ref[0].transpose(1, 0, 2).astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # [Hkv, G, P]
        # rows of the last used page beyond the context length hold
        # garbage (or another sequence's data on a shared page tail)
        pos = pi * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2)
        valid = pos < ctx                               # [1, 1, P]
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_ref[:, :, :1]                        # [Hkv, G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m_prev - m_new)                  # [Hkv, G, 1]
        l_ref[:, :, :1] = l_ref[:, :, :1] * corr \
            + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, :, :1] = m_new
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)         # [Hkv, G, D]

    @pl.when(pi == n_p - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :, :1], 1e-20)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def paged_attention(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                    block_tables: jax.Array, context_lens: jax.Array,
                    *, page_size: int,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Single-token decode attention over paged KV pools.

    q: [B, 1, H, D] post-rope queries (the current token's k/v must
    already be written into the pools); pool_k/pool_v: [T, Hkv, D];
    block_tables: [B, W] physical page of each logical page; and
    context_lens: [B] live tokens per lane (position < context_lens[b]
    attends — causality for decode, since the query sits at position
    context_lens[b] - 1).  Returns [B, 1, H, D] in q's dtype.

    Cost scales with ``W`` (the block-table width), not the pool or max
    context: callers shrink W to the max used pages across the batch.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, s, h, d = q.shape
    assert s == 1, f"paged_attention is decode-only (S=1), got S={s}"
    num_slots, hkv, _ = pool_k.shape
    assert num_slots % page_size == 0, "pool not page-aligned"
    num_pages = num_slots // page_size
    g = h // hkv
    w = block_tables.shape[1]
    scale = 1.0 / (d ** 0.5)

    qr = q.reshape(b, hkv, g, d)                     # GQA head grouping
    kp = pool_k.reshape(num_pages, page_size, hkv, d)
    vp = pool_v.reshape(num_pages, page_size, hkv, d)
    bt = block_tables.astype(jnp.int32)
    cl = context_lens.astype(jnp.int32)

    if interpret:
        # interpret mode carries whole operands through its grid loop,
        # making every step O(pool size) on CPU no matter how narrow
        # the table is.  Gather the table-reachable pages into a
        # compact pool and rebase the table: the kernel sees identical
        # content (shared pages arrive as duplicated rows — same
        # numerics), the gather itself is O(used context), and step
        # cost stays independent of the pool/max-context capacity.
        # The compiled TPU path never takes this branch — it DMAs
        # single pages straight from the flat pool via the index map.
        flat = bt.reshape(-1)
        kp = kp[flat]                                 # [B*W, P, Hkv, D]
        vp = vp[flat]
        bt = jnp.arange(b * w, dtype=jnp.int32).reshape(b, w)

    def _kv_index(bi, pi, bt, cl):
        # clamp unused grid steps to the last used page: same index as
        # the previous step, so the pipeline skips the redundant copy
        used = (cl[bi] + page_size - 1) // page_size
        p = jnp.minimum(pi, jnp.maximum(used - 1, 0))
        return (bt[bi, p], 0, 0, 0)

    kernel = functools.partial(_paged_kernel, page_size=page_size,
                               scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, w),
        in_specs=[
            pl.BlockSpec((1, hkv, g, d),
                         lambda bi, pi, bt, cl: (bi, 0, 0, 0)),
            pl.BlockSpec((1, page_size, hkv, d), _kv_index),
            pl.BlockSpec((1, page_size, hkv, d), _kv_index),
        ],
        out_specs=pl.BlockSpec((1, hkv, g, d),
                               lambda bi, pi, bt, cl: (bi, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hkv, g, d), jnp.float32),     # acc
            pltpu.VMEM((hkv, g, 128), jnp.float32),   # running max
            pltpu.VMEM((hkv, g, 128), jnp.float32),   # running denom
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(bt, cl, qr, kp, vp)
    return out.reshape(b, 1, h, d)
