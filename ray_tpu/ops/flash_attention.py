"""Pallas TPU flash attention (forward) with GQA and causal masking.

Online-softmax blockwise attention: the KV sequence never materializes a
[S, S] score matrix in HBM — scores live in VMEM one (block_q, block_k)
tile at a time with running max/denominator scratch carried across the
sequential kv grid dimension (guide: scratch persists across grid steps).

The backward pass recomputes through the reference dense attention via
custom_vjp: training paths use ring/default attention (pure jax,
autodiff-friendly); this kernel targets the serving/prefill path where
activation memory dominates.

Layout: q [B, S, H, D]; k/v [B, T, Hkv, D] (GQA groups = H // Hkv).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                  *, block_q: int, block_k: int, causal: bool, scale: float):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _update():
        q = q_ref[0, 0].astype(jnp.float32)           # [BQ, D]
        k = k_ref[0, 0].astype(jnp.float32)           # [BK, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_ref[:, :1]                          # [BQ, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        corr = jnp.exp(m_prev - m_new)                 # [BQ, 1]
        l_ref[:, :1] = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, :1] = m_new
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # kv blocks entirely above the diagonal contribute nothing
        @pl.when(ki * block_k <= qi * block_q + (block_q - 1))
        def _():
            _update()
    else:
        _update()

    @pl.when(ki == n_k - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :1], 1e-20)
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    t = k.shape[1]
    block_q = min(block_q, s)
    block_k = min(block_k, t)
    assert s % block_q == 0 and t % block_k == 0, "seq not divisible by block"
    qt = q.transpose(0, 2, 1, 3)   # [B, H, S, D]
    kt = k.transpose(0, 2, 1, 3)   # [B, Hkv, T, D]
    vt = v.transpose(0, 2, 1, 3)
    grid = (b * h, s // block_q, t // block_k)
    scale = 1.0 / (d ** 0.5)

    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bh, qi, ki: (bh // h, bh % h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bh, qi, ki: (bh // h, (bh % h) // g, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bh, qi, ki: (bh // h, (bh % h) // g, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bh, qi, ki: (bh // h, bh % h, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None):
    """Flash attention with a dense-recompute backward."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g_out):
    # dense_attention, not default_attention: the latter routes long
    # sequences back into this kernel, which would recurse at trace time
    from ray_tpu.models.llama import dense_attention

    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: dense_attention(q, k, v, causal=causal),
                     q, k, v)
    return vjp(g_out)


flash_attention.defvjp(_fwd, _bwd)
