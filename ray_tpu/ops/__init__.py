"""TPU compute kernels: ring attention, flash attention.

The reference has no sequence/context parallelism anywhere (SURVEY §5.7
— verified absent), so this package is green-field: long-context support
is built as a first-class mesh axis ("sp") with KV rotation over ICI.
"""

from ray_tpu.ops.ring_attention import make_ring_attention, ring_attention

__all__ = ["ring_attention", "make_ring_attention"]
