"""Ring attention: sequence-parallel causal attention over an "sp" mesh axis.

Each device holds one sequence block of Q, K, V. The KV block rotates
around the ring with `lax.ppermute` (ICI neighbour exchange on TPU) while
the local Q block accumulates attention with an online (flash-style)
softmax — max/denominator carried across blocks — so the full sequence
never materializes on any chip. Memory per chip is O(S/n_sp), enabling
context lengths that a single chip cannot hold.

Green-field design (the reference has no SP/CP — SURVEY §5.7); the
algorithm follows the public ring-attention recipe: blockwise attention +
KV rotation, compute overlapping the permute. XLA overlaps the ppermute
with the block matmuls; a Pallas double-buffered variant can tighten this
further on real ICI.

GQA layout matches the model: q [B, S, H, D], k/v [B, S, Hkv, D].
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# jax >= 0.6 exposes shard_map at top level with check_vma=;
# older releases ship jax.experimental.shard_map with check_rep=
try:
    _shard_map = jax.shard_map
    _CHECK_KW = "check_vma"
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"

_NEG_INF = -1e30


def _block_attend(q, k, v, mask, m, l, acc):
    """One flash-attention accumulation step over a KV block.

    q: [B,S,H,D]; k/v: [B,T,Hkv,D]; mask: [S,T] (True = attend);
    m/l: [B,H,G,S]; acc: [B,S,H,D] in fp32.
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    qg = q.reshape(b, s, hkv, g, d)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    logits = jnp.einsum("bshgd,bthd->bhgst", qg, k).astype(jnp.float32) * scale
    logits = jnp.where(mask[None, None, None], logits, _NEG_INF)
    m_blk = jnp.max(logits, axis=-1)  # [B,Hkv,G,S]
    m_new = jnp.maximum(m, m_blk)
    # keep fully-masked rows stable: exp(-inf - (-inf)) guards
    p = jnp.exp(logits - m_new[..., None])
    p = jnp.where(mask[None, None, None], p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhgst,bthd->bshgd", p.astype(v.dtype), v).astype(jnp.float32)
    acc_new = acc * correction.transpose(0, 3, 1, 2)[..., None].reshape(
        b, s, hkv * g, 1) + pv.reshape(b, s, h, d)
    return m_new, l_new, acc_new


def _ring_body(q, k0, v0, axis_name: str, n_blocks: int, block_len: int,
               causal: bool):
    """Runs on each device inside shard_map; returns the local output."""
    b, s, h, d = q.shape
    hkv = k0.shape[2]
    g = h // hkv
    my_idx = jax.lax.axis_index(axis_name)
    q_pos = my_idx * block_len + jnp.arange(block_len)

    m0 = jnp.full((b, hkv, g, s), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, hkv, g, s), dtype=jnp.float32)
    acc0 = jnp.zeros((b, s, h, d), dtype=jnp.float32)

    def step(t, carry):
        k, v, m, l, acc = carry
        kv_idx = (my_idx - t) % n_blocks
        k_pos = kv_idx * block_len + jnp.arange(block_len)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((block_len, block_len), dtype=bool)
        m, l, acc = _block_attend(q, k, v, mask, m, l, acc)
        # rotate KV to the next ring position (ICI neighbour exchange)
        n = n_blocks
        perm = [(i, (i + 1) % n) for i in range(n)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return k, v, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(
        0, n_blocks, step, (k0, v0, m0, l0, acc0))
    l = jnp.maximum(l, 1e-20)  # fully-masked rows (none under causal) stay finite
    denom = l.transpose(0, 3, 1, 2).reshape(b, s, h, 1)
    return (acc / denom).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name: str = "sp", causal: bool = True,
                   batch_axes=("dp", "fsdp"), head_axis: Optional[str] = "tp"):
    """Sequence-parallel attention. q/k/v are GLOBAL arrays (under jit with
    GSPMD shardings); the shard_map distributes over the mesh:
    batch over (dp, fsdp), sequence over sp, heads over tp."""
    n_sp = mesh.shape[axis_name]
    if n_sp == 1:
        from ray_tpu.models.llama import default_attention

        return default_attention(q, k, v, causal=causal)
    seq_len = q.shape[1]
    if seq_len % n_sp:
        raise ValueError(f"sequence {seq_len} not divisible by sp={n_sp}")
    block_len = seq_len // n_sp
    spec = P(batch_axes, axis_name, head_axis, None)
    body = partial(_ring_body, axis_name=axis_name, n_blocks=n_sp,
                   block_len=block_len, causal=causal)
    fn = _shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, **{_CHECK_KW: False})
    return fn(q, k, v)


def make_ring_attention(mesh, axis_name: str = "sp", **kw) -> Callable:
    """Attention-kernel hook for LlamaModel(kernel=...)."""

    def kernel(q, k, v, causal: bool = True):
        return ring_attention(q, k, v, mesh, axis_name=axis_name,
                              causal=causal, **kw)

    return kernel
