"""Job submission: supervised driver lifecycles on the cluster.

Equivalent of the reference's job submission stack
(reference: dashboard/modules/job/job_manager.py — JobManager launches
a supervisor actor per job that runs the entrypoint as a subprocess,
streams logs, and tracks JobInfo in the GCS KV;
python/ray/dashboard/modules/job/sdk.py JobSubmissionClient).

The supervisor is a detached actor: it Popens the entrypoint with
RT_ADDRESS pointing at the cluster (so `ray_tpu.init()` inside the job
connects automatically), captures combined output, and publishes
status + log tail to the internal KV where any client can read them.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Dict, List, Optional

_STATUS_KEY = "job:{}:status"
_LOGS_KEY = "job:{}:logs"
_INDEX_KEY = "job:index"

TERMINAL = ("SUCCEEDED", "FAILED", "STOPPED")


class _JobSupervisor:
    """Detached actor owning one job's entrypoint process
    (reference: job_manager.py JobSupervisor)."""

    LOG_FLUSH_PERIOD_S = 1.0
    LOG_CAP_BYTES = 1 << 20  # last 1 MiB of output is kept in the KV

    def __init__(self, job_id: str, entrypoint: str, working_dir: str,
                 env_vars: Dict[str, str], address: str):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.working_dir = working_dir
        self.env_vars = env_vars
        self.address = address
        self._stop_requested = False

    def _kv_put(self, key: str, value: bytes) -> None:
        from ray_tpu.experimental import internal_kv

        internal_kv.kv_put(key, value)

    def _set_status(self, **fields) -> None:
        from ray_tpu.experimental import internal_kv

        raw = internal_kv.kv_get(_STATUS_KEY.format(self.job_id))
        info = json.loads(raw) if raw else {}
        info.update(fields)
        self._kv_put(_STATUS_KEY.format(self.job_id),
                     json.dumps(info).encode())

    def run(self) -> str:
        """Run the entrypoint to completion; returns the final status."""
        import os
        import subprocess

        from ray_tpu._private.spawn import set_pdeathsig

        env = dict(os.environ)
        env.update(self.env_vars)
        env["RT_ADDRESS"] = self.address
        env["RT_JOB_ID"] = self.job_id
        self._set_status(status="RUNNING", start_time=time.time(),
                         entrypoint=self.entrypoint)
        buf = bytearray()
        try:
            # own session/process group: stop() can kill the whole group
            # without touching this worker; PDEATHSIG still ties the job
            # to the supervisor's life
            proc = subprocess.Popen(
                self.entrypoint, shell=True,
                cwd=self.working_dir or None, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                preexec_fn=set_pdeathsig, start_new_session=True)
        except Exception as e:
            self._set_status(status="FAILED", end_time=time.time(),
                             message=f"spawn failed: {e}")
            return "FAILED"
        self._proc = proc
        os.set_blocking(proc.stdout.fileno(), False)
        last_flush = 0.0
        while True:
            chunk = proc.stdout.read(65536)  # None when no data (non-block)
            if chunk:
                buf.extend(chunk)
                if len(buf) > self.LOG_CAP_BYTES:
                    del buf[:len(buf) - self.LOG_CAP_BYTES]
            elif proc.poll() is not None:
                rest = proc.stdout.read()
                if rest:
                    buf.extend(rest)
                break
            else:
                time.sleep(0.05)
            now = time.monotonic()
            if now - last_flush >= self.LOG_FLUSH_PERIOD_S:
                last_flush = now
                self._kv_put(_LOGS_KEY.format(self.job_id), bytes(buf))
        self._kv_put(_LOGS_KEY.format(self.job_id), bytes(buf))
        if self._stop_requested:
            status = "STOPPED"
        else:
            status = "SUCCEEDED" if proc.returncode == 0 else "FAILED"
        self._set_status(status=status, end_time=time.time(),
                         returncode=proc.returncode)
        return status

    def stop(self) -> None:
        """Terminate the entrypoint process group."""
        import os
        import signal

        self._stop_requested = True
        proc = getattr(self, "_proc", None)
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except Exception:
                try:
                    proc.terminate()
                except Exception:
                    pass

    def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """Submit and manage jobs against a running cluster
    (reference: dashboard/modules/job/sdk.py)."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu
        from ray_tpu._private.worker import global_worker_or_none

        self._owns_runtime = global_worker_or_none() is None
        if self._owns_runtime:
            ray_tpu.init(address=address)
        self._address = address

    def close(self) -> None:
        if self._owns_runtime:
            import ray_tpu

            ray_tpu.shutdown()

    # ---- submission --------------------------------------------------------

    def submit_job(self, entrypoint: str, *, submission_id: str = "",
                   working_dir: Optional[str] = None,
                   env_vars: Optional[Dict[str, str]] = None) -> str:
        import ray_tpu
        from ray_tpu.experimental import internal_kv

        job_id = submission_id or f"rtjob-{uuid.uuid4().hex[:10]}"
        w = ray_tpu.api._worker()
        address = f"{w.head_addr[0]}:{w.head_addr[1]}"
        # max_concurrency=2: stop() must get through while run() blocks
        supervisor = ray_tpu.api.ActorClass(
            _JobSupervisor, name=f"_rt_job:{job_id}",
            lifetime="detached", max_concurrency=2).remote(
                job_id, entrypoint, working_dir or "", env_vars or {},
                address)
        self._kv_append_index(job_id)
        internal_kv.kv_put(
            _STATUS_KEY.format(job_id),
            json.dumps({"job_id": job_id, "status": "PENDING",
                        "entrypoint": entrypoint,
                        "submission_time": time.time()}).encode())
        supervisor.run.remote()  # fire and forget; status lands in KV
        return job_id

    def _kv_append_index(self, job_id: str) -> None:
        from ray_tpu.experimental import internal_kv

        raw = internal_kv.kv_get(_INDEX_KEY)
        ids: List[str] = json.loads(raw) if raw else []
        ids.append(job_id)
        internal_kv.kv_put(_INDEX_KEY, json.dumps(ids[-1000:]).encode())

    # ---- queries -----------------------------------------------------------

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        from ray_tpu.experimental import internal_kv

        raw = internal_kv.kv_get(_STATUS_KEY.format(job_id))
        if raw is None:
            raise ValueError(f"no such job: {job_id}")
        return json.loads(raw)

    def get_job_status(self, job_id: str) -> str:
        return self.get_job_info(job_id)["status"]

    def get_job_logs(self, job_id: str) -> str:
        from ray_tpu.experimental import internal_kv

        raw = internal_kv.kv_get(_LOGS_KEY.format(job_id))
        return (raw or b"").decode(errors="replace")

    def list_jobs(self) -> List[Dict[str, Any]]:
        from ray_tpu.experimental import internal_kv

        raw = internal_kv.kv_get(_INDEX_KEY)
        out = []
        for job_id in (json.loads(raw) if raw else []):
            try:
                out.append(self.get_job_info(job_id))
            except ValueError:
                continue
        return out

    def stop_job(self, job_id: str) -> None:
        import ray_tpu

        try:
            sup = ray_tpu.get_actor(f"_rt_job:{job_id}")
            ray_tpu.get(sup.stop.remote(), timeout=30)
        except Exception as e:
            raise ValueError(f"cannot stop {job_id}: {e}") from e

    def wait_until_finish(self, job_id: str, timeout: float = 600.0,
                          poll_s: float = 0.5) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in TERMINAL:
                return status
            time.sleep(poll_s)
        raise TimeoutError(f"job {job_id} still "
                           f"{self.get_job_status(job_id)} after {timeout}s")
