"""Dataset: lazy, distributed transforms over object-store blocks.

Equivalent of the reference's Dataset + execution layer
(reference: python/ray/data/dataset.py — map_batches :371, iter_batches
:3640, materialize :4520; planner/executor under _internal/): a Dataset
is a logical plan; consecutive per-block transforms fuse into one task
per block (reference: rules/operator_fusion.py); iteration streams block
tasks with a bounded in-flight window (streaming_executor.py
backpressure); shuffles are two-phase map/reduce tasks.
"""

from __future__ import annotations

import builtins
import random as _random
import time
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Tuple, Union)

import numpy as np

from ray_tpu.data import logical
from ray_tpu.data.block import BlockAccessor, block_from_numpy, build_block

# streaming backpressure bounds (count clamps around the bytes budget;
# see _StreamBudget)
_WINDOW_MIN = 2
_WINDOW_MAX = 64
_BUDGET_FRACTION = 0.25        # of free store capacity per iteration
_BUDGET_FALLBACK = 64 * 1024 * 1024   # store stats unavailable
_BLOCK_EST_INIT = 1 * 1024 * 1024     # until real block sizes arrive
_OCCUPANCY_REFRESH_S = 0.5


def _store_usage() -> Tuple[int, int]:
    """(allocated, capacity) of the local object store, best-effort.
    Module-level so tests can monkeypatch the probe."""
    import ray_tpu

    usage = ray_tpu.api._worker().agent.call("node_info", timeout=2.0)["store"]
    return int(usage["allocated"]), int(usage["capacity"])


# one process-wide occupancy snapshot, refreshed at most every
# _OCCUPANCY_REFRESH_S: budgets stay per-execution, but the blocking
# node_info RPC behind them is amortized across all live iterators (a
# driver loop calling take(1)/schema() repeatedly must not pay a
# synchronous RPC — up to the 2s timeout against a wedged agent — per
# iteration start).  Failures are cached for the same window.
_usage_snapshot: Tuple[float, Optional[Tuple[int, int]]] = (0.0, None)


def _store_usage_cached() -> Tuple[int, int]:
    global _usage_snapshot
    now = time.monotonic()
    ts, val = _usage_snapshot
    if ts and now - ts < _OCCUPANCY_REFRESH_S:
        if val is None:
            raise RuntimeError("store stats unavailable (cached failure)")
        return val
    try:
        val = _store_usage()
    except Exception:
        _usage_snapshot = (now, None)
        raise
    _usage_snapshot = (now, val)
    return val


class _StreamBudget:
    """Per-EXECUTION streaming backpressure (reference:
    _internal/execution/streaming_executor.py + backpressure_policy/ —
    the reference bounds each execution by a resource budget and pauses
    on object-store pressure).

    Every ``iter_blocks()`` call constructs its own instance, so two
    concurrent iterations each get an independent budget instead of
    sharing one process-global window (the former 2-entry
    ``_stream_window`` cache meant iterator A's refresh dictated
    iterator B's concurrency).  The budget is in BYTES: a quarter of the
    store capacity that was free when the iteration began, spent against
    a running per-block size estimate (EWMA of consumed blocks), with
    [_WINDOW_MIN, _WINDOW_MAX] count clamps so tiny blocks still bound
    task fan-out and huge blocks still make progress.  Store occupancy
    is re-probed every _OCCUPANCY_REFRESH_S per instance; above 80% the
    effective budget halves.
    """

    __slots__ = ("budget_bytes", "inflight", "est_bytes", "_pressure",
                 "_probe_at")

    def __init__(self, budget_bytes: Optional[int] = None):
        if budget_bytes is None:
            try:
                allocated, capacity = _store_usage_cached()
                free = max(0, capacity - allocated)
                budget_bytes = int(free * _BUDGET_FRACTION) \
                    or _BUDGET_FALLBACK
            except Exception:
                budget_bytes = _BUDGET_FALLBACK
        self.budget_bytes = budget_bytes
        self.inflight = 0              # launched - consumed block tasks
        self.est_bytes = float(_BLOCK_EST_INIT)
        self._pressure = False
        self._probe_at = 0.0

    def _effective(self) -> float:
        now = time.monotonic()
        if now >= self._probe_at:
            self._probe_at = now + _OCCUPANCY_REFRESH_S
            try:
                allocated, capacity = _store_usage_cached()
                self._pressure = bool(capacity) \
                    and allocated / capacity > 0.8
            except Exception:
                self._pressure = False
        return self.budget_bytes / 2 if self._pressure else self.budget_bytes

    def admit(self) -> bool:
        """May one more block task launch right now?"""
        if self.inflight < _WINDOW_MIN:
            return True
        if self.inflight >= _WINDOW_MAX:
            return False
        return (self.inflight + 1) * self.est_bytes <= self._effective()

    def launched(self) -> None:
        self.inflight += 1

    def consumed(self, nbytes: int) -> None:
        self.inflight -= 1
        if nbytes > 0:
            self.est_bytes = 0.5 * (self.est_bytes + float(nbytes))


# --------------------------------------------------------------------- ops


class ActorPoolStrategy:
    """Run class-based UDFs on a pool of actors
    (reference: python/ray/data/_internal/compute.py ActorPoolStrategy,
    operators/actor_pool_map_operator.py)."""

    def __init__(self, size: Optional[int] = None,
                 min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        if size is not None:
            min_size = max_size = size
        self.min_size = min_size or 1
        self.max_size = max_size or max(self.min_size, 4)
        if self.min_size < 1 or self.max_size < self.min_size:
            raise ValueError("invalid actor pool bounds")


class _Op:
    """One fusable per-block transform.  For class UDFs (`is_actor`),
    ``fn`` is the class; each pool actor instantiates it once and the
    instance is called per batch."""

    def __init__(self, kind: str, fn: Optional[Callable] = None,
                 batch_size: Optional[int] = None,
                 is_actor: bool = False, ctor_args: tuple = (),
                 ctor_kwargs: Optional[dict] = None,
                 compute: Optional[ActorPoolStrategy] = None):
        self.kind = kind
        self.fn = fn
        self.batch_size = batch_size
        self.is_actor = is_actor
        self.ctor_args = ctor_args
        self.ctor_kwargs = ctor_kwargs or {}
        self.compute = compute


def _apply_ops(block, ops: List[_Op]):
    """Runs inside a worker task: apply a fused chain of ops to a block."""
    import pyarrow as pa

    for op in ops:
        acc = BlockAccessor(block)
        if op.kind == "map_batches":
            batch = acc.to_numpy()
            out = op.fn(batch)
            if isinstance(out, dict):
                block = block_from_numpy(out)
            else:
                block = build_block(list(out))
        elif op.kind == "map":
            block = build_block([op.fn(r) for r in acc.to_rows()])
        elif op.kind == "flat_map":
            rows = []
            for r in acc.to_rows():
                rows.extend(op.fn(r))
            block = build_block(rows)
        elif op.kind == "filter":
            block = build_block([r for r in acc.to_rows() if op.fn(r)])
        else:
            raise ValueError(f"unknown op {op.kind}")
    return block


def _fused_block_task(block, ops: List[_Op]):
    return _apply_ops(block, ops)


class _PoolMapWorker:
    """Actor applying a fused op chain; class UDFs are instantiated once
    per actor (reference: actor_pool_map_operator.py _MapWorker)."""

    def __init__(self, ops: List[_Op]):
        self.ops = []
        for op in ops:
            if op.is_actor:
                inst = op.fn(*op.ctor_args, **op.ctor_kwargs)
                op = _Op(op.kind, inst, op.batch_size)
            self.ops.append(op)

    def apply(self, block):
        return _apply_ops(block, self.ops)


def _shuffle_map(block, n_out: int, seed: int):
    """Phase 1 of a shuffle: split rows into n_out parts."""
    rows = BlockAccessor(block).to_rows()
    rng = _random.Random(seed)
    rng.shuffle(rows)
    parts: List[List[dict]] = [[] for _ in builtins.range(n_out)]
    for i, r in enumerate(rows):
        parts[i % n_out].append(r)
    out = tuple(build_block(p) for p in parts)
    return out if n_out > 1 else out[0]


def _shuffle_reduce(seed: int, *parts):
    rows = []
    for p in parts:
        rows.extend(BlockAccessor(p).to_rows())
    _random.Random(seed).shuffle(rows)
    return build_block(rows)


def _sort_block(block, key: str, descending: bool):
    import pyarrow.compute as pc

    idx = pc.sort_indices(block, sort_keys=[(key, "descending" if descending
                                             else "ascending")])
    return block.take(idx)


def _sort_sample(block, key: str, k: int = 20):
    """A few key values per block — the only sort data the driver sees."""
    col = block.column(key).to_pylist()
    if len(col) <= k:
        return col
    return _random.sample(col, k)


def _sort_map(block, key: str, bounds: List[Any], descending: bool,
              n_out: int):
    """Range-partition one block: sort ascending, cut at the sampled
    boundaries; part j holds keys in [bounds[j-1], bounds[j])."""
    import bisect

    sorted_block = _sort_block(block, key, False)
    col = sorted_block.column(key).to_pylist()
    cuts = [bisect.bisect_left(col, b) for b in bounds] + [len(col)]
    parts, prev = [], 0
    for cut in cuts:
        parts.append(sorted_block.slice(prev, cut - prev))
        prev = cut
    return tuple(parts) if n_out > 1 else parts[0]


def _sort_reduce(key: str, descending: bool, *parts):
    import pyarrow as pa

    tables = [p for p in parts if p.num_rows > 0]
    if not tables:
        return build_block([])
    return _sort_block(pa.concat_tables(tables), key, descending)


def _stable_hash(value) -> int:
    """Deterministic across processes (builtin hash() is seeded per
    interpreter, which would scatter one group over many partitions)."""
    import zlib

    return zlib.crc32(repr(value).encode("utf-8", "backslashreplace"))


def _canon_key(value):
    """Canonical form of a group key BEFORE it is hashed or bucketed.

    Partitioning hashes the key's repr (_stable_hash), but Python
    equality is looser than repr equality: ``2 == 2.0 == True+True`` yet
    their reprs differ, so without canonicalization equal keys land in
    different partitions and the same group emits one aggregate row per
    partition it leaked into.  Numerics therefore normalize (bool → int,
    integral float → int, numpy scalar → Python scalar) so that any two
    keys equal under ``==`` share one canonical repr.

    Supported key types: None, bool, int, float (non-NaN), str, bytes,
    numpy scalars of those, and tuples/lists thereof (canonicalized
    element-wise to a tuple, so ``('a', 2)`` and ``['a', 2.0]`` share a
    group — Arrow stores sequence keys as list columns, so a tuple key
    written into a block reads back as a list and must canonicalize to
    the same value).  Anything else — dicts, NaN (which is not even
    equal to itself), arbitrary objects — is rejected loudly rather
    than silently mis-partitioned.
    """
    if isinstance(value, np.generic):
        value = value.item()
    if isinstance(value, bool):
        return int(value)
    if isinstance(value, float):
        if value != value:
            raise TypeError("NaN groupby keys are unsupported (NaN != NaN: "
                            "no grouping is well-defined)")
        return int(value) if value.is_integer() else value
    if value is None or isinstance(value, (int, str, bytes)):
        return value
    if isinstance(value, (tuple, list)):
        return tuple(_canon_key(v) for v in value)
    raise TypeError(
        f"unsupported groupby key type {type(value).__name__!r}: keys must "
        "be None, bool, int, float, str, bytes, numpy scalars of those, "
        "or tuples/lists thereof")


def _groupby_map(block, key: str, n_out: int):
    """Hash-partition one block's rows by CANONICAL group key.  The key
    column is rewritten to its canonical value: equal-under-== keys now
    share a partition, so without normalization one partition block
    could mix bool and numeric key values, which Arrow refuses to
    type-unify."""
    parts: List[List[dict]] = [[] for _ in builtins.range(n_out)]
    for r in BlockAccessor(block).to_rows():
        k = _canon_key(r[key])
        parts[_stable_hash(k) % n_out].append({**r, key: k})
    out = tuple(build_block(p) for p in parts)
    return out if n_out > 1 else out[0]


def _group_rows(key: str, parts) -> Dict[Any, List[dict]]:
    """Bucket rows by canonical key; the canonical value is also what
    the output row carries (2.0 and 2 grouped together report key 2)."""
    groups: Dict[Any, List[dict]] = {}
    for p in parts:
        for r in BlockAccessor(p).to_rows():
            groups.setdefault(_canon_key(r[key]), []).append(r)
    return groups


def _groupby_agg(key: str, specs: List[Tuple[str, Optional[str]]], *parts):
    """Per-partition aggregation: every row of a group is local here
    (hash partitioning), so each agg is a plain in-memory fold."""
    out_rows = []
    groups = _group_rows(key, parts)
    for k in sorted(groups.keys(), key=repr):
        rows = groups[k]
        row = {key: k}
        for kind, on in specs:
            if kind == "count":
                row["count()"] = len(rows)
                continue
            vals = np.asarray([r[on] for r in rows], dtype=np.float64)
            if kind == "sum":
                row[f"sum({on})"] = float(vals.sum())
            elif kind == "min":
                row[f"min({on})"] = float(vals.min())
            elif kind == "max":
                row[f"max({on})"] = float(vals.max())
            elif kind == "mean":
                row[f"mean({on})"] = float(vals.mean())
            elif kind == "std":
                row[f"std({on})"] = float(vals.std(ddof=1)) \
                    if len(vals) > 1 else 0.0
            else:
                raise ValueError(f"unknown aggregate {kind!r}")
        out_rows.append(row)
    return build_block(out_rows)


def _groupby_apply(key: str, fn, *parts):
    """map_groups: the UDF sees all rows of one group, returns rows."""
    out_rows = []
    groups = _group_rows(key, parts)
    for k in sorted(groups.keys(), key=repr):
        out_rows.extend(fn(groups[k]))
    return build_block(out_rows)


def _read_file_task(path: str, fmt: str):
    import pyarrow as pa

    if fmt == "parquet":
        import pyarrow.parquet as pq

        return pq.read_table(path)
    if fmt == "csv":
        from pyarrow import csv as pcsv

        return pcsv.read_csv(path)
    if fmt == "json":
        from pyarrow import json as pjson

        return pjson.read_json(path)
    raise ValueError(fmt)


def _write_parquet_task(block, path: str):
    import pyarrow.parquet as pq

    pq.write_table(block, path)
    return path


# ----------------------------------------------------------------- dataset


class Dataset:
    def __init__(self, block_refs: List[Any],
                 plan: Optional[List["logical.LogicalOp"]] = None):
        self._block_refs = block_refs   # source blocks (ObjectRefs)
        # the LOGICAL plan: transforms append LogicalOp nodes; the
        # executor consumes the rewritten (rule-optimized) plan — see
        # data/logical.py (reference: _internal/logical/ + planner/)
        self._logical: List[logical.LogicalOp] = plan or []
        self._ops_cache: Optional[List[_Op]] = None
        self._phys_cache: Optional[Tuple[List[_Op], List[Tuple[int, List[_Op]]]]] = None
        self._materialized: Optional[List[Any]] = None
        self._last_stats: Dict[str, Any] = {}

    # ---- plan building ----

    @property
    def _ops(self) -> List[_Op]:
        """Physical fused op chain, derived by running the rewrite rules
        over the logical plan (FuseMapOperators collapses the map-likes
        into one task-per-block chain).  Cached: the plan is immutable
        after construction (_chain builds a NEW Dataset)."""
        if self._ops_cache is None:
            pre, segments = self._phys_plan()
            ops = list(pre)
            for _, seg_ops in segments:
                ops.extend(seg_ops)
            self._ops_cache = ops
        return self._ops_cache

    def _phys_plan(self) -> Tuple[List[_Op], List[Tuple[int, List[_Op]]]]:
        """Physical plan split at limit nodes: (ops before the first
        limit — run distributed, one task per launched block — and
        [(limit, trailing ops), ...] segments applied driver-side to
        the ≤limit rows that survive).  Non-empty segments switch
        iter_blocks to the early-stopping executor that never launches
        block tasks past the limit."""
        if self._phys_cache is None:
            pre: List[_Op] = []
            segments: List[Tuple[int, List[_Op]]] = []
            cur = pre
            for node in logical.optimize(self._logical):
                if node.name == "fused_map":
                    cur.extend(node.payload)
                elif node.name == "limit":
                    segments.append((int(node.payload), []))
                    cur = segments[-1][1]
                else:
                    # fail loudly: a plan node the executor doesn't know
                    # must never silently vanish from execution
                    raise ValueError(
                        f"no physical execution for logical op "
                        f"{node.name!r}")
            self._phys_cache = (pre, segments)
        return self._phys_cache

    def _chain(self, op: _Op) -> "Dataset":
        return Dataset(self._block_refs,
                       self._logical + [logical.LogicalOp(op.kind, op)])

    def map_batches(self, fn: Callable[[Dict[str, np.ndarray]], Any],
                    batch_size: Optional[int] = None,
                    compute: Optional[ActorPoolStrategy] = None,
                    concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None) -> "Dataset":
        """Per-batch transform.  A class `fn` runs on an actor pool: each
        actor constructs one instance (amortizing model loads) and calls
        it per batch (reference: dataset.py map_batches :371 +
        actor_pool_map_operator.py)."""
        is_cls = isinstance(fn, type)
        if is_cls and compute is None:
            compute = ActorPoolStrategy(size=concurrency) if concurrency \
                else ActorPoolStrategy()
        if not is_cls and (compute or fn_constructor_args
                           or fn_constructor_kwargs):
            raise ValueError("compute/fn_constructor_* require a class UDF")
        return self._chain(_Op(
            "map_batches", fn, batch_size, is_actor=is_cls,
            ctor_args=tuple(fn_constructor_args),
            ctor_kwargs=fn_constructor_kwargs, compute=compute))

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        return self._chain(_Op("map", fn))

    def flat_map(self, fn: Callable[[dict], Iterable[dict]]) -> "Dataset":
        return self._chain(_Op("flat_map", fn))

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        return self._chain(_Op("filter", fn))

    # ---- execution ----

    def _make_budget(self) -> _StreamBudget:
        """One fresh backpressure budget per iteration (test seam)."""
        return _StreamBudget()

    def _submit_block(self, ref) -> Any:
        """Launch the fused op chain on one source block; returns a ref."""
        if not self._ops:
            return ref
        fn = _remote_fused()
        return fn.remote(ref, self._ops)

    def _has_actor_op(self) -> bool:
        return any(op.is_actor for op in self._ops)

    def _make_pool(self, ops: Optional[List[_Op]] = None) -> List[Any]:
        """Actors for the chain's class UDFs, sized to the workload
        within the strategy's [min_size, max_size]."""
        import ray_tpu

        ops = self._ops if ops is None else ops
        compute = next((op.compute for op in ops
                        if op.is_actor and op.compute), None) \
            or ActorPoolStrategy()
        n = min(compute.max_size,
                max(compute.min_size, len(self._block_refs)))
        cls = ray_tpu.remote(_PoolMapWorker)
        return [cls.remote(ops) for _ in builtins.range(n)]

    def _execute(self) -> List[Any]:
        if self._materialized is None:
            if self._phys_plan()[1]:
                # a limit in the plan: the early-stopping iterator
                # bounds what reaches the driver to ≤limit rows, which
                # then re-enter the store as fresh blocks
                import ray_tpu

                self._materialized = [ray_tpu.put(b)
                                      for b in self.iter_blocks()]
            elif self._has_actor_op():
                import weakref

                actors = self._make_pool()
                refs = [actors[i % len(actors)].apply.remote(r)
                        for i, r in enumerate(self._block_refs)]
                # the pool must outlive its in-flight results
                for ref in refs:
                    weakref.finalize(ref, lambda _h: None, tuple(actors))
                self._materialized = refs
            else:
                self._materialized = [self._submit_block(r)
                                      for r in self._block_refs]
        return self._materialized

    def materialize(self) -> "Dataset":
        import ray_tpu

        refs = self._execute()
        ray_tpu.wait(refs, num_returns=len(refs), timeout=None)
        return Dataset(refs)

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def explain(self) -> str:
        """Human-readable plan: the logical op list, then the
        rule-rewritten plan the executor runs (reference: the planner's
        plan dump, _internal/planner/planner.py)."""
        lines = [f"Source[{len(self._block_refs)} blocks]"]
        if self._logical:
            lines.append("  logical:   " + logical.describe(self._logical))

        def _label(op: _Op) -> str:
            label = op.kind
            if op.is_actor:
                compute = op.compute or ActorPoolStrategy()
                label += (f"(actor_pool[{compute.min_size}"
                          f"..{compute.max_size}], "
                          f"{getattr(op.fn, '__name__', 'cls')})")
            else:
                label += f"({getattr(op.fn, '__name__', 'fn')})"
            return label

        # mirror the executor's split: distributed ops, then each limit
        # with its driver-side residual — a Limit in the plan must show
        # up here, not silently fold into the fused chain
        pre, segments = self._phys_plan()
        parts: List[str] = []
        if pre:
            parts.append("Fused[" + " | ".join(_label(o) for o in pre) + "]"
                         + (" on actor pool"
                            if any(o.is_actor for o in pre)
                            else " per-block task"))
        for n, seg in segments:
            parts.append(f"Limit[{n}]")
            if seg:
                parts.append("Fused[" + " | ".join(_label(o) for o in seg)
                             + "] driver-side")
        if parts:
            lines.append("  optimized: " + " -> ".join(parts))
        return "\n".join(lines)

    def stats(self) -> Dict[str, Any]:
        """Execution stats from the last iteration/materialization."""
        return dict(self._last_stats)

    # ---- consumption ----

    def iter_blocks(self) -> Iterator[Any]:
        """Stream result blocks under a per-execution bytes budget
        derived from object-store occupancy (reference: streaming
        executor backpressure).  Each call gets its OWN _StreamBudget —
        concurrent iterations never share a window."""
        import time as _time

        import ray_tpu

        t0 = _time.perf_counter()
        stats = {"blocks": 0, "rows": 0, "bytes": 0}

        def tally(block):
            stats["blocks"] += 1
            acc = BlockAccessor(block)
            stats["rows"] += acc.num_rows()
            stats["bytes"] += getattr(block, "nbytes", 0)
            return block

        def finish():
            stats["wall_s"] = round(_time.perf_counter() - t0, 4)
            self._last_stats = stats

        # try/finally: early-stopping consumers (take, schema) close the
        # generator mid-stream — partial stats still finalize
        try:
            if self._materialized is not None:
                for ref in self._materialized:
                    yield tally(ray_tpu.get(ref, timeout=600))
                return
            pending = list(self._block_refs)
            pre_ops, segments = self._phys_plan()
            if segments:
                yield from self._iter_blocks_limited(
                    pending, tally, pre_ops, segments)
                return
            in_flight: List[Any] = []
            if self._has_actor_op():
                actors = self._make_pool()
                rr = 0
                while pending or in_flight:
                    # ≤2 queued per actor keeps the pool busy without
                    # flooding any single replica's mailbox
                    while pending and len(in_flight) < 2 * len(actors):
                        in_flight.append(
                            actors[rr % len(actors)].apply.remote(
                                pending.pop(0)))
                        rr += 1
                    yield tally(ray_tpu.get(in_flight.pop(0), timeout=600))
                return
            budget = self._make_budget()
            if self._ops and len(pending) >= 4:
                # enough work to amortize shard tasks: the generator-based
                # executor replaces per-block task submission
                yield from self._iter_blocks_stream_shards(
                    pending, tally, budget)
                return
            while pending or in_flight:
                while pending and budget.admit():
                    in_flight.append(self._submit_block(pending.pop(0)))
                    budget.launched()
                ref = in_flight.pop(0)
                block = ray_tpu.get(ref, timeout=600)
                budget.consumed(getattr(block, "nbytes", 0))
                yield tally(block)
        finally:
            finish()

    def _iter_blocks_stream_shards(self, refs: List[Any], tally,
                                   budget: _StreamBudget):
        """Task-path executor rebuilt on streaming generators: shard
        tasks each pull their source blocks and YIELD each transformed
        block as it is produced, so consumption overlaps production
        without a driver-side in-flight window (reference: the streaming
        executor consuming generator outputs —
        data/_internal/execution/streaming_executor.py + the
        generator-backed MapOperator).

        A launched shard's unconsumed yields buffer owner-side, so the
        per-execution budget governs both the CHUNK size (about half the
        blocks the budget covers, so lookahead has byte-granularity) and
        whether a lookahead shard may launch at all; the whole chunk is
        charged at launch and credited back block-by-block as
        consumption drains it.  Streaming tasks are not auto-retried; a
        shard that dies mid-stream is resubmitted here for only its
        unconsumed suffix."""
        import ray_tpu

        budget_blocks = max(1, int(budget.budget_bytes
                                   // max(budget.est_bytes, 1.0)))
        size = max(1, min((len(refs) + 3) // 4,  # ≥4 shards when possible
                          max(1, budget_blocks // 2)))
        chunks = [refs[i:i + size]
                  for i in builtins.range(0, len(refs), size)]
        fn = _remote_fused_stream()
        gens: List[Any] = [None] * len(chunks)

        def launch(i, force=False):
            if i < len(chunks) and gens[i] is None \
                    and (force or budget.admit()):
                gens[i] = fn.remote(chunks[i], self._ops)
                for _ in chunks[i]:
                    budget.launched()

        launch(0, force=True)  # progress even when budget < one chunk
        launch(1)
        for ci, chunk in enumerate(chunks):
            consumed = 0
            attempts = 3
            if gens[ci] is None:
                launch(ci, force=True)
            gen = gens[ci]
            while consumed < len(chunk):
                try:
                    ref = gen.next_ref(timeout=600)
                except StopIteration:
                    raise RuntimeError(
                        f"shard stream ended after {consumed}/{len(chunk)} "
                        "blocks — op chain yielded short")
                except ray_tpu.RayWorkerError:
                    # worker died mid-stream: streaming tasks don't
                    # auto-retry, so resubmit the unconsumed suffix
                    attempts -= 1
                    if attempts <= 0:
                        raise
                    gen = fn.remote(chunk[consumed:], self._ops)
                    continue
                # deterministic op errors (RayTaskError) propagate —
                # re-running the chain would just fail again
                block = ray_tpu.get(ref, timeout=600)
                budget.consumed(getattr(block, "nbytes", 0))
                yield tally(block)
                consumed += 1
                launch(ci + 1)
                launch(ci + 2)

    def _iter_blocks_limited(self, refs: List[Any], tally,
                             pre_ops: List[_Op],
                             segments: List[Tuple[int, List[_Op]]]):
        """Early-stopping executor for plans with a limit: launch block
        tasks with a 2-deep lookahead and STOP launching once the first
        limit's rows have been produced — source blocks past the limit
        never become tasks (the limit-pushdown satellite).  The residual
        segments (ops/limits after the first limit) apply driver-side to
        the ≤limit surviving rows.  Class-UDF chains fall back to the
        actor pool for the pre-limit ops, still consumed with the same
        early stop."""
        import ray_tpu

        n1 = segments[0][0]
        counters = [0] * len(segments)
        produced = 0
        # post-limit class UDFs run driver-side on the capped rows:
        # instantiate them once here (the pool path would apply them
        # remotely BEFORE the cap)
        segments = [(lim, [_Op(op.kind,
                               op.fn(*op.ctor_args, **op.ctor_kwargs),
                               op.batch_size) if op.is_actor else op
                           for op in ops])
                    for lim, ops in segments]
        use_actors = any(op.is_actor for op in pre_ops)
        fn = _remote_fused() if pre_ops and not use_actors else None
        actors = self._make_pool(pre_ops) if use_actors else None
        in_flight: List[Any] = []
        idx = 0
        lookahead = 2 if not use_actors else 2 * len(actors)
        while produced < n1 and (idx < len(refs) or in_flight):
            while idx < len(refs) and len(in_flight) < lookahead:
                ref = refs[idx]
                if use_actors:
                    ref = actors[idx % len(actors)].apply.remote(ref)
                elif fn is not None:
                    ref = fn.remote(ref, pre_ops)
                in_flight.append(ref)
                idx += 1
            block = ray_tpu.get(in_flight.pop(0), timeout=600)
            acc = BlockAccessor(block)
            take_rows = min(acc.num_rows(), n1 - produced)
            produced += take_rows
            if take_rows <= 0:
                continue
            if take_rows < acc.num_rows():
                block = acc.slice(0, take_rows)
            block = self._apply_limit_suffix(block, segments, counters)
            if BlockAccessor(block).num_rows() > 0:
                yield tally(block)
            if len(segments) > 1 and all(
                    counters[i] >= segments[i][0]
                    for i in builtins.range(1, len(segments))):
                # every TRAILING limit is already full: rows still due
                # under the first (larger) limit can only come out as
                # empty blocks — stop launching/fetching now
                break

    @staticmethod
    def _apply_limit_suffix(block, segments, counters):
        """Ops after the first limit (and any further limits) run
        driver-side: every row here already survived the first cap, so
        the work is bounded by it."""
        if segments[0][1]:
            block = _apply_ops(block, segments[0][1])
        for i in builtins.range(1, len(segments)):
            lim, ops = segments[i]
            acc = BlockAccessor(block)
            take_rows = min(acc.num_rows(), lim - counters[i])
            if take_rows < acc.num_rows():
                block = acc.slice(0, take_rows)
            counters[i] += take_rows
            if ops and BlockAccessor(block).num_rows() > 0:
                block = _apply_ops(block, ops)
        return block

    def iter_rows(self) -> Iterator[dict]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).to_rows()

    def iter_batches(self, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        carry = None
        for block in self.iter_blocks():
            if carry is not None and carry.num_rows > 0:
                block = BlockAccessor.concat([carry, block])
            acc = BlockAccessor(block)
            n = acc.num_rows()
            start = 0
            while n - start >= batch_size:
                yield self._format(acc.slice(start, start + batch_size),
                                   batch_format)
                start += batch_size
            carry = acc.slice(start, n)
        if carry is not None and BlockAccessor(carry).num_rows() > 0 \
                and not drop_last:
            yield self._format(carry, batch_format)

    @staticmethod
    def _format(block, batch_format: str):
        acc = BlockAccessor(block)
        if batch_format == "numpy":
            return acc.to_numpy()
        if batch_format == "pandas":
            return acc.to_pandas()
        if batch_format == "pyarrow":
            return acc.block
        raise ValueError(batch_format)

    def take(self, n: int = 20) -> List[dict]:
        """First n rows.  Routed through limit(n), so the executor stops
        launching block tasks once n rows exist instead of streaming the
        whole dataset at the driver."""
        out: List[dict] = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[dict]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(BlockAccessor(b).num_rows() for b in self.iter_blocks())

    def schema(self):
        for block in self.iter_blocks():
            if BlockAccessor(block).num_rows() > 0:
                return BlockAccessor(block).schema()
        return None

    def sum(self, on: str) -> float:
        return builtins.sum(
            float(np.sum(BlockAccessor(b).to_numpy()[on]))
            for b in self.iter_blocks())

    def min(self, on: str) -> float:
        return builtins.min(float(np.min(BlockAccessor(b).to_numpy()[on]))
                            for b in self.iter_blocks()
                            if BlockAccessor(b).num_rows())

    def max(self, on: str) -> float:
        return builtins.max(float(np.max(BlockAccessor(b).to_numpy()[on]))
                            for b in self.iter_blocks()
                            if BlockAccessor(b).num_rows())

    def mean(self, on: str) -> float:
        total, count = 0.0, 0
        for b in self.iter_blocks():
            arr = BlockAccessor(b).to_numpy()[on]
            total += float(np.sum(arr))
            count += len(arr)
        return total / max(count, 1)

    # ---- exchange ops (materializing) ----

    def repartition(self, num_blocks: int) -> "Dataset":
        """Coalesce/split into `num_blocks` blocks of even row counts."""
        import ray_tpu

        rows = self.take_all()
        per = max(1, (len(rows) + num_blocks - 1) // num_blocks)
        blocks = []
        for i in builtins.range(num_blocks):
            chunk = rows[i * per:(i + 1) * per]
            blocks.append(ray_tpu.put(build_block(chunk)))
        return Dataset(blocks)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Two-phase distributed shuffle (map splits, reduce merges)."""
        import ray_tpu

        seed = seed if seed is not None else _random.randint(0, 1 << 30)
        refs = self._execute()
        n = len(refs)
        if n == 0:
            return Dataset([])
        mapper = _remote_shuffle_map(n)
        parts = [mapper.remote(ref, n, seed + i) for i, ref in enumerate(refs)]
        if n == 1:
            parts = [[p] for p in parts]
        reducer = _remote_shuffle_reduce()
        out = [reducer.remote(seed + 1000 + j, *[parts[i][j]
                                                 for i in builtins.range(n)])
               for j in builtins.range(n)]
        return Dataset(out)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Global sort via sample-based range partitioning: sample keys
        from every block (only the SAMPLES touch the driver), cut N-1
        boundaries, range-partition each block in a map task, merge each
        range in a reduce task.  Block i of the result holds range i, so
        concatenation order IS global order — no driver-side row merge
        (reference: _internal/planner/exchange/sort_task_spec.py
        SortTaskSpec.sample_boundaries + push_based_shuffle)."""
        import ray_tpu

        refs = self._execute()
        n = len(refs)
        if n == 0:
            return Dataset([])
        if n == 1:
            sorter = _remote_sort_block()
            return Dataset([sorter.remote(refs[0], key, descending)])
        sampler = _remote_sort_sample()
        samples = ray_tpu.get(
            [sampler.remote(r, key) for r in refs], timeout=600)
        merged = sorted(v for s in samples for v in s)
        if not merged:
            return Dataset(refs)
        # n-1 equi-spaced boundaries over the sampled key distribution
        bounds = [merged[(i * len(merged)) // n]
                  for i in builtins.range(1, n)]
        mapper = _remote_sort_map(n)
        parts = [mapper.remote(r, key, bounds, descending, n) for r in refs]
        reducer = _remote_sort_reduce()
        out = [reducer.remote(key, descending,
                              *[parts[i][j] for i in builtins.range(n)])
               for j in builtins.range(n)]
        if descending:
            out.reverse()
        return Dataset(out)

    def groupby(self, key: str) -> "GroupedData":
        """Group rows by a column for per-group aggregation
        (reference: python/ray/data/grouped_data.py:36 GroupedData)."""
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._execute())
        for o in others:
            refs.extend(o._execute())
        return Dataset(refs)

    def limit(self, n: int) -> "Dataset":
        """Lazy row cap: appends a ``limit`` node to the logical plan.
        The LimitPushdown rule merges/hops it and the executor stops
        launching block tasks once n rows are produced — no full
        materialization on the driver (the former behavior)."""
        if self._materialized is not None:
            # plan already ran: cap the materialized blocks directly
            # rather than re-launching the op chain over the sources
            return Dataset(self._materialized,
                           [logical.LogicalOp("limit", int(n))])
        return Dataset(self._block_refs,
                       self._logical + [logical.LogicalOp("limit", int(n))])

    # ---- splitting (train ingest) ----

    def split(self, n: int) -> List["Dataset"]:
        """Round-robin block split (reference: Dataset.split for ingest)."""
        refs = self._execute()
        shards: List[List[Any]] = [[] for _ in builtins.range(n)]
        for i, ref in enumerate(refs):
            shards[i % n].append(ref)
        return [Dataset(s) for s in shards]

    def write_parquet(self, dir_path: str) -> List[str]:
        import os

        import ray_tpu

        os.makedirs(dir_path, exist_ok=True)
        writer = _remote_writer()
        refs = [writer.remote(ref, os.path.join(dir_path, f"part-{i:05d}.parquet"))
                for i, ref in enumerate(self._execute())]
        return ray_tpu.get(refs, timeout=600)

    def __repr__(self):
        return (f"Dataset(num_blocks={len(self._block_refs)}, "
                f"pending_ops={len(self._ops)})")


class GroupedData:
    """Distributed group-by: rows hash-partition by key in map tasks, so
    each reduce task holds every row of its groups and aggregates (or
    applies a UDF) locally — no group's rows ever gather on the driver
    (reference: python/ray/data/grouped_data.py:36; the hash exchange in
    _internal/planner/exchange/).

    Aggregates: count(), sum/min/max/mean/std(on), multi-agg via
    aggregate(("sum", "x"), ("max", "y")); per-group UDFs via
    map_groups(fn) where fn(list-of-rows) -> list-of-rows.
    """

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _partitions(self) -> List[List[Any]]:
        """Hash-exchange the dataset: returns per-partition part lists
        (partition j = parts[i][j] over every input block i)."""
        refs = self._ds._execute()
        n = len(refs)
        if n == 0:
            return []
        mapper = _remote_groupby_map(n)
        parts = [mapper.remote(r, self._key, n) for r in refs]
        if n == 1:
            parts = [[p] for p in parts]
        return [[parts[i][j] for i in builtins.range(n)]
                for j in builtins.range(n)]

    def aggregate(self, *specs: Tuple[str, Optional[str]]) -> Dataset:
        """Each spec is ("count", None) or ("sum"|"min"|"max"|"mean"|
        "std", column); output has one row per group with columns like
        "sum(x)" (reference: AggregateFn result naming)."""
        if not specs:
            raise ValueError("aggregate() needs at least one spec")
        agg = _remote_groupby_agg()
        out = [agg.remote(self._key, list(specs), *plist)
               for plist in self._partitions()]
        return Dataset(out)

    def count(self) -> Dataset:
        return self.aggregate(("count", None))

    def sum(self, on: str) -> Dataset:
        return self.aggregate(("sum", on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(("min", on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(("max", on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(("mean", on))

    def std(self, on: str) -> Dataset:
        return self.aggregate(("std", on))

    def map_groups(self, fn: Callable[[List[dict]], List[dict]]) -> Dataset:
        apply = _remote_groupby_apply()
        out = [apply.remote(self._key, fn, *plist)
               for plist in self._partitions()]
        return Dataset(out)


# -------------------------------------------------- remote fn construction

_remote_cache: Dict[str, Any] = {}


def _fused_stream_task(refs, ops):
    """Shard executor body: fetch each source block, run the fused op
    chain, and yield the result — one streamed item per block."""
    import ray_tpu

    for r in refs:
        yield _apply_ops(ray_tpu.get(r), ops)


def _remote_fused():
    return _remote_simple("fused", _fused_block_task)


def _remote_fused_stream():
    key = "fused_stream"
    fn = _remote_cache.get(key)
    if fn is None:
        import ray_tpu

        fn = _remote_cache[key] = ray_tpu.remote(
            num_returns="streaming")(_fused_stream_task)
    return fn


def _remote_simple(name: str, fn):
    key = f"simple:{name}"
    cached = _remote_cache.get(key)
    if cached is None:
        import ray_tpu

        cached = _remote_cache[key] = ray_tpu.remote(fn)
    return cached


def _remote_multi(name: str, fn, n_out: int):
    key = f"multi:{name}:{n_out}"
    cached = _remote_cache.get(key)
    if cached is None:
        import ray_tpu

        cached = _remote_cache[key] = ray_tpu.remote(num_returns=n_out)(fn)
    return cached


def _remote_sort_block():
    return _remote_simple("sort_block", _sort_block)


def _remote_sort_sample():
    return _remote_simple("sort_sample", _sort_sample)


def _remote_sort_map(n_out: int):
    return _remote_multi("sort_map", _sort_map, n_out)


def _remote_sort_reduce():
    return _remote_simple("sort_reduce", _sort_reduce)


def _remote_groupby_map(n_out: int):
    return _remote_multi("groupby_map", _groupby_map, n_out)


def _remote_groupby_agg():
    return _remote_simple("groupby_agg", _groupby_agg)


def _remote_groupby_apply():
    return _remote_simple("groupby_apply", _groupby_apply)


def _remote_shuffle_map(n_out: int):
    return _remote_multi("shuffle_map", _shuffle_map, n_out)


def _remote_shuffle_reduce():
    return _remote_simple("shuffle_reduce", _shuffle_reduce)


def _remote_writer():
    return _remote_simple("writer", _write_parquet_task)


def _remote_reader():
    return _remote_simple("reader", _read_file_task)


# ------------------------------------------------------------ constructors


def from_items(items: List[Any], num_blocks: int = 8) -> Dataset:
    import ray_tpu

    rows = [it if isinstance(it, dict) else {"item": it} for it in items]
    num_blocks = max(1, min(num_blocks, len(rows) or 1))
    per = (len(rows) + num_blocks - 1) // num_blocks
    refs = [ray_tpu.put(build_block(rows[i * per:(i + 1) * per]))
            for i in builtins.range(num_blocks)]
    return Dataset(refs)


def range(n: int, num_blocks: int = 8) -> Dataset:
    import ray_tpu

    num_blocks = max(1, min(num_blocks, n or 1))
    per = (n + num_blocks - 1) // num_blocks
    refs = []
    for i in builtins.range(num_blocks):
        lo, hi = i * per, min((i + 1) * per, n)
        refs.append(ray_tpu.put(block_from_numpy(
            {"id": np.arange(lo, hi, dtype=np.int64)})))
    return Dataset(refs)


def from_numpy(arrays: Dict[str, np.ndarray], num_blocks: int = 8) -> Dataset:
    import ray_tpu

    n = len(next(iter(arrays.values())))
    num_blocks = max(1, min(num_blocks, n or 1))
    per = (n + num_blocks - 1) // num_blocks
    refs = []
    for i in builtins.range(num_blocks):
        chunk = {k: np.asarray(v)[i * per:(i + 1) * per]
                 for k, v in arrays.items()}
        refs.append(ray_tpu.put(block_from_numpy(chunk)))
    return Dataset(refs)


def _read_files(paths: Union[str, List[str]], fmt: str) -> Dataset:
    import glob as globmod
    import os

    if isinstance(paths, str):
        if os.path.isdir(paths):
            files = sorted(globmod.glob(os.path.join(paths, "*")))
        else:
            files = sorted(globmod.glob(paths)) or [paths]
    else:
        files = list(paths)
    reader = _remote_reader()
    return Dataset([reader.remote(f, fmt) for f in files])


def read_parquet(paths: Union[str, List[str]]) -> Dataset:
    return _read_files(paths, "parquet")


def read_csv(paths: Union[str, List[str]]) -> Dataset:
    return _read_files(paths, "csv")


def read_json(paths: Union[str, List[str]]) -> Dataset:
    return _read_files(paths, "json")
