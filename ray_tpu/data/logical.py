"""Logical plan + rewrite rules for ray_tpu.data.

Small re-imagining of the reference's logical/physical planner split
(reference: python/ray/data/_internal/logical/interfaces.py LogicalPlan
+ Rule; rules/operator_fusion.py OperatorFusionRule;
planner/planner.py): a Dataset accumulates LogicalOp nodes; before
execution the plan runs through an ordered list of rewrite rules, and
the physical executor consumes the rewritten plan.  Today's rules:

  * LimitPushdown — adjacent ``limit`` nodes merge (min wins) and a
    limit hops ahead of row-count-preserving per-row maps, so the
    executor stops launching block tasks at the limit instead of
    transforming rows it will drop (reference:
    rules/limit_pushdown.py).

  * FuseMapOperators — adjacent per-row/per-batch transforms collapse
    into one ``fused_map`` node executed as a single task (or actor
    call) per block, the fusion the reference expresses in
    operator_fusion.py.

The rule list is the extension seam: later rules (predicate pushdown,
exchange planning) append here without touching the Dataset surface.
The executor fails loudly on plan nodes it has no physical translation
for, so a new rule cannot silently drop work.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, List, Optional

# map-like ops are fusable: one task per block applies the whole chain
MAP_LIKE = ("map", "map_batches", "flat_map", "filter")


class LogicalOp:
    """One node of the (linear, for now) logical plan."""

    __slots__ = ("name", "payload")

    def __init__(self, name: str, payload: Any = None):
        self.name = name
        self.payload = payload  # _Op for map-likes; op params otherwise

    def describe(self) -> str:
        if self.name == "fused_map":
            inner = ", ".join(getattr(o.fn, "__name__", o.kind)
                              for o in self.payload)
            return f"FusedMap[{inner}]"
        if self.name == "limit":
            return f"Limit[{self.payload}]"
        if self.payload is not None and hasattr(self.payload, "kind"):
            fn = getattr(self.payload.fn, "__name__", "fn")
            return f"{self.name.title()}({fn})"
        return self.name.title()


class Rule(ABC):
    """A plan-to-plan rewrite (reference: logical/interfaces.py Rule)."""

    name: str = "rule"

    @abstractmethod
    def apply(self, ops: List[LogicalOp]) -> List[LogicalOp]:
        ...


class FuseMapOperators(Rule):
    """Collapse adjacent map-like ops into one fused_map node so the
    executor runs the whole chain as a single task per block
    (reference: rules/operator_fusion.py)."""

    name = "fuse_map_operators"

    def apply(self, ops: List[LogicalOp]) -> List[LogicalOp]:
        out: List[LogicalOp] = []
        for op in ops:
            if op.name in MAP_LIKE:
                if out and out[-1].name == "fused_map":
                    out[-1].payload.append(op.payload)
                else:
                    out.append(LogicalOp("fused_map", [op.payload]))
            else:
                out.append(op)
        return out


class LimitPushdown(Rule):
    """Merge adjacent ``limit`` nodes (min wins) and push a limit ahead
    of a preceding per-row ``map`` — maps are 1:1 and order-preserving,
    so limiting first is equivalent and spares transforming rows the
    limit would drop.  Non-row-preserving ops (filter, flat_map,
    map_batches) block the hop.  Runs before fusion so the map-likes
    left adjacent after the hop still fuse."""

    name = "limit_pushdown"

    def apply(self, ops: List[LogicalOp]) -> List[LogicalOp]:
        out = list(ops)
        changed = True
        while changed:
            changed = False
            i = 0
            while i < len(out):
                node = out[i]
                if node.name != "limit" or i == 0:
                    i += 1
                    continue
                prev = out[i - 1]
                if prev.name == "limit":
                    out[i - 1] = LogicalOp(
                        "limit", min(prev.payload, node.payload))
                    del out[i]
                    changed = True
                elif prev.name == "map":
                    out[i - 1], out[i] = node, prev
                    changed = True
                    i += 1
                else:
                    i += 1
        return out


DEFAULT_RULES: List[Rule] = [LimitPushdown(), FuseMapOperators()]


def optimize(ops: List[LogicalOp],
             rules: Optional[List[Rule]] = None) -> List[LogicalOp]:
    for rule in (rules if rules is not None else DEFAULT_RULES):
        ops = rule.apply(ops)
    return ops


def describe(ops: List[LogicalOp]) -> str:
    return " -> ".join(op.describe() for op in ops) or "(empty)"
