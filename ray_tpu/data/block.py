"""Block representation and accessors.

Equivalent of the reference's block layer
(reference: python/ray/data/_internal/arrow_block.py, block.py):
a block is a pyarrow Table; the accessor converts to/from rows, numpy
batches, and pandas.  Arrow's buffer layout serializes into the
shared-memory store with the pickle5 out-of-band path, so cross-process
block handoff is zero-copy on read.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional

import numpy as np
import pyarrow as pa


def build_block(rows: List[Dict[str, Any]]) -> pa.Table:
    if not rows:
        return pa.table({})
    return pa.Table.from_pylist(rows)


def block_from_numpy(arrays: Dict[str, np.ndarray]) -> pa.Table:
    import json

    cols = {}
    fields = []
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        if arr.ndim == 1:
            a = pa.array(arr)
            cols[name] = a
            fields.append(pa.field(name, a.type))
        else:
            # tensor columns: fixed-size lists keep the buffer contiguous;
            # the element shape rides in field metadata so to_numpy can
            # restore rank>2 tensors (reference: ArrowTensorArray)
            width = max(int(np.prod(arr.shape[1:])), 1)
            flat = arr.reshape(arr.shape[0], width)
            a = pa.FixedSizeListArray.from_arrays(pa.array(flat.ravel()), width)
            cols[name] = a
            fields.append(pa.field(
                name, a.type,
                metadata={b"tensor_shape": json.dumps(arr.shape[1:]).encode()}))
    return pa.table(cols, schema=pa.schema(fields))


class BlockAccessor:
    def __init__(self, block: pa.Table):
        self.block = block

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def to_rows(self) -> List[Dict[str, Any]]:
        return self.block.to_pylist()

    def to_numpy(self) -> Dict[str, np.ndarray]:
        import json

        out = {}
        for i, name in enumerate(self.block.column_names):
            col = self.block.column(name)
            if pa.types.is_fixed_size_list(col.type):
                combined = col.combine_chunks()
                width = col.type.list_size
                arr = np.asarray(combined.values).reshape(-1, width)
                meta = self.block.schema.field(i).metadata or {}
                shape = meta.get(b"tensor_shape")
                if shape is not None:
                    arr = arr.reshape(-1, *json.loads(shape))
                out[name] = arr
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def to_pandas(self):
        return self.block.to_pandas()

    def slice(self, start: int, end: int) -> pa.Table:
        return self.block.slice(start, end - start)

    def schema(self):
        return self.block.schema

    @staticmethod
    def concat(blocks: List[pa.Table]) -> pa.Table:
        blocks = [b for b in blocks if b.num_rows > 0]
        if not blocks:
            return pa.table({})
        return pa.concat_tables(blocks, promote_options="default")
