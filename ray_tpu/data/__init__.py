"""ray_tpu.data: distributed datasets over object-store blocks.

Equivalent of Ray Data (reference: python/ray/data/ — Dataset API
dataset.py, streaming executor _internal/execution/streaming_executor.py,
blocks in plasma).  Blocks are Arrow tables in the shared-memory object
store; transforms run as tasks; iteration streams with a bounded
in-flight window (backpressure).
"""

from ray_tpu.data.dataset import (ActorPoolStrategy, Dataset, GroupedData,
                                  from_items, from_numpy, range, read_csv,
                                  read_json, read_parquet)

__all__ = ["ActorPoolStrategy", "Dataset", "GroupedData", "from_items",
           "from_numpy", "range", "read_parquet", "read_csv", "read_json"]
