"""RLModule: the framework-native policy/value network abstraction.

Equivalent of the reference's RLModule
(reference: rllib/core/rl_module/rl_module.py:867 —
forward_inference / forward_exploration / forward_train as the three
entry points), reduced to a JAX/flax actor-critic for the PPO slice.
TPU-first: pure-functional apply (params are pytrees shipped through
the object store), jit-friendly static shapes.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple


def _flax():
    import flax.linen as nn

    return nn


class ActorCriticModule:
    """Discrete-action actor-critic MLP (reference: rllib's default
    fcnet Catalog models, models/catalog.py)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64)):
        import flax.linen as nn

        self.obs_dim = obs_dim
        self.num_actions = num_actions

        class _Net(nn.Module):
            # separate actor/critic towers: a shared trunk lets the
            # high-magnitude value loss thrash the policy features
            # (reference: rllib vf_share_layers=False default for PPO)
            hidden: Tuple[int, ...]
            num_actions: int

            @nn.compact
            def __call__(self, obs):
                x = obs
                for h in self.hidden:
                    x = nn.tanh(nn.Dense(h)(x))
                logits = nn.Dense(self.num_actions,
                                  kernel_init=nn.initializers.orthogonal(0.01)
                                  )(x)
                y = obs
                for h in self.hidden:
                    y = nn.tanh(nn.Dense(h)(y))
                v = nn.Dense(1, kernel_init=nn.initializers.orthogonal(1.0))(y)
                return logits, v[..., 0]

        self.net = _Net(tuple(hidden), num_actions)

    def init(self, rng) -> Dict[str, Any]:
        import jax.numpy as jnp

        return self.net.init(rng, jnp.zeros((1, self.obs_dim)))

    def apply(self, params, obs):
        """-> (logits, value). Pure function: safe under jit/grad."""
        return self.net.apply(params, obs)

    def forward_inference(self, params, obs):
        """Greedy action (reference: forward_inference)."""
        import jax.numpy as jnp

        logits, _ = self.apply(params, obs)
        return jnp.argmax(logits, axis=-1)

    def forward_exploration(self, params, obs, rng):
        """Sampled action + logp + value (reference: forward_exploration)."""
        import jax

        logits, value = self.apply(params, obs)
        action = jax.random.categorical(rng, logits)
        logp = jax.nn.log_softmax(logits)[
            jax.numpy.arange(logits.shape[0]), action]
        return action, logp, value
