"""PPO Learner: the gradient-update half of the algorithm.

Equivalent of the reference's Learner/LearnerGroup
(reference: rllib/core/learner/learner.py, learner_group.py:64 —
update_from_batch on local or remote GPU workers wrapped in DDP;
PPO loss rllib/algorithms/ppo/torch/ppo_torch_learner.py).

TPU-first redesign: ONE jitted update step does GAE, advantage
normalization, and all SGD epochs x minibatches via lax.scan — no
Python loop per minibatch, no host round-trips mid-update; params and
optimizer state are donated so the update runs in place on device.
Data parallelism over a mesh comes from sharding the batch dimension
(parallel/mesh.py) — XLA inserts the gradient all-reduce, which is the
GSPMD equivalent of the reference's DDP wrapper.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple


class PPOLearner:
    def __init__(self, module, *, lr: float = 3e-4, gamma: float = 0.99,
                 gae_lambda: float = 0.95, clip_eps: float = 0.2,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 num_epochs: int = 4, minibatch_size: int = 256,
                 max_grad_norm: float = 0.5, seed: int = 0):
        import jax
        import optax

        self.module = module
        self.gamma = gamma
        self.gae_lambda = gae_lambda
        self.clip_eps = clip_eps
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.num_epochs = num_epochs
        self.minibatch_size = minibatch_size
        self.tx = optax.chain(optax.clip_by_global_norm(max_grad_norm),
                              optax.adam(lr))
        self.params = module.init(jax.random.PRNGKey(seed))
        self.opt_state = self.tx.init(self.params)
        self._rng = jax.random.PRNGKey(seed + 1)
        self._update = None  # jitted lazily (first batch fixes shapes)

    # ---- jitted update -----------------------------------------------------

    def _build_update(self):
        import jax
        import jax.numpy as jnp

        gamma, lam = self.gamma, self.gae_lambda
        clip_eps = self.clip_eps
        vf_c, ent_c = self.vf_coeff, self.entropy_coeff
        module, tx = self.module, self.tx
        num_epochs, mb = self.num_epochs, self.minibatch_size

        def gae(rewards, values, last_value, nonterminal, mask):
            """Reverse-scan GAE (compiler-friendly: lax.scan, no Python
            loop over time)."""
            next_values = jnp.concatenate(
                [values[1:], last_value[None]], axis=0)

            def step(carry, xs):
                r, v, nv, nt, m = xs
                delta = r + gamma * nv * nt - v
                adv = delta + gamma * lam * nt * carry
                adv = adv * m  # reset transitions carry nothing
                return adv, adv

            _, advs = jax.lax.scan(
                step, jnp.zeros_like(last_value),
                (rewards, values, next_values, nonterminal, mask),
                reverse=True)
            return advs

        def loss_fn(params, b):
            logits, values = module.apply(params, b["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, b["actions"][:, None], axis=-1)[:, 0]
            ratio = jnp.exp(logp - b["logp_old"])
            m = b["mask"]
            msum = jnp.maximum(m.sum(), 1.0)
            adv = b["adv"]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv)
            pi_loss = -(surr * m).sum() / msum
            vf_loss = (jnp.square(values - b["v_target"]) * m).sum() / msum
            entropy = (-(jnp.exp(logp_all) * logp_all).sum(-1) * m).sum() / msum
            total = pi_loss + vf_c * vf_loss - ent_c * entropy
            return total, (pi_loss, vf_loss, entropy)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def update(params, opt_state, rng, batch):
            # ---- flatten [T, E] -> [N] and compute targets once
            T, E = batch["rewards"].shape
            adv = gae(batch["rewards"], batch["values"],
                      batch["last_value"], batch["nonterminal"],
                      batch["mask"])
            v_target = adv + batch["values"]
            flat = {
                "obs": batch["obs"].reshape(T * E, -1),
                "actions": batch["actions"].reshape(T * E),
                "logp_old": batch["logp"].reshape(T * E),
                "adv": adv.reshape(T * E),
                "v_target": v_target.reshape(T * E),
                "mask": batch["mask"].reshape(T * E),
            }
            # normalize advantages over valid transitions
            m = flat["mask"]
            msum = jnp.maximum(m.sum(), 1.0)
            mean = (flat["adv"] * m).sum() / msum
            var = (jnp.square(flat["adv"] - mean) * m).sum() / msum
            flat["adv"] = (flat["adv"] - mean) / jnp.sqrt(var + 1e-8)

            N = T * E
            mb_eff = min(mb, N)  # small rollouts: one minibatch = all
            n_mb = max(1, N // mb_eff)
            usable = n_mb * mb_eff

            def epoch(carry, rng_e):
                params, opt_state = carry
                perm = jax.random.permutation(rng_e, N)[:usable]
                mbs = jax.tree_util.tree_map(
                    lambda x: x[perm].reshape(
                        (n_mb, mb_eff) + x.shape[1:]), flat)

                def mb_step(carry, mb_batch):
                    params, opt_state = carry
                    (loss, aux), grads = grad_fn(params, mb_batch)
                    updates, opt_state = tx.update(grads, opt_state, params)
                    import optax

                    params = optax.apply_updates(params, updates)
                    return (params, opt_state), (loss, *aux)

                (params, opt_state), stats = jax.lax.scan(
                    mb_step, (params, opt_state), mbs)
                return (params, opt_state), stats

            rngs = jax.random.split(rng, num_epochs)
            (params, opt_state), stats = jax.lax.scan(
                epoch, (params, opt_state), rngs)
            losses = jax.tree_util.tree_map(lambda s: s.mean(), stats)
            return params, opt_state, {
                "total_loss": losses[0], "policy_loss": losses[1],
                "vf_loss": losses[2], "entropy": losses[3]}

        return update

    def update_from_batch(self, batch: Dict[str, Any]) -> Dict[str, float]:
        """One PPO update over a [T, E] rollout batch.  Returns stats."""
        import jax

        if self._update is None:
            self._update = self._build_update()
        self._rng, rng = jax.random.split(self._rng)
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, rng, batch)
        return {k: float(v) for k, v in stats.items()}

    # ---- weight transport --------------------------------------------------

    def get_weights(self):
        """Params as a numpy pytree (plasma-friendly)."""
        import jax

        return jax.tree_util.tree_map(lambda x: __import__("numpy").asarray(x),
                                      self.params)
