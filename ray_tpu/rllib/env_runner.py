"""EnvRunner: vectorized environment sampling actor.

Equivalent of the reference's EnvRunner
(reference: rllib/env/single_agent_env_runner.py — gymnasium vector
envs stepped with the current module weights, returning episodes to
the algorithm).  Runs as a ray_tpu actor; rollout arrays ride the
object store back to the learner.

Gymnasium >= 1.0 vector autoreset is NextStep mode: the step after a
terminal one resets that sub-env (its transition is a reset, not a
real step) — those transitions are masked out of the loss instead of
being special-cased.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class EnvRunner:
    def __init__(self, env_name: str, num_envs: int, module_config: Dict[str, Any],
                 seed: int = 0):
        import gymnasium as gym
        import jax
        import numpy as np

        from ray_tpu.rllib.core.rl_module import ActorCriticModule

        self.envs = gym.make_vec(env_name, num_envs=num_envs)
        self.num_envs = num_envs
        self.module = ActorCriticModule(**module_config)
        self._rng = jax.random.PRNGKey(seed)
        self._np = np
        obs, _ = self.envs.reset(seed=seed)
        self._obs = np.asarray(obs, dtype=np.float32)
        # envs needing a reset-step next (NextStep autoreset bookkeeping)
        self._autoreset = np.zeros(num_envs, dtype=bool)
        self._episode_return = np.zeros(num_envs, dtype=np.float64)
        self._episode_len = np.zeros(num_envs, dtype=np.int64)
        self._completed: list = []  # finished episode returns
        self._policy = None

    def _policy_fn(self):
        if self._policy is None:
            import jax

            self._policy = jax.jit(self.module.forward_exploration)
        return self._policy

    def sample(self, weights, num_steps: int) -> Dict[str, Any]:
        """Collect a [T, E] rollout with the given weights.

        Returns numpy arrays: obs/actions/logp/values/rewards/
        nonterminal/mask + last_value, plus episode stats.
        """
        import jax
        import numpy as np

        policy = self._policy_fn()
        T, E = num_steps, self.num_envs
        out = {
            "obs": np.zeros((T, E) + self._obs.shape[1:], np.float32),
            "actions": np.zeros((T, E), np.int32),
            "logp": np.zeros((T, E), np.float32),
            "values": np.zeros((T, E), np.float32),
            "rewards": np.zeros((T, E), np.float32),
            "nonterminal": np.ones((T, E), np.float32),
            "mask": np.ones((T, E), np.float32),
        }
        self._completed = []
        for t in range(T):
            self._rng, step_rng = jax.random.split(self._rng)
            action, logp, value = policy(weights, self._obs, step_rng)
            action = np.asarray(action)
            out["obs"][t] = self._obs
            out["actions"][t] = action
            out["logp"][t] = np.asarray(logp)
            out["values"][t] = np.asarray(value)
            # transitions taken from an autoreset step carry no reward
            # signal for the PREVIOUS episode: mask them out
            out["mask"][t] = (~self._autoreset).astype(np.float32)
            obs, reward, terminated, truncated, _ = self.envs.step(action)
            reward = np.asarray(reward, np.float32)
            terminated = np.asarray(terminated)
            truncated = np.asarray(truncated)
            out["rewards"][t] = reward * (~self._autoreset)
            # value bootstrap stops at termination; truncation bootstraps
            out["nonterminal"][t] = (~terminated).astype(np.float32)
            live = ~self._autoreset
            self._episode_return[live] += reward[live]
            self._episode_len[live] += 1
            done = (terminated | truncated) & live
            for i in np.nonzero(done)[0]:
                self._completed.append(
                    (float(self._episode_return[i]),
                     int(self._episode_len[i])))
                self._episode_return[i] = 0.0
                self._episode_len[i] = 0
            self._autoreset = terminated | truncated
            self._obs = np.asarray(obs, np.float32)
        self._rng, v_rng = jax.random.split(self._rng)
        _, _, last_value = policy(weights, self._obs, v_rng)
        out["last_value"] = np.asarray(last_value)
        out["episode_returns"] = [r for r, _ in self._completed]
        out["episode_lens"] = [l for _, l in self._completed]
        return out

    def evaluate(self, weights, num_episodes: int = 5,
                 max_steps: int = 1000) -> float:
        """Mean greedy-policy return (reference: evaluation rollouts)."""
        import gymnasium as gym
        import jax
        import numpy as np

        env = self.envs
        infer = jax.jit(self.module.forward_inference)
        obs, _ = env.reset()
        obs = np.asarray(obs, np.float32)
        returns: list = []
        ep_ret = np.zeros(self.num_envs)
        autoreset = np.zeros(self.num_envs, dtype=bool)
        steps = 0
        while len(returns) < num_episodes and steps < max_steps:
            action = np.asarray(infer(weights, obs))
            obs, r, term, trunc, _ = env.step(action)
            obs = np.asarray(obs, np.float32)
            live = ~autoreset
            ep_ret[live] += np.asarray(r)[live]
            done = (np.asarray(term) | np.asarray(trunc)) & live
            for i in np.nonzero(done)[0]:
                returns.append(float(ep_ret[i]))
                ep_ret[i] = 0.0
            autoreset = np.asarray(term) | np.asarray(trunc)
            steps += 1
        # leftover state belongs to training sampling: reset cleanly
        obs, _ = self.envs.reset()
        self._obs = np.asarray(obs, np.float32)
        self._autoreset[:] = False
        self._episode_return[:] = 0.0
        self._episode_len[:] = 0
        return float(np.mean(returns)) if returns else 0.0
