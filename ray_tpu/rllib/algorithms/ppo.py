"""PPO: sample -> update -> weight-sync on ray_tpu actors.

Equivalent of the reference's PPO
(reference: rllib/algorithms/ppo/ppo.py:403 training_step — sample via
EnvRunners, update via the learner group, sync weights back;
algorithm_config.py for the typed builder).  The learner is JAX
(core/learner.py, one jitted update), EnvRunners are ray_tpu actors,
and weights travel through the object store — a put per iteration
fanned to every runner.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional


class PPOConfig:
    """Typed config builder (reference: AlgorithmConfig chaining)."""

    def __init__(self):
        self.env_name = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 128
        self.lr = 3e-4
        self.gamma = 0.99
        self.gae_lambda = 0.95
        self.clip_eps = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 4
        self.minibatch_size = 256
        self.hidden = (64, 64)
        self.seed = 0

    def environment(self, env: str) -> "PPOConfig":
        self.env_name = env
        return self

    def env_runners(self, num_env_runners: int = 2,
                    num_envs_per_env_runner: int = 8,
                    rollout_fragment_length: int = 128) -> "PPOConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 lambda_: Optional[float] = None,
                 clip_param: Optional[float] = None,
                 vf_loss_coeff: Optional[float] = None,
                 entropy_coeff: Optional[float] = None,
                 num_epochs: Optional[int] = None,
                 minibatch_size: Optional[int] = None,
                 model_hidden: Optional[tuple] = None) -> "PPOConfig":
        for name, val in [("lr", lr), ("gamma", gamma),
                          ("gae_lambda", lambda_), ("clip_eps", clip_param),
                          ("vf_coeff", vf_loss_coeff),
                          ("entropy_coeff", entropy_coeff),
                          ("num_epochs", num_epochs),
                          ("minibatch_size", minibatch_size),
                          ("hidden", model_hidden)]:
            if val is not None:
                setattr(self, name, val)
        return self

    def debugging(self, seed: int = 0) -> "PPOConfig":
        self.seed = seed
        return self

    def build(self) -> "PPO":
        return PPO(self)

    def to_trainable(self, stop_reward: float = float("inf"),
                     max_iterations: int = 100) -> Callable:
        """A Tune-compatible trainable running this PPO config; config
        overrides from the Tuner's param_space are applied on top
        (reference: Algorithm as a Tune Trainable)."""
        base = self

        def trainable(config: Dict[str, Any]):
            from ray_tpu import train as rt_train

            algo_cfg = PPOConfig()
            algo_cfg.__dict__.update(base.__dict__)
            algo_cfg.__dict__.update(config)
            algo = algo_cfg.build()
            try:
                for _ in range(max_iterations):
                    result = algo.train()
                    rt_train.report(result)
                    if result["episode_return_mean"] >= stop_reward:
                        break
            finally:
                algo.stop()

        return trainable


class PPO:
    """The algorithm driver (reference: Algorithm.step/training_step)."""

    def __init__(self, config: PPOConfig):
        import gymnasium as gym
        import ray_tpu
        from ray_tpu.rllib.core.learner import PPOLearner
        from ray_tpu.rllib.core.rl_module import ActorCriticModule
        from ray_tpu.rllib.env_runner import EnvRunner

        self.config = config
        probe = gym.make(config.env_name)
        obs_dim = int(probe.observation_space.shape[0])
        num_actions = int(probe.action_space.n)
        probe.close()
        module_config = {"obs_dim": obs_dim, "num_actions": num_actions,
                        "hidden": tuple(config.hidden)}
        self.module = ActorCriticModule(**module_config)
        self.learner = PPOLearner(
            self.module, lr=config.lr, gamma=config.gamma,
            gae_lambda=config.gae_lambda, clip_eps=config.clip_eps,
            vf_coeff=config.vf_coeff, entropy_coeff=config.entropy_coeff,
            num_epochs=config.num_epochs,
            minibatch_size=config.minibatch_size, seed=config.seed)
        runner_cls = ray_tpu.remote(EnvRunner)
        self.runners = [
            runner_cls.remote(config.env_name, config.num_envs_per_runner,
                              module_config, seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_env_runners)]
        self.iteration = 0
        self._recent_returns: List[float] = []

    def train(self) -> Dict[str, Any]:
        """One iteration: parallel rollouts -> jitted update -> metrics
        (reference: ppo.py:403 training_step)."""
        import numpy as np
        import ray_tpu

        t0 = time.perf_counter()
        weights_ref = ray_tpu.put(self.learner.get_weights())
        T = self.config.rollout_fragment_length
        rollouts = ray_tpu.get(
            [r.sample.remote(weights_ref, T) for r in self.runners],
            timeout=600)
        sample_s = time.perf_counter() - t0
        batch = {
            k: np.concatenate([ro[k] for ro in rollouts], axis=1)
            for k in ("obs", "actions", "logp", "values", "rewards",
                      "nonterminal", "mask")}
        batch["last_value"] = np.concatenate(
            [ro["last_value"] for ro in rollouts], axis=0)
        t1 = time.perf_counter()
        stats = self.learner.update_from_batch(batch)
        update_s = time.perf_counter() - t1
        for ro in rollouts:
            self._recent_returns.extend(ro["episode_returns"])
        self._recent_returns = self._recent_returns[-100:]
        self.iteration += 1
        env_steps = T * self.config.num_envs_per_runner * len(self.runners)
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else 0.0),
            "num_env_steps_sampled": env_steps * self.iteration,
            "env_steps_per_s": env_steps / max(sample_s + update_s, 1e-9),
            "time_sample_s": round(sample_s, 4),
            "time_update_s": round(update_s, 4),
            **stats,
        }

    def evaluate(self, num_episodes: int = 10) -> float:
        import ray_tpu

        weights_ref = ray_tpu.put(self.learner.get_weights())
        return ray_tpu.get(
            self.runners[0].evaluate.remote(weights_ref, num_episodes),
            timeout=300)

    def get_weights(self):
        return self.learner.get_weights()

    def stop(self) -> None:
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
