from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig  # noqa: F401
